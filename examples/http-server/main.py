"""Basic HTTP server (reference examples/http-server/main.go:17-33):
plain routes, path params, error mapping, health for free."""

from dataclasses import dataclass

from gofr_tpu.app import App, new_app
from gofr_tpu.http.errors import ErrorEntityNotFound

USERS = {"1": {"id": "1", "name": "ada"}, "2": {"id": "2", "name": "grace"}}


@dataclass
class NewUser:
    name: str


def build_app(config=None) -> App:
    app = new_app() if config is None else App(config=config)

    @app.get("/greet")
    def greet(ctx):
        name = ctx.param("name") or "world"
        return f"Hello {name}!"

    @app.get("/users/{id}")
    def get_user(ctx):
        user = USERS.get(ctx.path_param("id"))
        if user is None:
            raise ErrorEntityNotFound("user", ctx.path_param("id"))
        return user

    @app.post("/users")
    def create_user(ctx):
        new = ctx.bind(NewUser)
        uid = str(len(USERS) + 1)
        USERS[uid] = {"id": uid, "name": new.name}
        return USERS[uid]

    return app


if __name__ == "__main__":
    build_app().run()
