"""Versioned migrations (reference examples/using-migrations): the
ledger lives in gofr_migrations; each UP runs transactionally."""

from gofr_tpu.app import App, new_app
from gofr_tpu.migrations.runner import Migrate


def create_employee_table(ds) -> None:
    ds.sql.exec("CREATE TABLE IF NOT EXISTS employee "
                "(id INTEGER PRIMARY KEY, name TEXT NOT NULL)")


def seed_employees(ds) -> None:
    ds.sql.exec("INSERT INTO employee (id, name) VALUES (1, 'ada')")
    ds.sql.exec("INSERT INTO employee (id, name) VALUES (2, 'grace')")


ALL = {
    20240101000001: Migrate(up=create_employee_table),
    20240101000002: Migrate(up=seed_employees),
}


def build_app(config=None) -> App:
    app = new_app() if config is None else App(config=config)
    if app.container.sql is None:
        from gofr_tpu.datasource.sql import SQL
        app.container.add_sql(SQL(database=":memory:"))
    app.migrate(ALL)

    @app.get("/employees")
    def employees(ctx):
        return [dict(r) for r in
                ctx.sql.query("SELECT * FROM employee ORDER BY id")]

    return app


if __name__ == "__main__":
    build_app().run()
