"""TPU model serving — the flagship path with no reference
counterpart: a Llama-family model behind /chat with continuous
batching, TTFT metrics, and health showing engine state.

Uses the tiny config by default so it runs anywhere; set
MODEL_PRESET=llama3_1b (etc.) on real hardware.
"""

from gofr_tpu.app import App, new_app


def build_app(config=None) -> App:
    import jax
    from gofr_tpu.models.llama import LlamaConfig, llama_init
    from gofr_tpu.serving.engine import EngineConfig
    from gofr_tpu.serving.glue import llama_engine

    app = new_app() if config is None else App(config=config)
    preset = getattr(LlamaConfig,
                     app.config.get_or_default("MODEL_PRESET", "tiny"))
    model_config = preset()
    params = llama_init(jax.random.key(0), model_config)
    engine = llama_engine(params, model_config,
                          EngineConfig(max_batch=4,
                                       max_seq=model_config.max_seq))
    app.serve_model("llama", engine)  # POST /chat + health + lifecycle
    return app


if __name__ == "__main__":
    build_app().run()
