"""TPU model serving — the flagship path with no reference
counterpart: a Llama-family model behind /chat AND the
OpenAI-compatible /v1 surface, with continuous batching, TTFT
metrics, and health showing engine state.

Uses the tiny random-weight config by default so it runs anywhere.
Point MODEL_PATH at an HF-format checkpoint directory
(config.json + model.safetensors [+ tokenizer.json]) to serve real
weights; or set MODEL_PRESET=llama3_1b (etc.) for a random-weight
architecture twin. MODEL_QUANT=int8|int4 enables weight-only quantization
(half the HBM traffic of the memory-bound decode) in either mode.
"""

from gofr_tpu.app import App, new_app


def build_app(config=None) -> App:
    import jax
    from gofr_tpu.models.llama import LlamaConfig, llama_init
    from gofr_tpu.serving.engine import EngineConfig
    from gofr_tpu.serving.glue import llama_engine
    from gofr_tpu.serving.openai_compat import install_openai_routes
    from gofr_tpu.serving.tokenizer import BPETokenizer, ByteTokenizer

    app = new_app() if config is None else App(config=config)
    quant = app.config.get_or_default("MODEL_QUANT", "") or None
    model_path = app.config.get_or_default("MODEL_PATH", "")
    tokenizer = ByteTokenizer()
    hf_tokenizer = False
    if model_path:
        from pathlib import Path

        from gofr_tpu.models.hf_checkpoint import (load_llama_checkpoint,
                                                   resolve_serving_dtype)
        max_seq = int(app.config.get_or_default("MODEL_MAX_SEQ", "8192"))
        dtype_name = app.config.get_or_default("MODEL_DTYPE", "")
        params, model_config = load_llama_checkpoint(
            model_path, quantize=quant, max_seq=max_seq,
            dtype=resolve_serving_dtype(dtype_name) if dtype_name else None)
        quant = None  # already applied on load
        model_name = Path(model_path).name
        tok_json = Path(model_path) / "tokenizer.json"
        if tok_json.is_file():
            tokenizer = BPETokenizer.from_hf_json(tok_json)
            hf_tokenizer = True
    else:
        model_name = app.config.get_or_default("MODEL_PRESET", "tiny")
        model_config = getattr(LlamaConfig, model_name)()
        params = llama_init(jax.random.key(0), model_config)
    engine = llama_engine(
        params, model_config,
        EngineConfig(max_batch=4, max_seq=model_config.max_seq,
                     # stop at end-of-text only when the checkpoint's
                     # own tokenizer defined it — the byte-fallback's
                     # eos_id would alias an ordinary vocab token
                     eos_id=tokenizer.eos_id if hf_tokenizer else -1),
        quantize=quant)
    app.serve_model("llama", engine,
                    tokenizer)  # POST /chat + health + lifecycle
    install_openai_routes(app, engine, tokenizer,
                          model=model_name)  # /v1/* (OpenAI clients)
    return app


if __name__ == "__main__":
    build_app().run()
