"""TPU model serving — the flagship path with no reference
counterpart: a Llama-family model behind /chat AND the
OpenAI-compatible /v1 surface, with continuous batching, TTFT
metrics, and health showing engine state.

Uses the tiny config by default so it runs anywhere; set
MODEL_PRESET=llama3_1b (etc.) on real hardware, and MODEL_QUANT=int8
for weight-only quantization (half the HBM traffic of the
memory-bound decode).
"""

from gofr_tpu.app import App, new_app


def build_app(config=None) -> App:
    import jax
    from gofr_tpu.models.llama import LlamaConfig, llama_init
    from gofr_tpu.serving.engine import EngineConfig
    from gofr_tpu.serving.glue import llama_engine
    from gofr_tpu.serving.openai_compat import install_openai_routes
    from gofr_tpu.serving.tokenizer import ByteTokenizer

    app = new_app() if config is None else App(config=config)
    preset_name = app.config.get_or_default("MODEL_PRESET", "tiny")
    model_config = getattr(LlamaConfig, preset_name)()
    params = llama_init(jax.random.key(0), model_config)
    engine = llama_engine(
        params, model_config,
        EngineConfig(max_batch=4, max_seq=model_config.max_seq),
        quantize=app.config.get_or_default("MODEL_QUANT", "") or None)
    app.serve_model("llama", engine)  # POST /chat + health + lifecycle
    install_openai_routes(app, engine, ByteTokenizer(),
                          model=preset_name)  # /v1/* (OpenAI clients)
    return app


if __name__ == "__main__":
    build_app().run()
