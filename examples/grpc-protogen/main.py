"""protogen workflow (the gofr-cli `wrap grpc` analog): generate the
service skeleton from order.proto, implement it, serve it.

Regenerate the glue after editing the proto:

    python -m gofr_tpu.grpc.protogen examples/grpc-protogen/order.proto

The generated ``order_gofr.py`` carries the dataclasses, the
``OrderDeskBase`` skeleton this module subclasses, an ``OrderDeskClient``
for callers, and the protoc-compiled descriptors that make server
reflection schema-aware (``GRPC_ENABLE_REFLECTION=true``).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from gofr_tpu.app import App  # noqa: E402
from gofr_tpu.grpc.protogen import generate  # noqa: E402

_HERE = os.path.dirname(os.path.abspath(__file__))
_GLUE = os.path.join(_HERE, "order_gofr.py")
if not os.path.exists(_GLUE):  # first run: generate the glue in place
    with open(_GLUE, "w") as f:
        f.write(generate(os.path.join(_HERE, "order.proto")))

import order_gofr  # noqa: E402


class OrderDesk(order_gofr.OrderDeskBase):
    async def Place(self, ctx, request):
        order = order_gofr.Order.from_dict(request)
        ctx.logger.info(f"order placed: {order.item} x{order.quantity}")
        return {"id": order.id or "o-1", "status": "ACCEPTED"}

    async def Track(self, ctx, request):
        order = order_gofr.Order.from_dict(request)
        for status in ("ACCEPTED", "PACKED", "SHIPPED"):
            yield {"id": order.id, "status": status}


def build_app(config=None) -> App:
    app = App(config=config) if config is not None else App()
    app.register_grpc_service(OrderDesk())
    return app


if __name__ == "__main__":
    build_app().run()
