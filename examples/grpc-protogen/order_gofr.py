"""Generated from order.proto by gofr_tpu.grpc.protogen
— the gofr-cli `wrap grpc` analog. Fill in the *Base methods.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, AsyncIterator

from gofr_tpu.grpc.service import (GRPCService, bidi_stream_rpc,
                                   client_stream_rpc, rpc,
                                   server_stream_rpc)

@dataclass
class Order:
    id: str = ""
    item: str = ""
    quantity: int = 0

    @classmethod
    def from_dict(cls, d):
        d = d if isinstance(d, dict) else {}
        names = set(cls.__dataclass_fields__)
        return cls(**{k: v for k, v in d.items() if k in names})


@dataclass
class OrderAck:
    id: str = ""
    status: str = ""

    @classmethod
    def from_dict(cls, d):
        d = d if isinstance(d, dict) else {}
        names = set(cls.__dataclass_fields__)
        return cls(**{k: v for k, v in d.items() if k in names})


class OrderDeskBase(GRPCService):
    """Server skeleton for `examples.orders.OrderDesk` — subclass and implement each RPC."""

    name = "examples.orders.OrderDesk"

    @rpc
    async def Place(self, ctx, request) -> Any:
        """rpc Place(Order) returns (OrderAck)"""
        req = Order.from_dict(request)
        raise NotImplementedError("implement Place")

    @server_stream_rpc
    async def Track(self, ctx, request) -> AsyncIterator[dict]:
        """rpc Track(Order) returns (stream OrderAck)"""
        req = Order.from_dict(request)
        raise NotImplementedError("implement Track")
        yield {}  # pragma: no cover


class OrderDeskClient:
    """grpc.aio client for `examples.orders.OrderDesk` (JSON codec)."""

    def __init__(self, channel):
        import json as _json
        self._channel = channel
        self._dumps = lambda o: _json.dumps(
            o.__dict__ if hasattr(o, '__dataclass_fields__') else o).encode()
        self._loads = lambda b: _json.loads(b or b'{}')

    async def Place(self, request):
        call = self._channel.unary_unary(
            "/examples.orders.OrderDesk/Place",
            request_serializer=self._dumps,
            response_deserializer=self._loads)
        return await call(request)

    def Track(self, request):
        call = self._channel.unary_stream(
            "/examples.orders.OrderDesk/Track",
            request_serializer=self._dumps,
            response_deserializer=self._loads)
        return call(request)


#: protoc-compiled FileDescriptorSet — register with the server so
#: reflection answers file_containing_symbol with real descriptors
FILE_DESCRIPTOR_SET = b'\n\xab\x02\n\x0border.proto\x12\x0fexamples.orders"G\n\x05Order\x12\x0e\n\x02id\x18\x01 \x01(\tR\x02id\x12\x12\n\x04item\x18\x02 \x01(\tR\x04item\x12\x1a\n\x08quantity\x18\x03 \x01(\x05R\x08quantity"2\n\x08OrderAck\x12\x0e\n\x02id\x18\x01 \x01(\tR\x02id\x12\x16\n\x06status\x18\x02 \x01(\tR\x06status2\x85\x01\n\tOrderDesk\x12:\n\x05Place\x12\x16.examples.orders.Order\x1a\x19.examples.orders.OrderAck\x12<\n\x05Track\x12\x16.examples.orders.Order\x1a\x19.examples.orders.OrderAck0\x01b\x06proto3'
