"""Cron scheduling (reference examples/using-cron-jobs): 5-field
schedules ticking inside the app process."""

import time

from gofr_tpu.app import App, new_app

STATE = {"runs": 0, "last": None}


def build_app(config=None) -> App:
    app = new_app() if config is None else App(config=config)

    def heartbeat(ctx):
        STATE["runs"] += 1
        STATE["last"] = time.time()
        ctx.logger.info("heartbeat", runs=STATE["runs"])

    app.add_cron_job("* * * * *", "heartbeat", heartbeat)

    @app.get("/runs")
    def runs(ctx):
        return dict(STATE)

    return app


if __name__ == "__main__":
    build_app().run()
