"""ASR batch worker (baseline config 4): Whisper transcription pulled
from pub/sub in device-sized batches + an interactive /transcribe
endpoint. No reference counterpart — this is the TPU-native analog of
a GoFr subscriber app.
"""

import asyncio

from gofr_tpu.app import App, new_app


def build_app(config=None) -> App:
    import jax
    from gofr_tpu.models.whisper import WhisperConfig, whisper_init
    from gofr_tpu.serving.asr import (ASRConfig, ASRWorker, Transcriber,
                                      make_asr_handler)

    app = new_app() if config is None else App(config=config)
    if app.container.pubsub is None:
        from gofr_tpu.pubsub.inmemory import InMemoryBroker
        app.container.add_pubsub(InMemoryBroker(
            logger=app.logger, metrics=app.container.metrics))

    model_path = app.config.get_or_default("MODEL_PATH", "")
    if model_path:
        # HF-format Whisper checkpoint (config.json + model.safetensors);
        # MODEL_DTYPE overrides the serving dtype (default bfloat16 —
        # set float32 to keep a float32 checkpoint's exact numerics)
        from gofr_tpu.models.hf_checkpoint import (load_whisper_checkpoint,
                                                   resolve_serving_dtype)
        dtype_name = app.config.get_or_default("MODEL_DTYPE", "")
        params, model_config = load_whisper_checkpoint(
            model_path,
            dtype=resolve_serving_dtype(dtype_name) if dtype_name else None)
    else:
        preset = getattr(
            WhisperConfig,
            app.config.get_or_default("MODEL_PRESET", "tiny_test"))
        model_config = preset()
        params = whisper_init(jax.random.key(0), model_config)
    transcriber = Transcriber(params, model_config,
                              ASRConfig(max_batch=4, max_tokens=16,
                                        sample_buckets=(16000, 80000)))
    app.container.add_model("whisper", transcriber)
    app.post("/transcribe", make_asr_handler(transcriber))

    worker = ASRWorker(transcriber, app.container.pubsub)
    app.state_worker = worker  # exposed for tests/inspection

    @app.on_start
    def start_worker(container):
        app._tasks.append(asyncio.ensure_future(worker.run()))

    return app


if __name__ == "__main__":
    build_app().run()
