"""HTTP server over Redis (reference examples/http-server-using-redis):
the in-process redis backend by default; REDIS_HOST selects a real one."""

from gofr_tpu.app import App, new_app


def build_app(config=None) -> App:
    app = new_app() if config is None else App(config=config)
    if app.container.redis is None:
        from gofr_tpu.datasource.redis import Redis
        app.container.add_redis(Redis())

    @app.post("/visit/{page}")
    def visit(ctx):
        count = ctx.redis.incr(f"visits:{ctx.path_param('page')}")
        return {"page": ctx.path_param("page"), "visits": count}

    @app.get("/visit/{page}")
    def visits(ctx):
        value = ctx.redis.get(f"visits:{ctx.path_param('page')}")
        return {"page": ctx.path_param("page"),
                "visits": int(value) if value else 0}

    return app


if __name__ == "__main__":
    build_app().run()
