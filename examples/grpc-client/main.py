"""gRPC client app (reference examples/grpc/grpc-unary-client +
grpc-streaming-client): an HTTP service whose handlers call a
downstream gRPC server — unary, server-stream, and health — with trace
propagation through the client's metadata."""

from gofr_tpu.app import App, new_app
from gofr_tpu.grpc import GRPCClient


def build_app(config=None, grpc_target: str = "127.0.0.1:9000") -> App:
    app = new_app() if config is None else App(config=config)
    client = GRPCClient(grpc_target, tracer=app.container.tracer)

    @app.get("/hello")
    async def hello(ctx):
        reply = await client.call("examples.Greeter", "SayHello",
                                  {"name": ctx.param("name") or "world"})
        return reply

    @app.get("/countdown")
    async def countdown(ctx):
        seen = []
        async for message in client.stream(
                "examples.Greeter", "Countdown",
                {"from": int(ctx.param("from") or "3")}):
            seen.append(message)
        return {"messages": seen}

    @app.get("/downstream-health")
    async def downstream_health(ctx):
        return {"status": await client.health_check()}

    return app


if __name__ == "__main__":
    build_app().run()
