"""Inter-service HTTP client (reference examples/using-http-service):
a named downstream with circuit breaker + retry decorators."""

from gofr_tpu.app import App, new_app
from gofr_tpu.service.client import CircuitBreaker, Retry, new_http_service


def build_app(config=None, downstream_url: str = "http://127.0.0.1:9001") -> App:
    app = new_app() if config is None else App(config=config)
    svc = new_http_service(
        downstream_url,
        Retry(max_retries=2),
        CircuitBreaker(threshold=3, interval_s=5.0),
        logger=app.logger, metrics=app.container.metrics,
        tracer=app.container.tracer)
    app.container.register_service("catalog", svc)

    @app.get("/proxy/{item}")
    async def proxy(ctx):
        catalog = ctx.get_http_service("catalog")
        resp = await catalog.get(f"/items/{ctx.path_param('item')}")
        return resp.json()

    return app


if __name__ == "__main__":
    build_app().run()
