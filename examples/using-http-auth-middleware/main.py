"""Auth middleware (reference examples/using-http-auth-middleware):
basic auth guards every route; /.well-known stays open."""

from gofr_tpu.app import App, new_app


def build_app(config=None) -> App:
    app = new_app() if config is None else App(config=config)
    app.enable_basic_auth(ada="lovelace", grace="hopper")

    @app.get("/secret")
    def secret(ctx):
        return {"for": ctx.auth_info.get("username"),
                "data": "the MXU is a 128x128 systolic array"}

    return app


if __name__ == "__main__":
    build_app().run()
