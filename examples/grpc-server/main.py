"""gRPC service (reference examples/grpc/grpc-unary-server): unary +
server-stream RPCs with container injection and observability."""

from dataclasses import dataclass

from gofr_tpu.app import App, new_app
from gofr_tpu.grpc import GRPCService, rpc, server_stream_rpc


@dataclass
class HelloRequest:
    name: str = "world"


class GreeterService(GRPCService):
    name = "examples.Greeter"

    @rpc
    def SayHello(self, ctx, request):
        hello = ctx.bind(HelloRequest)
        return {"message": f"Hello {hello.name}!",
                "served_by": self.container.app_name}

    @server_stream_rpc
    async def Countdown(self, ctx, request):
        for i in range(int(request.get("from", 3)), 0, -1):
            yield {"t_minus": i}


def build_app(config=None) -> App:
    app = new_app() if config is None else App(config=config)
    app.register_grpc_service(GreeterService())
    return app


if __name__ == "__main__":
    build_app().run()
