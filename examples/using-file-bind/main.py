"""Multipart upload binding (reference examples/using-file-bind):
file parts and form fields arrive through the same ctx.bind."""

from gofr_tpu.app import App, new_app


def build_app(config=None) -> App:
    app = new_app() if config is None else App(config=config)

    @app.post("/upload")
    def upload(ctx):
        form = ctx.bind() or {}
        out = {}
        for key, value in form.items():
            if isinstance(value, dict) and "content" in value:  # file part
                out[key] = {"filename": value.get("filename", ""),
                            "bytes": len(value["content"])}
            else:
                out[key] = value
        return out

    return app


if __name__ == "__main__":
    build_app().run()
