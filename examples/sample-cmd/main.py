"""CLI app (reference examples/sample-cmd): subcommands on argv with
the same Handler signature as HTTP routes."""

from dataclasses import dataclass

from gofr_tpu.cli.cmd import CMDApp


@dataclass
class GreetArgs:
    name: str = "world"
    shout: bool = False


def build_app(config=None) -> CMDApp:
    app = CMDApp(config=config)

    @app.sub_command("greet", help="print a greeting")
    def greet(ctx):
        args = ctx.bind(GreetArgs)
        message = f"hello {args.name}"
        return message.upper() if args.shout else message

    @app.sub_command("version", help="print the framework version")
    def version(ctx):
        from gofr_tpu.version import FRAMEWORK
        return FRAMEWORK

    return app


if __name__ == "__main__":
    raise SystemExit(build_app().run())
