"""Multi-host serving control plane (SURVEY §7 stage 8, BASELINE
config 5's host-coordination half): a leader app assigning ranks to
worker hosts, gossiping health, evicting the dead, and driving elastic
relaunches.

Run the leader:   python main.py            (serves /control/*)
Run a worker:     python main.py worker h1  (joins + heartbeats)

On a real pod each worker's ``on_assignment`` callback calls
``jax.distributed.initialize(**assignment.jax_initialize_args())`` and
relaunches the mesh-sharded engine; here it prints the assignment.
"""

import sys

from gofr_tpu.app import App, new_app
from gofr_tpu.serving.control_plane import ControlPlaneLeader, WorkerAgent


def build_app(config=None, coordinator: str = "10.0.0.1:8476") -> App:
    app = new_app() if config is None else App(config=config)
    leader = ControlPlaneLeader(coordinator=coordinator,
                                heartbeat_interval_s=2.0,
                                logger=app.logger)
    leader.install(app)
    return app


def run_worker(leader_url: str, host_id: str) -> WorkerAgent:
    def on_assignment(assignment):
        print(f"[{host_id}] generation {assignment.generation}: "
              f"rank {assignment.rank}/{assignment.world_size} "
              f"-> jax.distributed.initialize("
              f"{assignment.jax_initialize_args()})")

    worker = WorkerAgent(leader_url, host_id=host_id, n_devices=4,
                         on_assignment=on_assignment)
    worker.start()
    return worker


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "worker":
        agent = run_worker("http://127.0.0.1:8000",
                           sys.argv[2] if len(sys.argv) > 2 else "host-1")
        import time
        while True:
            time.sleep(60)
    else:
        build_app().run()
