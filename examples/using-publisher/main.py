"""Publisher (reference examples/using-publisher): HTTP ingress fanned
into the broker. PUBSUB_BACKEND env picks NATS/MQTT/MEMORY."""

from gofr_tpu.app import App, new_app


def build_app(config=None) -> App:
    app = new_app() if config is None else App(config=config)
    if app.container.pubsub is None:
        from gofr_tpu.pubsub.inmemory import InMemoryBroker
        app.container.add_pubsub(InMemoryBroker(
            logger=app.logger, metrics=app.container.metrics))

    @app.post("/publish/order")
    async def publish_order(ctx):
        await ctx.publish("orders", ctx.bind() or {})
        return {"queued": True}

    return app


if __name__ == "__main__":
    build_app().run()
