"""Websocket endpoint (reference examples/using-web-socket): the
handler runs once per inbound frame — ctx.bind() is the message, the
return value is written back; ctx.write_message_to_socket streams."""

from gofr_tpu.app import App, new_app


def build_app(config=None) -> App:
    app = new_app() if config is None else App(config=config)

    @app.websocket("/ws/echo")
    def echo(ctx):
        return {"echo": ctx.bind(str)}

    @app.websocket("/ws/count")
    async def count(ctx):
        n = int(ctx.bind(str))
        for i in range(n):
            await ctx.write_message_to_socket({"tick": i})
        return {"done": n}

    return app


if __name__ == "__main__":
    build_app().run()
