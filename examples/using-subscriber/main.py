"""Subscriber (reference examples/using-subscriber/main.go:8-18): a
broker message drives the handler exactly like an HTTP request, with
commit-on-success."""

from gofr_tpu.app import App, new_app

SEEN: list[dict] = []


def build_app(config=None) -> App:
    app = new_app() if config is None else App(config=config)
    if app.container.pubsub is None:
        from gofr_tpu.pubsub.inmemory import InMemoryBroker
        app.container.add_pubsub(InMemoryBroker(
            logger=app.logger, metrics=app.container.metrics))

    @app.subscribe("orders")
    def on_order(ctx):
        order = ctx.bind() or {}
        SEEN.append(order)
        ctx.logger.info("order received", order=order)

    @app.get("/orders/seen")
    def seen(ctx):
        return SEEN

    return app


if __name__ == "__main__":
    build_app().run()
