"""Auto-CRUD (reference examples/using-add-rest-handlers): one
dataclass becomes POST/GET/GET-all/PUT/DELETE SQL handlers."""

from dataclasses import dataclass

from gofr_tpu.app import App, new_app


@dataclass
class Book:
    id: int
    title: str = ""
    author: str = ""


def build_app(config=None) -> App:
    app = new_app() if config is None else App(config=config)
    if app.container.sql is None:
        from gofr_tpu.datasource.sql import SQL
        app.container.add_sql(SQL(database=":memory:"))
    app.container.sql.exec(
        "CREATE TABLE IF NOT EXISTS book "
        "(id INTEGER PRIMARY KEY, title TEXT, author TEXT)")
    app.add_rest_handlers(Book)
    return app


if __name__ == "__main__":
    build_app().run()
