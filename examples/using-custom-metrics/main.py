"""Custom metrics (reference examples/using-custom-metrics): register
app-level series next to the framework set; scrape at :2121/metrics."""

from gofr_tpu.app import App, new_app


def build_app(config=None) -> App:
    app = new_app() if config is None else App(config=config)
    m = app.container.metrics
    m.new_counter("orders_created", "orders created by POST /order")
    m.new_histogram("order_amount", "order amount distribution",
                    buckets=(1, 5, 10, 50, 100, 500))
    m.new_gauge("inventory_level", "current stock")
    m.set_gauge("inventory_level", 100)

    @app.post("/order")
    def order(ctx):
        body = ctx.bind() or {}
        amount = float(body.get("amount", 1))
        ctx.metrics.increment_counter("orders_created")
        ctx.metrics.record_histogram("order_amount", amount)
        ctx.metrics.set_gauge("inventory_level", 100)
        return {"ok": True, "amount": amount}

    return app


if __name__ == "__main__":
    build_app().run()
