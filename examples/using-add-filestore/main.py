"""File store (reference examples/using-add-filestore): the FileSystem
abstraction over a local root; remote stores implement the same iface."""

import tempfile

from gofr_tpu.app import App, new_app
from gofr_tpu.datasource.file_store import LocalFileSystem


def build_app(config=None, root: str | None = None) -> App:
    app = new_app() if config is None else App(config=config)
    app.container.add_file_store(
        LocalFileSystem(root or tempfile.mkdtemp(prefix="gofr-files-")))

    @app.post("/notes/{name}")
    def write_note(ctx):
        body = ctx.bind() or {}
        ctx.file.create(f"{ctx.path_param('name')}.txt",
                        str(body.get("text", "")))
        return {"saved": ctx.path_param("name")}

    @app.get("/notes/{name}")
    def read_note(ctx):
        return {"text": ctx.file.read_text(f"{ctx.path_param('name')}.txt")}

    @app.get("/notes")
    def list_notes(ctx):
        return [info.name for info in ctx.file.read_dir(".")]

    return app


if __name__ == "__main__":
    build_app().run()
