"""HTML template rendering (reference examples/using-html-template):
Template responses render ./templates/<name> with $var substitution."""

import os

from gofr_tpu.app import App, new_app
from gofr_tpu.http.response import Template

_PAGE = """<!doctype html>
<html><body><h1>Hello $name</h1><p>Served by $app</p></body></html>
"""


def _ensure_templates() -> None:
    """Templates resolve relative to CWD (reference loads ./templates)."""
    os.makedirs("templates", exist_ok=True)
    path = os.path.join("templates", "hello.html")
    if not os.path.isfile(path):
        with open(path, "w") as f:
            f.write(_PAGE)


def build_app(config=None) -> App:
    _ensure_templates()
    app = new_app() if config is None else App(config=config)

    @app.get("/hello")
    def hello(ctx):
        return Template("hello.html",
                        {"name": ctx.param("name") or "world",
                         "app": ctx.container.app_name})

    return app


if __name__ == "__main__":
    build_app().run()
