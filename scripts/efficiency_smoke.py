"""CI smoke: drive traffic and assert the goodput observatory is live.

Boots a real App with a tiny serving engine, warms it (sealing the
recompile sentinel), drives chat traffic, and asserts:

- ``GET /debug/efficiency`` serves the goodput classification and the
  conservation invariant holds there: useful + sum(waste causes) ==
  busy (to float epsilon);
- ``app_engine_goodput_ratio`` is scraped off /metrics and is in
  (0, 1], and the ``app_engine_waste_seconds{cause}`` counters never
  exceed the busy total they conserve against;
- memory watermarks are present and monotone across two reads — the
  ``kv_bytes`` watermark (``app_engine_kv_bytes_watermark``) included;
- the recompile sentinel is sealed with zero recompiles (the smoke's
  traffic only uses warmed shapes);
- an int8 KV pool (``kv_dtype="int8"``) at the SAME byte budget
  admits at least 1.8x the resident sessions of the native pool.

Exits nonzero on any failure; one line per check on success.
"""

import asyncio
import http.client
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from gofr_tpu.app import App
from gofr_tpu.config import DictConfig
from gofr_tpu.serving.engine import EngineConfig
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.tokenizer import ByteTokenizer


def parse_prometheus(text: str) -> dict:
    """name{labels} value -> {(name, labels-frag): value}."""
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value = line.rpartition(" ")
        try:
            out[name_part] = float(value)
        except ValueError:
            continue
    return out


def series(parsed: dict, name: str) -> dict:
    return {k: v for k, v in parsed.items()
            if k == name or k.startswith(name + "{")}


def request(port, method, path, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    headers = dict(headers or {})
    if isinstance(body, dict):
        body = json.dumps(body)
        headers.setdefault("Content-Type", "application/json")
    try:
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def check_kv_capacity() -> None:
    """int8 KV pages at a fixed ``kv_pool_bytes`` budget must hold
    >= 1.8x the resident sessions of the native pool: per-row bytes
    drop from itemsize*head_dim to head_dim+4 (codes + f32 scale),
    and the engine sizes the pool in bytes, not rows."""
    budget = 1 << 20
    sess_len, page = 64, 16
    pages_per_sess = -(-sess_len // page)

    def sessions(kv_dtype: str) -> int:
        eng = demo_llama_engine(EngineConfig(
            max_batch=4, max_seq=128, seed=0, kv_layout="paged",
            page_size=page, kv_dtype=kv_dtype, kv_pool_bytes=budget))
        return eng._n_pages // pages_per_sess

    native, int8 = sessions("bf16"), sessions("int8")
    assert int8 >= 1.8 * native > 0, (native, int8)
    print(f"ok: int8 KV pool admits {int8} resident sessions vs "
          f"{native} native at the same {budget}-byte budget "
          f"({int8 / native:.2f}x >= 1.8x)")


def main() -> int:
    check_kv_capacity()
    engine = demo_llama_engine(EngineConfig(
        max_batch=4, max_seq=128, seed=0, kv_layout="paged",
        page_size=16, prefix_cache=True, paged_attention="view"))
    # warm + seal: post-warmup novel shapes would now count as
    # recompiles — the smoke's prompts stay inside the warmed bucket.
    # chunked=True matters: with the prefix cache on, repeat prompts
    # reattach through the chunk-with-history walk, and an unwarmed
    # chunk graph is a REAL serving-path recompile the sentinel
    # (correctly) flags
    engine.warmup(prompt_lens=(32,), chunked=True)
    app = App(config=DictConfig({
        "HTTP_PORT": "0", "METRICS_PORT": "0",
        "APP_NAME": "efficiency-smoke", "TRACE_EXPORTER": "memory",
        "GOFR_TELEMETRY": "false"}))
    app.serve_model("llm", engine, ByteTokenizer())

    loop = asyncio.new_event_loop()
    started = threading.Event()

    def runner():
        asyncio.set_event_loop(loop)

        async def main_coro():
            await app.start()
            started.set()
            await app._stop_event.wait()

        loop.run_until_complete(main_coro())

    thread = threading.Thread(target=runner, daemon=True)
    thread.start()
    if not started.wait(60):
        print("FAIL: app did not start", file=sys.stderr)
        return 1
    try:
        port = app.http_server.bound_port
        mport = app.metrics_server.bound_port
        for i in range(4):
            status, data = request(
                port, "POST", "/chat",
                {"prompt": f"efficiency smoke {i}", "max_tokens": 8,
                 "temperature": 0.0})
            assert status == 201, (status, data[:200])
        print("ok: 4x /chat 201")
        time.sleep(0.6)  # throttled gauge refresh window

        status, data = request(port, "GET", "/debug/efficiency")
        assert status == 200, (status, data[:200])
        eff = json.loads(data)["data"]["llm"]
        gp = eff["goodput"]
        busy = gp["busy_s"]
        waste_sum = sum(gp["waste_s"].values())
        assert busy > 0, gp
        # THE invariant: every busy device-second is classified (the
        # serialized fields are rounded to 6 decimals, hence the 5e-6
        # grain; the raw-float residual must be exact)
        assert abs(gp["useful_s"] + waste_sum - busy) < 5e-6, gp
        assert abs(gp["conservation_error_s"]) < 1e-9, gp
        assert 0.0 < gp["goodput_ratio"] <= 1.0, gp
        assert gp["dominant_waste"] in (None, *gp["waste_s"]), gp
        print(f"ok: /debug/efficiency conserves "
              f"(busy={busy}s, ratio={gp['goodput_ratio']})")

        marks1 = eff["watermarks"]
        assert marks1.get("kv_pages", {}).get("value", 0) > 0, marks1
        assert marks1.get("kv_bytes", {}).get("value", 0) > 0, marks1
        assert marks1.get("host_rss_bytes", {}).get("value", 0) > 0, \
            marks1
        # pool accounting rides the same payload: total HBM bytes and
        # the per-token cost the byte-budget sizing is stated in
        assert eff["kv_bytes"] > 0, eff
        assert eff["kv_bytes_per_token"] > 0, eff
        sent = eff["recompiles"]
        assert sent["sealed"], sent
        assert sent["recompiles"] == 0, \
            f"warm-shape traffic tripped the sentinel: {sent}"
        print(f"ok: watermarks present, sentinel sealed with "
              f"{sent['recompiles']} recompiles")

        status, data = request(mport, "GET", "/metrics")
        assert status == 200, status
        parsed = parse_prometheus(data.decode())
        ratio = parsed.get("app_engine_goodput_ratio")
        assert ratio is not None, "app_engine_goodput_ratio not scraped"
        assert 0.0 < ratio <= 1.0, ratio
        waste = series(parsed, "app_engine_waste_seconds")
        assert waste, "no app_engine_waste_seconds{cause} series"
        # published counters lag the meter by at most one throttle
        # window, so they can never exceed the busy total they
        # conserve against
        assert sum(waste.values()) <= busy + 1e-6, (waste, busy)
        for key in ("app_engine_kv_pages_watermark",
                    "app_engine_kv_bytes_watermark",
                    "app_engine_host_rss_bytes_watermark"):
            assert parsed.get(key, 0.0) > 0.0, key
        print(f"ok: /metrics goodput ratio {ratio} in (0,1], "
              f"{len(waste)} waste cause series conserve")

        # one more request, then watermarks must be monotone
        status, _ = request(port, "POST", "/chat",
                            {"prompt": "efficiency smoke again",
                             "max_tokens": 8, "temperature": 0.0})
        assert status == 201
        time.sleep(0.6)
        status, data = request(port, "GET", "/debug/efficiency")
        marks2 = json.loads(data)["data"]["llm"]["watermarks"]
        for name, mark in marks1.items():
            assert marks2[name]["value"] >= mark["value"], (name,
                                                            marks1,
                                                            marks2)
        print("ok: watermarks monotone non-decreasing across reads")
    except AssertionError as exc:
        print(f"FAIL: {exc}", file=sys.stderr)
        return 1
    finally:
        asyncio.run_coroutine_threadsafe(app.stop(), loop).result(30)
        thread.join(10)
    return 0


if __name__ == "__main__":
    sys.exit(main())
