"""CI smoke: the fleet front door routes by prefix cache and absorbs
a mid-traffic drain.

Boots a LEADER App with the data-plane router installed
(``serve_fleet_leader(router=RouterConfig())``) and TWO workers, each
serving a tiny paged-KV engine with the prefix cache on, joined to
the leader. Proves both halves of the router story:

1. **Prefix-aware beats round-robin.** A shared-system-prompt workload
   driven through the leader concentrates on the host whose heartbeat
   digest covers the prompt — its ``prefix_hits`` rise once per
   request, while round-robin on the same workload washes half the
   hits away across hosts.
2. **Typed-retry failover, bit-identical.** One worker drains
   mid-traffic (in-flight stream still running): new requests pinned
   to it draw typed ``draining``/``engine_down`` 503s, the router
   retries them on the survivor, every greedy output is bit-identical
   to its pre-drain reference with zero duplicated stream tokens, and
   the in-flight stream finishes with its terminal event.

Also asserts ``app_router_*`` series on the leader's ``/metrics`` and
the router block in ``/debug/fleet``. Exits nonzero on any failure;
one line per check on success.
"""

import asyncio
import http.client
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from gofr_tpu.app import App
from gofr_tpu.config import DictConfig
from gofr_tpu.serving.engine import EngineConfig
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.router import RouterConfig, prefix_hash
from gofr_tpu.serving.tokenizer import ByteTokenizer

WORKERS = ("router-w0", "router-w1")
SYSTEM = ("You are the gofr-tpu router smoke. Answer in one short "
          "line. ")  # shared system prompt: the prefix every request bears
PAGE = 8


def request(port: int, method: str, path: str, body=None, headers=None,
            timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    headers = dict(headers or {})
    if isinstance(body, dict):
        body = json.dumps(body)
        headers.setdefault("Content-Type", "application/json")
    try:
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def chat(port, prompt, *, max_tokens=4, session=None, stream=False):
    body = {"prompt": prompt, "max_tokens": max_tokens,
            "temperature": 0.0, "stream": stream}
    if session:
        body["session"] = session
    return request(port, "POST", "/chat", body)


def sse_tokens(payload: bytes):
    """-> (token ids, saw_done) out of an SSE body."""
    tokens, done = [], False
    for line in payload.decode().splitlines():
        if not line.startswith("data: "):
            continue
        data = line[len("data: "):]
        if data == "[DONE]":
            done = True
        else:
            doc = json.loads(data)
            if "token" in doc:
                tokens.append(doc["token"])
    return tokens, done


class AppThread:
    """Boot an App on its own event loop thread (ephemeral ports)."""

    def __init__(self, app: App) -> None:
        self.app = app
        self.loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)

        async def main_coro():
            await self.app.start()
            self._started.set()
            await self.app._stop_event.wait()

        self.loop.run_until_complete(main_coro())

    def start(self) -> "AppThread":
        self._thread.start()
        if not self._started.wait(60):
            raise TimeoutError("app did not start")
        return self

    def stop(self) -> None:
        asyncio.run_coroutine_threadsafe(
            self.app.stop(), self.loop).result(30)
        self._thread.join(10)

    @property
    def port(self) -> int:
        return self.app.http_server.bound_port

    @property
    def metrics_port(self) -> int:
        return self.app.metrics_server.bound_port


def make_app(name: str) -> App:
    return App(config=DictConfig({
        "HTTP_PORT": "0", "METRICS_PORT": "0", "APP_NAME": name,
        "TRACE_EXPORTER": "memory", "GOFR_TELEMETRY": "false"}))


def main() -> int:
    leader_app = make_app("router-leader")
    leader = leader_app.serve_fleet_leader(
        host_id="leader",
        router=RouterConfig(max_retries=2, affinity_size=64))
    router = leader.router
    leader_thread = AppThread(leader_app).start()
    leader_url = f"http://127.0.0.1:{leader_thread.port}"
    lport = leader_thread.port

    workers, engines = [], {}
    for host in WORKERS:
        app = make_app(host)
        engine = demo_llama_engine(EngineConfig(
            max_batch=4, max_seq=256, kv_layout="paged",
            page_size=PAGE, prefill_buckets=(8,), seed=5))
        app.serve_model("llm", engine, ByteTokenizer())
        app.join_fleet(leader_url, host_id=host,
                       heartbeat_interval_s=0.2)
        workers.append((host, AppThread(app).start()))
        engines[host] = engine

    try:
        # workers advertise their ephemeral ports via heartbeat — wait
        # until the leader's routing view can dial both
        deadline = time.time() + 30
        while time.time() < deadline:
            view = leader.routing_view()
            if len(view) == 2 and all(m["address"] for m in view):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("workers never became routable")
        print("ok: both workers advertised routable addresses")

        # ---------------------------------------- phase A: prefix routing
        # the warm prompt and the workload prompts differ only in the
        # LAST character: the divergence lands inside the final
        # (unregistered) page, so every workload request shares the
        # warm request's page-aligned cache key
        status, _, data = chat(lport, SYSTEM + "prefix w")
        assert status == 201, (status, data[:200])
        deadline = time.time() + 10
        owner = None
        while owner is None and time.time() < deadline:
            owner = next((h for h, e in engines.items()
                          if len(e._prefix_cache)), None)
            if owner is None:
                time.sleep(0.02)
        assert owner is not None, "warm request registered no prefix"
        other = next(h for h in WORKERS if h != owner)
        # wait until the owner's digest (with the aligned system-prefix
        # hash) rides a heartbeat into the leader's routing view
        tokens = ByteTokenizer().encode(SYSTEM + "prefix w")
        aligned = ((len(tokens) - 1) // PAGE) * PAGE
        expect = prefix_hash(tokens[:aligned])
        deadline = time.time() + 30
        while time.time() < deadline:
            view = {m["host_id"]: m for m in leader.routing_view()}
            digest = view.get(owner, {}).get("summary", {}) \
                .get("prefix_digest") or {}
            if expect in (digest.get("hashes") or []):
                break
            time.sleep(0.1)
        else:
            raise AssertionError(
                f"{owner}'s prefix digest never reached the leader")
        print(f"ok: {owner} published its prefix digest via heartbeat")

        hits_before = {h: engines[h].stats["prefix_hits"]
                       for h in WORKERS}
        routed_before = dict(router.debug_state()["routed"])
        for i in range(6):
            status, _, data = chat(lport, SYSTEM + f"prefix {i}")
            assert status == 201, (status, data[:200])
        routed = router.debug_state()["routed"]
        assert routed.get(owner, 0) - routed_before.get(owner, 0) == 6, \
            (routed, routed_before)
        prefix_gain = engines[owner].stats["prefix_hits"] \
            - hits_before[owner]
        assert prefix_gain == 6, prefix_gain
        assert engines[other].stats["prefix_hits"] \
            == hits_before[other], "non-owner saw prefix traffic"
        print(f"ok: prefix policy sent 6/6 to {owner} "
              f"(+{prefix_gain} prefix_hits, 0 on {other})")

        # round-robin baseline over the same workload: hits wash out
        router.config.policy = "round_robin"
        rr_before = {h: engines[h].stats["prefix_hits"]
                     for h in WORKERS}
        for i in range(6):
            status, _, data = chat(lport, SYSTEM + f"rrobin {i}")
            assert status == 201, (status, data[:200])
        rr_owner_gain = engines[owner].stats["prefix_hits"] \
            - rr_before[owner]
        assert rr_owner_gain <= 3, rr_owner_gain
        assert prefix_gain > rr_owner_gain, (prefix_gain, rr_owner_gain)
        router.config.policy = "prefix"
        print(f"ok: round-robin washed the owner down to "
              f"+{rr_owner_gain} hits — prefix routing measurably wins")

        state = router.debug_state()
        assert state["cache_hit_ratio"] > 0, state
        print(f"ok: routed cache-hit ratio "
              f"{state['cache_hit_ratio']} on /debug/fleet")

        # -------------------------------- phase B: drain-driven failover
        # greedy references while both hosts serve (the engines are
        # identical, so a reference is host-independent)
        prompts = [SYSTEM + f"failover {i}" for i in range(4)]
        stream_prompt = SYSTEM + "failover stream"
        refs = {}
        for p, n in [(p, 12) for p in prompts] + [(stream_prompt, 96)]:
            status, _, data = chat(lport, p, max_tokens=n)
            assert status == 201, (status, data[:200])
            refs[p] = json.loads(data)["data"]["tokens"]
            assert refs[p], p

        # a long stream pinned to the owner, running when drain begins
        router.affinity.put("s-stream", owner)
        stream_result = {}

        def run_stream():
            status, _, payload = chat(
                lport, stream_prompt, max_tokens=96,
                session="s-stream", stream=True)
            stream_result["status"] = status
            stream_result["payload"] = payload

        stream_thread = threading.Thread(target=run_stream)
        stream_thread.start()
        deadline = time.time() + 30
        owner_engine = engines[owner]
        while time.time() < deadline:
            if any(r is not None for r in owner_engine.active):
                break
            time.sleep(0.01)
        else:
            raise AssertionError("stream never became active on owner")

        drain_result = {}
        drain_thread = threading.Thread(
            target=lambda: drain_result.update(
                ok=owner_engine.drain(timeout_s=60)))
        drain_thread.start()

        # mid-drain traffic pinned at the draining host: typed rejects
        # fail over to the survivor, outputs stay bit-identical
        for i, p in enumerate(prompts):
            router.affinity.put(f"s-{i}", owner)
            status, _, payload = chat(lport, p, max_tokens=12,
                                      session=f"s-{i}", stream=True)
            assert status == 200, (status, payload[:200])
            got, done = sse_tokens(payload)
            assert done, f"stream truncated for {p!r}"
            assert got == refs[p][:len(got)] and len(got) == len(refs[p]), \
                (p, got, refs[p])  # bit-identical, zero duplicates

        drain_thread.join(90)
        stream_thread.join(30)
        assert not drain_thread.is_alive() and drain_result.get("ok"), \
            "drain did not complete cleanly"
        assert stream_result["status"] == 200
        got, done = sse_tokens(stream_result["payload"])
        assert done, "in-flight stream lost its terminal event"
        assert got == refs[stream_prompt][:len(got)] \
            and len(got) == len(refs[stream_prompt]), \
            "in-flight stream tokens diverged"
        state = router.debug_state()
        assert state["retries"] >= 1, state
        assert router.affinity.get("s-0") == other, \
            "failed-over session did not re-pin to the survivor"
        print(f"ok: drain absorbed — {state['retries']} typed "
              f"retries, 5/5 greedy outputs bit-identical, in-flight "
              f"stream finished")

        # ------------------------------------------ observability surface
        status, _, data = request(lport, "GET", "/debug/fleet")
        assert status == 200, status
        fleet = json.loads(data)["data"]
        assert fleet["router"]["routed_total"] >= 17, fleet["router"]
        assert fleet["router"]["policy"] == "prefix"
        print("ok: router block on /debug/fleet")

        status, _, data = request(leader_thread.metrics_port, "GET",
                                  "/metrics")
        assert status == 200, status
        text = data.decode()
        for name in ("app_router_routed", "app_router_retries",
                     "app_router_routed_share",
                     "app_router_cache_hit_ratio"):
            assert name in text, f"{name} missing from leader /metrics"
        print("ok: app_router_* series on the leader's /metrics")
    except AssertionError as exc:
        print(f"FAIL: {exc}", file=sys.stderr)
        return 1
    finally:
        for _host, thread in workers:
            thread.stop()
        leader_thread.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
