"""CI smoke: a leader plus two in-process workers federate for real.

Boots a LEADER App with the control plane installed and TWO worker
Apps, each serving a tiny engine and joining the leader
(``app.join_fleet``: health + flight summary + metrics snapshot ride
every heartbeat). Drives one chat request per worker, then scrapes the
leader's ``/control/fleet/metrics`` and asserts:

- host/rank-labeled engine series are present for both workers;
- federated counters equal the sum of the per-worker values;
- ``/debug/fleet`` shows per-host flight summaries, skew and the
  generation.

Exits nonzero on any failure; one line per check on success.
"""

import asyncio
import http.client
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from gofr_tpu.app import App
from gofr_tpu.config import DictConfig
from gofr_tpu.serving.engine import EngineConfig
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.tokenizer import ByteTokenizer

WORKERS = ("worker-0", "worker-1")


def request(port: int, method: str, path: str, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    headers = dict(headers or {})
    if isinstance(body, dict):
        body = json.dumps(body)
        headers.setdefault("Content-Type", "application/json")
    try:
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def parse_prom(text: str) -> dict[str, float]:
    """{'name{labels}': value} with labels kept verbatim."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value = line.rpartition(" ")
        try:
            out[name_part] = float(value)
        except ValueError:
            continue
    return out


class AppThread:
    """Boot an App on its own event loop thread (ephemeral ports)."""

    def __init__(self, app: App) -> None:
        self.app = app
        self.loop = asyncio.new_event_loop()
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)

        async def main_coro():
            await self.app.start()
            self._started.set()
            await self.app._stop_event.wait()

        self.loop.run_until_complete(main_coro())

    def start(self) -> "AppThread":
        self._thread.start()
        if not self._started.wait(60):
            raise TimeoutError("app did not start")
        return self

    def stop(self) -> None:
        asyncio.run_coroutine_threadsafe(
            self.app.stop(), self.loop).result(30)
        self._thread.join(10)

    @property
    def port(self) -> int:
        return self.app.http_server.bound_port


def make_app(name: str) -> App:
    return App(config=DictConfig({
        "HTTP_PORT": "0", "METRICS_PORT": "0", "APP_NAME": name,
        "TRACE_EXPORTER": "memory", "GOFR_TELEMETRY": "false"}))


def main() -> int:
    leader_app = make_app("fleet-leader")
    leader = leader_app.serve_fleet_leader(host_id="leader")
    leader_thread = AppThread(leader_app).start()
    leader_url = f"http://127.0.0.1:{leader_thread.port}"

    workers = []
    for host in WORKERS:
        app = make_app(host)
        engine = demo_llama_engine(EngineConfig(
            max_batch=4, max_seq=128, seed=0, watchdog_interval_s=1.0))
        app.serve_model("llm", engine, ByteTokenizer())
        app.join_fleet(leader_url, host_id=host,
                       heartbeat_interval_s=0.2)
        workers.append((host, AppThread(app).start()))

    try:
        # one chat request per worker so the engine surface has samples
        for host, thread in workers:
            status, data = request(
                thread.port, "POST", "/chat",
                {"prompt": f"fleet smoke {host}", "max_tokens": 8,
                 "temperature": 0.0})
            assert status == 201, (host, status, data[:200])
        print("ok: /chat 201 on both workers")

        # wait for a post-request heartbeat from every worker
        deadline = time.time() + 30
        fleet = None
        while time.time() < deadline:
            status, data = request(leader_thread.port, "GET",
                                   "/debug/fleet")
            assert status == 200, status
            fleet = json.loads(data)["data"]
            hosts = fleet.get("hosts", {})
            if all(h in hosts and hosts[h]["federated"]
                   and hosts[h]["summary"].get("passes_recorded", 0) > 0
                   for h in WORKERS):
                break
            time.sleep(0.2)
        hosts = fleet["hosts"]
        assert set(WORKERS) <= set(hosts), hosts.keys()
        assert fleet["generation"] >= 2 and fleet["world_size"] == 2
        for h in WORKERS:
            summary = hosts[h]["summary"]
            assert summary.get("passes_recorded", 0) > 0, (h, summary)
            assert "pass_p95_s" in summary or "pass_p50_s" in summary, \
                (h, summary)
        assert "pass_skew" in fleet["fleet"], fleet["fleet"]
        print(f"ok: /debug/fleet (generation={fleet['generation']}, "
              f"skew={fleet['fleet']['pass_skew']})")

        status, data = request(leader_thread.port, "GET",
                               "/control/fleet/metrics")
        assert status == 200, status
        series = parse_prom(data.decode())
        ranks = {h: hosts[h]["rank"] for h in WORKERS}
        for name in ("app_engine_active_slots",
                     "app_engine_tokens_per_second",
                     "app_chat_ttft_seconds_count"):
            for h in WORKERS:
                key = f'{name}{{host="{h}",rank="{ranks[h]}"}}'
                assert key in series, (key, sorted(
                    k for k in series if k.startswith(name))[:4])
        print("ok: host/rank-labeled engine series for both workers")

        # federated counters equal the sum of per-worker values
        per_worker = []
        for _host, thread in workers:
            manager = thread.app.container.metrics
            per_worker.append(
                manager.get("app_chat_ttft_seconds").get_count())
        fed_total = sum(v for k, v in series.items()
                        if k.startswith('app_chat_ttft_seconds_count{'))
        assert fed_total == sum(per_worker) > 0, \
            (fed_total, per_worker)
        print(f"ok: federated counter sum matches per-worker values "
              f"({fed_total})")

        assert "app_fleet_generation" in series \
            and "app_fleet_pass_skew" in series, "fleet gauges missing"
        print("ok: app_fleet_* gauges on the federated scrape")
    except AssertionError as exc:
        print(f"FAIL: {exc}", file=sys.stderr)
        return 1
    finally:
        for _host, thread in workers:
            thread.stop()
        leader_thread.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
