"""Perf-regression gate over the bench trajectory ledger.

``bench.py`` appends every run's headline numbers to
``BENCH_TRAJECTORY.jsonl`` (one JSON object per line: ts, host,
status fresh|cached|fallback|error, platform, metrics). This tool
diffs the LATEST fresh entry for a platform against the PREVIOUS one
and exits nonzero when any headline metric regressed by more than the
threshold (default 10%) — throughput metrics regress by dropping,
latency metrics (``*_ms``) by rising.

Usage:
    python scripts/bench_compare.py [--file PATH] [--platform cpu]
                                    [--threshold 0.10] [--same-host]
                                    [--self-test]

Exit codes: 0 = no regression (or fewer than two comparable entries),
1 = regression past the threshold, 2 = bad invocation/ledger.

``--same-host`` restricts the comparison to entries from the same
machine — the 10% default is meaningful within one host's series;
cross-machine diffs (e.g. a CI runner vs the dev box that committed
the previous entry) should pass a looser ``--threshold``.

``--self-test`` runs the gate against synthetic entries (a clean pair,
a 15% tokens/s drop, a 15% TTFT rise) and exits nonzero unless the
detector catches exactly the regressions — the negative test CI runs
before trusting the gate.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

DEFAULT_THRESHOLD = 0.10

#: metrics where LOWER is a regression (throughput family, plus the
#: goodput ratio: a drop means more device-seconds went to waste —
#: padding/bubbles/preemption/rejected drafts — for the same workload;
#: the per-cause waste_*_s seconds are reported but never gate, their
#: absolute values scale with wall time)
THROUGHPUT_KEYS = ("chat_req_per_s", "chat_tok_per_s",
                   "decode_tok_per_s_fused", "decode_tok_per_s_single",
                   "prefill_tok_per_s_kernel", "prefill_tok_per_s_view",
                   "prod_tok_per_s", "prod_req_per_s", "goodput_ratio")

#: goodput_ratio only gates when BOTH entries accumulated at least
#: this much busy device time — tiny CPU headline runs have ~20 ms of
#: busy time, where a single extra padded prefill swings the ratio
#: past the 10% threshold (pure noise, the flappy gate of record).
#: Entries predating the goodput_busy_s headline also skip the gate.
GOODPUT_BUSY_FLOOR_S = 1.0


def is_latency(key: str) -> bool:
    return key.endswith("_ms")


def load_entries(path: str) -> list[dict]:
    entries = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as exc:
                raise ValueError(f"{path}:{i}: not JSON: {exc}") from exc
            if isinstance(rec, dict):
                entries.append(rec)
    return entries


def comparable(entries: list[dict], platform: str,
               same_host: bool) -> list[dict]:
    """Fresh entries for the platform, oldest -> newest. Cached and
    fallback payloads are provenance-tainted (they may predate the
    code under test) and never gate; error entries carry no metrics."""
    fresh = [e for e in entries
             if e.get("status") == "fresh"
             and e.get("platform") == platform
             and e.get("metrics")]
    if same_host and fresh:
        host = fresh[-1].get("host")
        fresh = [e for e in fresh if e.get("host") == host]
    return sorted(fresh, key=lambda e: e.get("ts", 0.0))


def diff(prev: dict, cur: dict, threshold: float) -> tuple[list, list]:
    """-> (regressions, lines). A regression is a relative change past
    the threshold in the bad direction for a metric present in BOTH
    entries; metrics only one side has are reported but never gate."""
    pm, cm = prev.get("metrics", {}), cur.get("metrics", {})
    regressions, lines = [], []
    for key in sorted(set(pm) | set(cm)):
        old, new = pm.get(key), cm.get(key)
        if old is None or new is None:
            lines.append(f"  {key:28s} {old} -> {new}  (uncomparable)")
            continue
        if old <= 0:
            lines.append(f"  {key:28s} {old} -> {new}  (zero baseline)")
            continue
        change = (new - old) / old
        if key == "goodput_ratio":
            busy_prev = pm.get("goodput_busy_s")
            busy_cur = cm.get("goodput_busy_s")
            if busy_prev is None or busy_cur is None \
                    or min(busy_prev, busy_cur) < GOODPUT_BUSY_FLOOR_S:
                lines.append(f"  {key:28s} {old:>12} -> {new:>12}  "
                             f"{change:+7.1%}  (busy below "
                             f"{GOODPUT_BUSY_FLOOR_S}s floor — not gated)")
                continue
        bad = change < -threshold if key in THROUGHPUT_KEYS else \
            change > threshold if is_latency(key) else False
        marker = "  REGRESSION" if bad else ""
        lines.append(f"  {key:28s} {old:>12} -> {new:>12}  "
                     f"{change:+7.1%}{marker}")
        if bad:
            regressions.append({"metric": key, "prev": old, "cur": new,
                                "change": round(change, 4)})
    return regressions, lines


def compare(entries: list[dict], *, platform: str, threshold: float,
            same_host: bool) -> int:
    series = comparable(entries, platform, same_host)
    if len(series) < 2:
        print(f"bench_compare: {len(series)} fresh '{platform}' "
              f"entr{'y' if len(series) == 1 else 'ies'} in the ledger "
              f"— nothing to diff yet (gate passes vacuously)")
        return 0
    prev, cur = series[-2], series[-1]
    print(f"bench_compare: {platform} fresh "
          f"ts={prev.get('ts')} ({prev.get('host')}) -> "
          f"ts={cur.get('ts')} ({cur.get('host')}), "
          f"threshold {threshold:.0%}")
    regressions, lines = diff(prev, cur, threshold)
    print("\n".join(lines))
    if regressions:
        print(f"bench_compare: {len(regressions)} headline metric(s) "
              f"regressed past {threshold:.0%}: "
              + ", ".join(r["metric"] for r in regressions))
        return 1
    print("bench_compare: no regression past the threshold")
    return 0


# ------------------------------------------------------------ self-test
def self_test() -> int:
    """The gate must catch a synthetic >10% tokens/s regression and a
    latency rise, and must pass identical entries — run by CI before
    the real comparison so a broken detector cannot silently wave
    regressions through."""
    base = {"status": "fresh", "platform": "cpu", "host": "h", "ts": 1.0,
            "metrics": {"chat_tok_per_s": 1000.0, "chat_req_per_s": 50.0,
                        "p50_ttft_ms": 40.0, "goodput_ratio": 0.8,
                        "goodput_busy_s": 5.0, "waste_padding_s": 1.0}}

    def entry(ts, **overrides):
        rec = json.loads(json.dumps(base))
        rec["ts"] = ts
        rec["metrics"].update(overrides)
        return rec

    checks = [
        ("identical entries pass",
         [base, entry(2.0)], 0),
        ("5% tokens/s dip within threshold passes",
         [base, entry(2.0, chat_tok_per_s=950.0)], 0),
        ("15% tokens/s regression fails",
         [base, entry(2.0, chat_tok_per_s=850.0)], 1),
        ("15% TTFT rise fails",
         [base, entry(2.0, p50_ttft_ms=46.0)], 1),
        ("15% tokens/s IMPROVEMENT passes",
         [base, entry(2.0, chat_tok_per_s=1150.0)], 0),
        ("15% goodput-ratio drop fails",
         [base, entry(2.0, goodput_ratio=0.68)], 1),
        ("5% goodput-ratio dip within threshold passes",
         [base, entry(2.0, goodput_ratio=0.77)], 0),
        ("waste seconds double but never gate",
         [base, entry(2.0, waste_padding_s=2.0)], 0),
        ("goodput drop below the busy floor never gates",
         [dict(base, metrics=dict(base["metrics"],
                                  goodput_busy_s=0.02)),
          entry(2.0, goodput_ratio=0.5, goodput_busy_s=0.02)], 0),
        ("goodput drop without busy_s (old ledger entry) never gates",
         [dict(base, metrics={"goodput_ratio": 0.8}),
          dict(entry(2.0), metrics={"goodput_ratio": 0.5})], 0),
        ("single entry passes vacuously",
         [base], 0),
        ("cached entries never gate",
         [base, dict(entry(2.0, chat_tok_per_s=1.0),
                     status="cached")], 0),
        # kv_* capacity numbers are report-only: not in
        # THROUGHPUT_KEYS and not *_ms, so even a halved capacity
        # ratio or tok/s must never gate (the bench asserts the
        # >= 1.8x capacity floor itself)
        ("kv capacity drop reports but never gates",
         [dict(base, metrics=dict(base["metrics"],
                                  kv_capacity_ratio=3.0,
                                  kv_tok_per_s_int8=5000.0)),
          entry(2.0, kv_capacity_ratio=1.2,
                kv_tok_per_s_int8=2000.0)], 0),
        # spec_* speculation diagnostics are report-only: accept rates
        # and pass-efficiency ratios are workload properties (the
        # bench asserts its own floors in-run), so even a collapsed
        # accept rate or halved pass-efficiency must never gate
        ("spec diagnostics drop reports but never gates",
         [dict(base, metrics=dict(base["metrics"],
                                  spec_tok_per_pass_ratio=1.8,
                                  spec_accept_rate_rep=0.9,
                                  spec_accept_rate_low=0.3,
                                  spec_adaptive_regression=1.0,
                                  spec_waste_static_s=0.01,
                                  spec_waste_adaptive_s=0.001)),
          entry(2.0, spec_tok_per_pass_ratio=0.9,
                spec_accept_rate_rep=0.1,
                spec_accept_rate_low=0.05,
                spec_adaptive_regression=0.5,
                spec_waste_static_s=0.2,
                spec_waste_adaptive_s=0.1)], 0),
        # cost_* per-kind µs/token prices are report-only: pass prices
        # move with host load and shape mix, so even a doubled decode
        # price must never gate (not in THROUGHPUT_KEYS, not *_ms —
        # the _token suffix keeps them out of the latency rule)
        ("cost us-per-token doubles but never gates",
         [dict(base, metrics=dict(base["metrics"],
                                  cost_decode_us_per_token=120.0,
                                  cost_prefill_us_per_token=40.0)),
          entry(2.0, cost_decode_us_per_token=260.0,
                cost_prefill_us_per_token=95.0)], 0),
    ]
    failed = 0
    for name, entries, want in checks:
        got = compare(entries, platform="cpu",
                      threshold=DEFAULT_THRESHOLD, same_host=False)
        ok = got == want
        print(f"self-test {'ok' if ok else 'FAIL'}: {name} "
              f"(exit {got}, want {want})")
        failed += 0 if ok else 1
    return 1 if failed else 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    default_file = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_TRAJECTORY.jsonl")
    ap.add_argument("--file", default=default_file)
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD)
    ap.add_argument("--same-host", action="store_true")
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args()
    if args.self_test:
        return self_test()
    if args.threshold <= 0:
        print("bench_compare: threshold must be > 0", file=sys.stderr)
        return 2
    if not os.path.exists(args.file):
        print(f"bench_compare: no ledger at {args.file} — run bench.py "
              f"first (gate passes vacuously)")
        return 0
    try:
        entries = load_entries(args.file)
    except ValueError as exc:
        print(f"bench_compare: {exc}", file=sys.stderr)
        return 2
    return compare(entries, platform=args.platform,
                   threshold=args.threshold, same_host=args.same_host)


if __name__ == "__main__":
    sys.exit(main())
