"""Replay-driven capacity estimator: max sustainable concurrency
before the SLO burn rate trips.

Usage:
    python scripts/capacity.py WORKLOAD.jsonl
        [--levels 1,2,4,8,16] [--seed S] [--max-batch B] [--max-seq L]
        [--ttft-s 2.0] [--tpot-s 0.5] [--e2e-s 30] [--availability A]
        [--timeout T] [--report OUT.json] [--json SETPOINT.json]

Replays a captured workload (``GET /debug/workload``) through a local
engine at increasing ``--closed-loop`` concurrency. At each level the
SLO tracker and the goodput meter start clean; after the level drains,
the script records throughput (QPS, tok/s), the goodput ratio and
waste breakdown, and the fast-burn state. The sweep stops at the first
level whose fast-burn trips; the report names the last sustainable
level — the admission-control baseline a scheduler can enforce — plus
the full goodput-vs-load curve (watch padding fall and bubble/preempt
waste rise as the batch saturates).

The engine is the demo tiny-llama family (same as scripts/replay.py);
for a production model call :func:`sweep` against your own engine.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_level(engine, workload, level: int, slo_config,
              timeout_s: float = 300.0) -> dict:
    """One closed-loop replay at ``level`` in-flight requests with a
    fresh SLO tracker + goodput meter; returns the level's digest."""
    from gofr_tpu.serving.observability import SLOTracker
    from gofr_tpu.serving.replay import replay_workload

    engine.slo = SLOTracker(slo_config)
    report = replay_workload(engine, workload, closed_loop=level,
                             timeout_s=timeout_s)
    slo_state = report.get("slo") or {}
    fast = slo_state.get("fast_burn") or {}
    goodput = report.get("replayed_goodput") or {}
    ok = report["submitted"] - report.get("replay_errors", 0)
    wall = max(report.get("wall_s") or 0.0, 1e-9)
    return {
        "concurrency": level,
        "qps": round(ok / wall, 3),
        "wall_s": report.get("wall_s"),
        "requests_ok": ok,
        "replay_errors": report.get("replay_errors", 0),
        "latency": report.get("replayed_latency"),
        "goodput_ratio": goodput.get("goodput_ratio"),
        "waste_s": goodput.get("waste_s"),
        "busy_s": goodput.get("busy_s"),
        "burn_rate": fast.get("burn_rate"),
        "burn_window": fast.get("window"),
        "tripped": bool(fast.get("tripped")),
    }


def pick_max_sustainable(levels: list[dict]) -> dict | None:
    """The highest untripped level BELOW the first trip (the sweep is
    monotone in offered load, so everything past the first trip is
    over capacity even if a later level happened to squeak by)."""
    best = None
    for entry in levels:
        if entry.get("tripped"):
            break
        best = entry
    return best


def sweep(engine, workload, levels, slo_config,
          timeout_s: float = 300.0, log=print) -> dict:
    """Run the concurrency ladder; stops after the first tripped
    level (it is the capacity boundary — higher levels only burn
    time past it)."""
    curve: list[dict] = []
    for level in levels:
        entry = run_level(engine, workload, level, slo_config,
                          timeout_s=timeout_s)
        curve.append(entry)
        log(f"# closed-loop {level}: {entry['qps']} req/s, "
            f"goodput={entry['goodput_ratio']}, "
            f"burn={entry['burn_rate']} "
            f"({'TRIPPED' if entry['tripped'] else 'ok'})")
        if entry["tripped"]:
            break
    best = pick_max_sustainable(curve)
    return {
        "levels": curve,
        "max_sustainable": best,
        "max_sustainable_concurrency":
            best["concurrency"] if best else 0,
        "max_sustainable_qps": best["qps"] if best else 0.0,
        "tripped_at": next((e["concurrency"] for e in curve
                            if e["tripped"]), None),
    }


def setpoint_doc(result: dict) -> dict:
    """The ``--json`` setpoint file: the exact subset the router
    autoscaler (``RouterConfig.setpoint_file``) and CI consume —
    stable keys, no stdout scraping."""
    return {
        "max_concurrency": result.get("max_sustainable_concurrency", 0),
        "qps": result.get("max_sustainable_qps", 0.0),
        "tripped_at": result.get("tripped_at"),
        "levels": [
            {"concurrency": e.get("concurrency"),
             "qps": e.get("qps"),
             "goodput_ratio": e.get("goodput_ratio"),
             "tripped": bool(e.get("tripped"))}
            for e in result.get("levels", [])
        ],
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("workload", help="workload JSONL file "
                    "(GET /debug/workload)")
    ap.add_argument("--levels", default="1,2,4,8,16",
                    help="comma-separated closed-loop concurrency "
                    "ladder (default 1,2,4,8,16)")
    ap.add_argument("--seed", type=int, default=None,
                    help="override the header's engine_seed")
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--max-seq", type=int, default=256)
    ap.add_argument("--ttft-s", type=float, default=2.0)
    ap.add_argument("--tpot-s", type=float, default=0.5)
    ap.add_argument("--e2e-s", type=float, default=30.0)
    ap.add_argument("--availability", type=float, default=0.999)
    ap.add_argument("--timeout", type=float, default=300.0,
                    help="per-level replay timeout")
    ap.add_argument("--report", default=None,
                    help="also write the report JSON to this path")
    ap.add_argument("--json", dest="setpoint", default=None,
                    metavar="OUT",
                    help="write a machine-readable setpoint file "
                    "(max_concurrency, qps, per-level goodput) for "
                    "the router autoscaler and CI")
    args = ap.parse_args()

    try:
        levels = sorted({int(x) for x in args.levels.split(",")
                         if x.strip()})
        assert levels and all(lv > 0 for lv in levels)
    except (ValueError, AssertionError):
        print(f"capacity: bad --levels {args.levels!r}", file=sys.stderr)
        return 2

    from gofr_tpu.serving.engine import EngineConfig
    from gofr_tpu.serving.glue import demo_llama_engine
    from gofr_tpu.serving.observability import SLOConfig
    from gofr_tpu.serving.replay import load_workload

    workload = load_workload(args.workload)
    header = workload["header"]
    if header.get("redacted"):
        print("capacity: redacted workloads are not replayable",
              file=sys.stderr)
        return 2
    seed = args.seed if args.seed is not None \
        else header.get("engine_seed")
    print(f"# workload: {len(workload['records'])} records, "
          f"levels={levels}", file=sys.stderr)
    engine = demo_llama_engine(EngineConfig(
        max_batch=args.max_batch, max_seq=args.max_seq,
        seed=seed if seed is not None else 0))
    slo_config = SLOConfig(ttft_s=args.ttft_s, tpot_s=args.tpot_s,
                           e2e_s=args.e2e_s,
                           availability=args.availability)
    # warm every prompt shape first: a cold XLA compile on level 1
    # would bill seconds of TTFT to the SLO and trip the burn gate on
    # compilation, not capacity (it also seals the recompile sentinel)
    lens = sorted({len(r.get("prompt_tokens") or [])
                   for r in workload["records"]
                   if r.get("prompt_tokens")})
    if lens:
        print(f"# warmup over {len(lens)} prompt lengths",
              file=sys.stderr)
        engine.warmup(prompt_lens=tuple(lens), chunked=True)
    try:
        result = sweep(engine, workload, levels, slo_config,
                       timeout_s=args.timeout,
                       log=lambda msg: print(msg, file=sys.stderr))
    finally:
        engine.stop()
    result["workload"] = {"records": len(workload["records"]),
                          "engine_seed": header.get("engine_seed")}
    result["slo"] = {"ttft_s": args.ttft_s, "tpot_s": args.tpot_s,
                     "e2e_s": args.e2e_s,
                     "availability": args.availability}
    text = json.dumps(result, indent=2, default=str)
    print(text)
    if args.report:
        with open(args.report, "w") as f:
            f.write(text + "\n")
    if args.setpoint:
        with open(args.setpoint, "w") as f:
            json.dump(setpoint_doc(result), f, indent=2)
            f.write("\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
