"""CI smoke: workload capture -> deterministic replay against a LIVE app.

Boots a real App with API-key auth (two named tenants) and a tiny
serving engine, then proves the whole capture/replay plane end to end:

- ``POST /debug/workload/start`` arms capture; six authed /chat
  requests across two tenants run greedy; ``POST /debug/workload/stop``
  disarms; ``GET /debug/workload`` downloads the versioned JSONL file,
- the endpoints harden bad input (garbage ``?n=`` -> 400, negative/huge
  -> clamp) and respect the app's auth (bare requests -> 401),
- a FRESH engine built with the same config + the header's
  ``engine_seed`` replays the file: greedy replay must be
  **bit-identical** (zero divergence) and the report must carry both
  recorded and replayed latency,
- a deliberately tampered record must be caught and located.

Exits nonzero on any failure; one line per check on success.
"""

import asyncio
import http.client
import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from gofr_tpu.app import App
from gofr_tpu.config import DictConfig
from gofr_tpu.serving.engine import EngineConfig
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.replay import parse_workload, replay_workload
from gofr_tpu.serving.tokenizer import ByteTokenizer

KEYS = {"alpha-key": "team-alpha", "beta-key": "team-beta"}
SEED = 41
ENGINE_CFG = dict(max_batch=4, max_seq=128, seed=SEED)


def request(port: int, method: str, path: str, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    headers = dict(headers or {})
    if isinstance(body, dict):
        body = json.dumps(body)
        headers.setdefault("Content-Type", "application/json")
    try:
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def main() -> int:
    engine = demo_llama_engine(EngineConfig(**ENGINE_CFG))
    app = App(config=DictConfig({
        "HTTP_PORT": "0", "METRICS_PORT": "0",
        "APP_NAME": "replay-smoke", "GOFR_TELEMETRY": "false"}))
    app.enable_api_key_auth(key_names=KEYS)
    app.serve_model("llm", engine, ByteTokenizer())

    loop = asyncio.new_event_loop()
    started = threading.Event()

    def runner():
        asyncio.set_event_loop(loop)

        async def main_coro():
            await app.start()
            started.set()
            await app._stop_event.wait()

        loop.run_until_complete(main_coro())

    thread = threading.Thread(target=runner, daemon=True)
    thread.start()
    if not started.wait(60):
        print("FAIL: app did not start", file=sys.stderr)
        return 1
    auth = {"X-Api-Key": "alpha-key"}
    try:
        port = app.http_server.bound_port

        # -------------------------------------------------- hardening
        for path in ("/debug/workload?n=zzz", "/debug/engine?n=zzz"):
            status, _, _ = request(port, "GET", path, headers=auth)
            assert status == 400, (path, status)
        for path in ("/debug/workload?n=-3",
                     "/debug/engine?n=99999999999"):
            status, _, _ = request(port, "GET", path, headers=auth)
            assert status == 200, (path, status)
        status, _, _ = request(port, "GET", "/debug/workload")
        assert status == 401, "unauthenticated workload read must bounce"
        print("ok: /debug/workload|engine clamp bad n, 400 garbage, "
              "401 bare")

        # ---------------------------------------------------- capture
        status, _, _ = request(port, "POST", "/debug/workload/start",
                               headers=auth)
        assert status in (200, 201), status
        sent = []
        for i, (key, prompt) in enumerate((
                ("alpha-key", "replay smoke alpha one"),
                ("alpha-key", "replay smoke alpha two"),
                ("beta-key", "replay smoke beta one"),
                ("alpha-key", "replay smoke alpha three"),
                ("beta-key", "replay smoke beta two"),
                ("beta-key", "replay smoke beta three"))):
            status, _, data = request(
                port, "POST", "/chat",
                {"prompt": prompt, "max_tokens": 6, "temperature": 0.0},
                headers={"X-Api-Key": key})
            assert status == 201, (status, data[:200])
            sent.append(json.loads(data)["data"])
        status, _, data = request(port, "POST", "/debug/workload/stop",
                                  headers=auth)
        assert status in (200, 201), status
        assert json.loads(data)["data"]["workload"]["records"] == 6
        print("ok: captured 6 greedy /chat requests across 2 tenants")

        status, headers, data = request(port, "GET", "/debug/workload",
                                        headers=auth)
        assert status == 200, status
        assert "application/jsonl" in headers.get("Content-Type", "")
        workload = parse_workload(data.decode())
        assert workload["header"]["engine_seed"] == SEED
        assert len(workload["records"]) == 6
        tenants = {r["tenant"] for r in workload["records"]}
        assert tenants == {"team-alpha", "team-beta"}, tenants
        recorded_tokens = sorted(
            tuple(r["completion_tokens"]) for r in workload["records"])
        chat_tokens = sorted(tuple(u["tokens"]) for u in sent)
        assert recorded_tokens == chat_tokens, \
            "captured completions != tokens the chat responses returned"
        print("ok: /debug/workload JSONL carries the exact served "
              "completions")

        # ----------------------------------------------------- replay
        fresh = demo_llama_engine(EngineConfig(
            max_batch=ENGINE_CFG["max_batch"],
            max_seq=ENGINE_CFG["max_seq"],
            seed=workload["header"]["engine_seed"]))
        try:
            report = replay_workload(fresh, workload, speed=100.0,
                                     timeout_s=120.0)
        finally:
            fresh.stop()
        assert report["compared"] == 6, report
        assert report["divergent"] == 0, report["divergences"]
        assert report["bit_identical"] is True
        assert report["recorded_latency"]["p50_ttft_ms"] is not None
        assert report["replayed_latency"]["p50_ttft_ms"] is not None
        print("ok: greedy replay through a fresh engine is "
              "bit-identical (0/6 divergent)")

        # a tampered completion must be caught and located
        tampered = json.loads(json.dumps(workload))
        tampered["records"][2]["completion_tokens"][1] ^= 1
        fresh2 = demo_llama_engine(EngineConfig(
            max_batch=ENGINE_CFG["max_batch"],
            max_seq=ENGINE_CFG["max_seq"], seed=SEED))
        try:
            report2 = replay_workload(fresh2, tampered, speed=100.0,
                                      timeout_s=120.0)
        finally:
            fresh2.stop()
        assert report2["divergent"] == 1, report2
        assert report2["divergences"][0]["first_divergent_token"] == 1
        print("ok: tampered record detected at first divergent token")
    except AssertionError as exc:
        print(f"FAIL: {exc}", file=sys.stderr)
        return 1
    finally:
        asyncio.run_coroutine_threadsafe(app.stop(), loop).result(30)
        thread.join(10)
    return 0


if __name__ == "__main__":
    sys.exit(main())
