"""One-command incident bundle from a live gofr-tpu host.

Usage:
    python scripts/bundle.py http://host:8000 [--out BUNDLE.json]
                             [--incident ID] [--timeout S]

Fetches the flight-data-recorder surfaces — the event ledger
(``/debug/events``), spooled incident bundles (``/debug/incidents``),
flight recorder + stats (``/debug/engine``), goodput
(``/debug/efficiency``), SLO (``/debug/slo``), scheduler
(``/debug/scheduler``), the workload capture (``/debug/workload``) and,
when the host is a fleet leader, the merged fleet timeline
(``/debug/fleet/events``) + leader incidents + ``/debug/fleet`` — into
ONE JSON document you can attach to a ticket and replay later:

    python scripts/replay.py <(jq -r .workload bundle.json) \
        --events <(jq -r .events bundle.json)

``--incident ID`` additionally inlines that spooled bundle verbatim.
Surfaces a host does not serve are recorded as ``null`` with the error
string under ``errors`` — a partial bundle from a sick host is the
whole point, so nothing here is fatal except total unreachability.
"""

import argparse
import json
import sys
import urllib.error
import urllib.request

#: (bundle key, path, is_json) — text surfaces (JSONL) keep raw bytes
SURFACES = (
    ("events", "/debug/events", False),
    ("incidents", "/debug/incidents", True),
    ("engine", "/debug/engine", True),
    ("efficiency", "/debug/efficiency", True),
    ("integrity", "/debug/integrity", True),
    ("slo", "/debug/slo", True),
    ("scheduler", "/debug/scheduler", True),
    ("workload", "/debug/workload", False),
    ("fleet", "/debug/fleet", True),
    ("fleet_events", "/debug/fleet/events", False),
    ("fleet_incidents", "/debug/fleet/incidents", True),
    ("health", "/.well-known/alive", True),
)


def fetch(base: str, path: str, timeout: float) -> bytes:
    req = urllib.request.Request(base + path,
                                 headers={"Accept": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.read()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("base", help="host base URL, e.g. http://host:8000")
    ap.add_argument("--out", default="bundle.json",
                    help="output path (default bundle.json)")
    ap.add_argument("--incident", default=None, metavar="ID",
                    help="also inline this spooled incident bundle")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args()
    base = args.base.rstrip("/")
    if "://" not in base:  # bare HOST:PORT is the 3am spelling
        base = "http://" + base

    bundle: dict = {"format": "gofr-bundle", "version": 1, "base": base}
    errors: dict = {}
    reached = 0
    for key, path, is_json in SURFACES:
        try:
            raw = fetch(base, path, args.timeout)
            reached += 1
        except (urllib.error.URLError, OSError, ValueError) as exc:
            bundle[key] = None
            errors[key] = str(exc)
            continue
        if is_json:
            try:
                bundle[key] = json.loads(raw)
            except ValueError:
                bundle[key] = raw.decode(errors="replace")
        else:
            bundle[key] = raw.decode(errors="replace")
    if args.incident:
        for path in (f"/debug/incidents?id={args.incident}",
                     f"/debug/fleet/incidents?id={args.incident}"):
            try:
                bundle["incident"] = json.loads(
                    fetch(base, path, args.timeout))
                break
            except (urllib.error.URLError, OSError, ValueError) as exc:
                bundle["incident"] = None
                errors["incident"] = str(exc)
    if errors:
        bundle["errors"] = errors
    if not reached:
        print(f"# UNREACHABLE: no debug surface answered at {base}",
              file=sys.stderr)
        return 2
    with open(args.out, "w") as f:
        json.dump(bundle, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"# bundle: {args.out} ({reached}/{len(SURFACES)} surfaces"
          f"{', ' + str(len(errors)) + ' errors' if errors else ''})",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
