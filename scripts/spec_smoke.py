"""CI smoke: adaptive speculative decoding end to end.

Asserts the four claims the speculation stack makes:

- **Greedy bit-identity**: a speculative engine's greedy output is
  token-identical to plain decode — checked on the int8 paged pool
  (KV compaction moves raw codes+scales, so acceptance must be exact)
  and on the dense slot layout (the gather/scatter fallback path);
- the ``app_engine_spec_accept_rate`` gauge is scraped off /metrics
  and sits in [0, 1], and ``/debug/efficiency`` serves the
  controller's state (fitted costs, per-slot EWMAs, lifetime ledger);
- the goodput conservation invariant ``useful + sum(waste) == busy``
  holds with the speculation controller active (rejected drafts are
  billed to ``spec_rejected``, never dropped on the floor);
- the recompile sentinel stays sealed with ZERO post-warmup
  recompiles — verify widths are pow-2 bucketed and every bucket is
  compiled during warmup, so adaptive depth changes never retrace.

Exits nonzero on any failure; one line per check on success.
"""

import asyncio
import http.client
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from gofr_tpu.app import App
from gofr_tpu.config import DictConfig
from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.tokenizer import ByteTokenizer

# repetitive pattern prompt: its n-grams recur, so prompt-lookup
# drafting engages deterministically
PATTERN = [7, 11, 13, 17, 19, 23, 29, 31] * 8


def parse_prometheus(text: str) -> dict:
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value = line.rpartition(" ")
        try:
            out[name_part] = float(value)
        except ValueError:
            continue
    return out


def request(port, method, path, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    headers = dict(headers or {})
    if isinstance(body, dict):
        body = json.dumps(body)
        headers.setdefault("Content-Type", "application/json")
    try:
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def run_engine(cfg: EngineConfig, n_tokens: int = 24):
    engine = demo_llama_engine(cfg)
    engine.warmup(prompt_lens=(64,), chunked=True)
    engine.start()
    try:
        req = engine.submit_sync(PATTERN[:61], SamplingParams(
            temperature=0.0, max_new_tokens=n_tokens))
        assert req.error is None, req.error
        return list(req.generated), dict(engine.stats)
    finally:
        engine.stop()


def check_greedy_identity() -> None:
    """Spec ON == spec OFF, greedy, on both KV layouts (int8 paged
    pool exercises raw-code KV compaction; slot layout exercises the
    dense gather/scatter fallback)."""
    layouts = (
        ("int8 paged", dict(kv_layout="paged", page_size=16,
                            kv_dtype="int8")),
        ("dense slot", {}),
    )
    for name, extra in layouts:
        base = dict(max_batch=2, max_seq=128, seed=0,
                    prefill_buckets=(64,), decode_steps_per_pass=1,
                    spec_ngram=2, **extra)
        plain, _ = run_engine(EngineConfig(**base))
        spec, stats = run_engine(EngineConfig(speculative=True, **base))
        assert spec == plain, (
            f"{name}: speculative greedy output diverged from plain "
            f"decode:\n  spec : {spec}\n  plain: {plain}")
        assert stats["spec_passes"] > 0, (
            f"{name}: speculation never engaged: {stats}")
        assert stats["recompiles"] == 0, (
            f"{name}: post-warmup recompile: {stats}")
        print(f"ok: {name} greedy bit-identical over "
              f"{len(plain)} tokens ({stats['spec_passes']} verify "
              f"passes, {stats['spec_accepted']}/"
              f"{stats['spec_drafted']} drafts accepted, "
              f"0 recompiles)")


def main() -> int:
    check_greedy_identity()

    engine = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=128, seed=0, kv_layout="paged",
        page_size=16, speculative=True, spec_ngram=2,
        decode_steps_per_pass=1))
    engine.warmup(prompt_lens=(32,), chunked=True)
    app = App(config=DictConfig({
        "HTTP_PORT": "0", "METRICS_PORT": "0",
        "APP_NAME": "spec-smoke", "TRACE_EXPORTER": "memory",
        "GOFR_TELEMETRY": "false"}))
    app.serve_model("llm", engine, ByteTokenizer())

    loop = asyncio.new_event_loop()
    started = threading.Event()

    def runner():
        asyncio.set_event_loop(loop)

        async def main_coro():
            await app.start()
            started.set()
            await app._stop_event.wait()

        loop.run_until_complete(main_coro())

    thread = threading.Thread(target=runner, daemon=True)
    thread.start()
    if not started.wait(60):
        print("FAIL: app did not start", file=sys.stderr)
        return 1
    try:
        # repetitive text so byte-level n-grams recur and drafting
        # engages inside the warmed 32-byte bucket
        for i in range(4):
            status, data = request(
                port := app.http_server.bound_port, "POST", "/chat",
                {"prompt": "abcabcabcabcabcabc", "max_tokens": 16,
                 "temperature": 0.0})
            assert status == 201, (status, data[:200])
        print("ok: 4x /chat 201")
        assert engine.stats["spec_passes"] > 0, dict(engine.stats)
        time.sleep(0.6)  # throttled gauge refresh window

        status, data = request(port, "GET", "/debug/efficiency")
        assert status == 200, (status, data[:200])
        eff = json.loads(data)["data"]["llm"]
        gp = eff["goodput"]
        busy = gp["busy_s"]
        waste_sum = sum(gp["waste_s"].values())
        assert busy > 0, gp
        # conservation with the controller ACTIVE: rejected-draft
        # device time lands in waste_s.spec_rejected, and every busy
        # second stays classified
        assert abs(gp["useful_s"] + waste_sum - busy) < 5e-6, gp
        assert "spec_rejected" in gp["waste_s"], gp
        print(f"ok: goodput conserves with controller active "
              f"(busy={busy}s, spec_rejected="
              f"{gp['waste_s']['spec_rejected']}s)")

        spec = eff["spec"]
        assert spec["adaptive"] is True, spec
        assert spec["drafted"] >= spec["accepted"] >= 0, spec
        assert 0.0 <= spec["accept_rate"] <= 1.0, spec
        assert len(spec["slots"]) == engine.config.max_batch, spec
        for slot in spec["slots"]:
            assert 0.0 <= slot["accept_ewma"] <= 1.0, spec
        print(f"ok: /debug/efficiency controller state "
              f"(accept_rate={spec['accept_rate']}, "
              f"drafted={spec['drafted']}, "
              f"sec_per_token={spec['sec_per_token']})")

        sent = eff["recompiles"]
        assert sent["sealed"], sent
        assert sent["recompiles"] == 0, (
            f"adaptive speculation tripped the sentinel: {sent}")
        print("ok: sentinel sealed, 0 post-warmup recompiles")

        status, data = request(app.metrics_server.bound_port, "GET",
                               "/metrics")
        assert status == 200, status
        parsed = parse_prometheus(data.decode())
        rate = parsed.get("app_engine_spec_accept_rate")
        assert rate is not None, \
            "app_engine_spec_accept_rate not scraped"
        assert 0.0 <= rate <= 1.0, rate
        print(f"ok: /metrics accept-rate gauge {rate} in [0, 1]")
    except AssertionError as exc:
        print(f"FAIL: {exc}", file=sys.stderr)
        return 1
    finally:
        asyncio.run_coroutine_threadsafe(app.stop(), loop).result(30)
        thread.join(10)
    return 0


if __name__ == "__main__":
    sys.exit(main())
