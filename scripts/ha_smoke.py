"""CI smoke: the fleet survives losing its leader.

Boots TWO leader candidates — rank 0 active, rank 1 standby — and two
engine workers configured with the ranked candidate list, then drills
the full HA story end to end:

1. **Reference run.** 6 greedy prompts through the active leader
   record bit-exact token references.
2. **Kill the leader mid-traffic.** With a stream in flight, the
   active leader is stopped. The workers' missed-ack failover elects
   the standby deterministically (lease-with-epoch: epoch bumps to 2),
   within 2 heartbeat intervals. The in-flight stream either finishes
   or is retried typed — and the retried output carries zero
   duplicated tokens.
3. **Bit-identical service resumes.** The same 6 prompts through the
   new leader (with a Retry-After-honoring client, absorbing any
   ``leader_takeover``/``no_members`` 503s during convergence) match
   the references token for token.
4. **A revived stale leader is fenced.** A fresh rank-0 leader boots
   believing epoch 1; a control write carrying epoch 2 is refused with
   a typed 409 ``stale_leader``, the write is NOT applied, the reject
   is counted on ``app_fleet_stale_leader_rejects``, and the revived
   leader demotes (``GET /control/leader`` shows active=false).

Exits nonzero on any failure; one line per check on success.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from gofr_tpu.serving.control_plane import FleetConfig
from gofr_tpu.serving.engine import EngineConfig
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.router import RouterConfig
from gofr_tpu.serving.tokenizer import ByteTokenizer
from router_smoke import AppThread, chat, make_app, request, sse_tokens

WORKERS = ("ha-w0", "ha-w1")
SYSTEM = "You are the gofr-tpu HA smoke. Answer in one short line. "
HEARTBEAT = 0.5


def boot_leader(name, rank, candidates=()):
    app = make_app(name)
    leader = app.serve_fleet_leader(
        host_id=name, rank=rank,
        fleet=FleetConfig(leader_candidates=tuple(candidates)),
        router=RouterConfig(max_retries=2, affinity_size=64),
        heartbeat_interval_s=HEARTBEAT)
    return leader, AppThread(app).start()


def chat_retry(port, prompt, *, max_tokens=12, stream=False,
               deadline_s=30):
    """A well-behaved HA client: honor Retry-After on the typed 503s a
    takeover window serves, then retry — the contract that keeps
    greedy outputs bit-identical through a failover."""
    deadline = time.time() + deadline_s
    while True:
        status, headers, payload = chat(
            port, prompt, max_tokens=max_tokens, stream=stream)
        if status != 503:
            return status, headers, payload
        if time.time() > deadline:
            raise AssertionError(
                f"retries never converged for {prompt!r}: {payload[:200]}")
        retry_after = next((v for k, v in headers.items()
                            if k.lower() == "retry-after"), "1")
        time.sleep(min(float(retry_after), 1.0))


def main() -> int:
    leader0, thread0 = boot_leader("ha-leader0", 0)
    leader1, thread1 = boot_leader("ha-leader1", 1)
    urls = (f"http://127.0.0.1:{thread0.port}",
            f"http://127.0.0.1:{thread1.port}")
    for lead in (leader0, leader1):
        lead.fleet.leader_candidates = urls

    workers = []
    for host in WORKERS:
        app = make_app(host)
        engine = demo_llama_engine(EngineConfig(
            max_batch=4, max_seq=256, kv_layout="paged",
            page_size=8, prefill_buckets=(8,), seed=5))
        app.serve_model("llm", engine, ByteTokenizer())
        app.join_fleet(urls[0], host_id=host,
                       heartbeat_interval_s=HEARTBEAT,
                       fleet=FleetConfig(leader_candidates=urls,
                                         missed_acks_before_failover=1))
        workers.append((host, AppThread(app).start()))

    revived = None
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            view = leader0.routing_view()
            if len(view) == 2 and all(m["address"] for m in view):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("workers never became routable")
        assert leader0.epoch == 1 and not leader1.active
        print("ok: rank-0 leader active at epoch 1, standby fenced, "
              "both workers routable")

        # ------------------------------------------ phase 0: references
        prompts = [SYSTEM + f"ha {i}" for i in range(6)]
        stream_prompt = SYSTEM + "ha stream"
        refs = {}
        for p, n in [(p, 12) for p in prompts] + [(stream_prompt, 48)]:
            status, _, data = chat(thread0.port, p, max_tokens=n)
            assert status == 201, (status, data[:200])
            refs[p] = json.loads(data)["data"]["tokens"]
            assert refs[p], p
        print("ok: recorded 7 greedy references through leader0")

        # ----------------------- phase 1: kill the leader mid-traffic
        stream_result = {}

        def run_stream():
            try:
                stream_result["response"] = chat(
                    thread0.port, stream_prompt, max_tokens=48,
                    stream=True)
            except Exception as exc:  # connection died with the leader
                stream_result["error"] = exc

        stream_thread = threading.Thread(target=run_stream)
        stream_thread.start()
        time.sleep(0.05)  # let the stream reach a worker
        thread0.stop()
        t_down = time.time()
        while not leader1.leadership()["active"]:
            if time.time() - t_down > 30:
                raise AssertionError("standby never took over")
            time.sleep(0.005)
        elapsed = time.time() - t_down
        assert elapsed <= 2 * HEARTBEAT, (
            f"takeover took {elapsed:.2f}s > 2 heartbeat intervals")
        assert leader1.epoch == 2, leader1.epoch
        print(f"ok: standby took over in {elapsed:.2f}s "
              f"(< {2 * HEARTBEAT}s) at epoch 2")

        # both workers re-register with the new leader (stateless
        # rebuild off their next heartbeat round)
        deadline = time.time() + 30
        while time.time() < deadline:
            view = leader1.routing_view()
            if len(view) == 2 and all(m["address"] for m in view):
                break
            time.sleep(0.05)
        else:
            raise AssertionError("workers never reached the new leader")
        print("ok: new leader rebuilt membership + routing from "
              "heartbeats alone")

        # the in-flight stream finished, or draws a typed retry whose
        # output is bit-identical with zero duplicated tokens
        stream_thread.join(30)
        response = stream_result.get("response")
        finished = False
        if response is not None and response[0] == 200:
            got, done = sse_tokens(response[2])
            if done and got == refs[stream_prompt]:
                finished = True
        if not finished:
            status, _, payload = chat_retry(
                thread1.port, stream_prompt, max_tokens=48, stream=True)
            assert status == 200, (status, payload[:200])
            got, done = sse_tokens(payload)
            assert done, "retried stream lost its terminal event"
        assert got == refs[stream_prompt], "stream tokens diverged"
        assert len(got) == len(refs[stream_prompt]), "duplicated tokens"
        print("ok: in-flight stream "
              + ("finished" if finished else "retried typed")
              + " — bit-identical, zero duplicated tokens")

        # --------------------- phase 2: bit-identical post-takeover run
        for p in prompts:
            status, _, data = chat_retry(thread1.port, p)
            assert status == 201, (status, data[:200])
            got = json.loads(data)["data"]["tokens"]
            assert got == refs[p], (p, got, refs[p])
        print("ok: 6/6 greedy outputs via the new leader bit-identical "
              "to the undisturbed references")

        status, _, data = request(thread1.metrics_port, "GET",
                                  "/metrics")
        assert status == 200
        text = data.decode()
        assert "app_fleet_leader_epoch 2" in text, \
            "leader epoch gauge did not advance"
        print("ok: app_fleet_leader_epoch=2 on the new leader's "
              "/metrics")

        # ---------------------- phase 3: revived stale leader is fenced
        stale, revived = boot_leader("ha-leader0-revived", 0, urls)
        assert stale.epoch == 1  # believes its old lease
        status, _, data = request(
            revived.port, "POST", "/control/heartbeat",
            body={"host_id": WORKERS[0], "generation": 1, "epoch": 2})
        assert status == 409, (status, data[:200])
        doc = json.loads(data)
        assert doc["error"]["details"]["code"] == "stale_leader", doc
        assert stale.topology()["world_size"] == 0, \
            "stale-epoch write was accepted"
        status, _, data = request(revived.port, "GET", "/control/leader")
        assert status == 200
        assert json.loads(data)["data"]["active"] is False, \
            "revived stale leader did not demote"
        status, _, data = request(revived.metrics_port, "GET",
                                  "/metrics")
        assert "app_fleet_stale_leader_rejects 1" in data.decode(), \
            "stale reject was not counted"
        print("ok: revived stale leader fenced — 409 stale_leader, "
              "zero accepted writes, demoted, reject counted")
    except AssertionError as exc:
        print(f"FAIL: {exc}", file=sys.stderr)
        return 1
    finally:
        for _host, thread in workers:
            thread.stop()
        if revived is not None:
            revived.stop()
        thread1.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
