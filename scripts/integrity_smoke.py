"""CI smoke: the output-integrity observatory catches a silently
corrupting host and the fleet routes around it.

Seals a golden canary set from a captured greedy workload, then boots
a LEADER App with the data-plane router and THREE workers serving
identical tiny engines with golden probes armed. One worker carries a
``logit_corrupt`` fault plan scoped to the probe tenant — the
deterministic stand-in for bad HBM / a miscompiled kernel: client
traffic stays clean, but every canary it serves emits a perturbed
token, so its probe digests diverge while its SLO stays green. Proves
the full detection -> vote -> quarantine story:

1. the corrupt host's golden-probe digests depart the sealed
   expectations (a local mismatch episode opens ONCE);
2. the leader's majority vote names exactly that host as the outlier —
   one ``fleet.integrity_divergence`` event, one incident bundle — and
   quarantines it out of the routing view;
3. post-quarantine traffic routes only to the healthy pair
   (routed share -> 0 for the outlier) and greedy outputs stay
   bit-identical to their pre-fault references;
4. probe device time is priced as ``integrity_probe`` waste and the
   goodput conservation identity stays exact on every host.

Exits nonzero on any failure; one line per check on success.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.integrity import GoldenSet
from gofr_tpu.serving.router import RouterConfig
from gofr_tpu.serving.tokenizer import ByteTokenizer

from router_smoke import AppThread, chat, make_app, request

WORKERS = ("integrity-w0", "integrity-w1", "integrity-bad")
BAD = "integrity-bad"
ENGINE_CFG = dict(max_batch=2, max_seq=128, seed=17,
                  prefill_buckets=(8,))
PROBE_PASSES = 6


def drain(reqs, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline and any(
            r.finished_at is None and r.error is None for r in reqs):
        time.sleep(0.01)
    return reqs


def seal_golden(path: str) -> None:
    """The operator flow: capture a greedy workload, seal canaries."""
    engine = demo_llama_engine(EngineConfig(
        workload_capture=True, **ENGINE_CFG))
    engine.start()
    reqs = [engine.submit([5 + i, 2, 9], SamplingParams(
        temperature=0.0, max_new_tokens=6)) for i in range(3)]
    drain(reqs)
    records = engine.workload.snapshot()["records"]
    engine.stop()
    assert all(r.error is None for r in reqs), \
        [r.error for r in reqs]
    golden = GoldenSet.seal(records)
    assert len(golden) == 3, len(golden)
    golden.save(path)


def main() -> int:
    golden_path = os.path.join(tempfile.mkdtemp(prefix="gofr-golden-"),
                               "golden.jsonl")
    seal_golden(golden_path)
    print(f"ok: sealed 3 golden canaries from a captured workload")

    leader_app = make_app("integrity-leader")
    leader = leader_app.serve_fleet_leader(
        host_id="leader", router=RouterConfig(max_retries=2,
                                              policy="round_robin"))
    router = leader.router
    leader_thread = AppThread(leader_app).start()
    leader_url = f"http://127.0.0.1:{leader_thread.port}"
    lport = leader_thread.port

    workers, engines = [], {}
    for host in WORKERS:
        cfg = dict(ENGINE_CFG, integrity_golden_path=golden_path,
                   integrity_probe_passes=PROBE_PASSES)
        if host == BAD:
            # scoped to the probe tenant: client bytes stay clean, the
            # canaries corrupt — silent corruption the SLO cannot see
            cfg["faults"] = "logit_corrupt:times=0,request=_integrity"
        app = make_app(host)
        engine = demo_llama_engine(EngineConfig(**cfg))
        app.serve_model("llm", engine, ByteTokenizer())
        app.join_fleet(leader_url, host_id=host,
                       heartbeat_interval_s=0.2)
        workers.append((host, AppThread(app).start()))
        engines[host] = engine

    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            view = leader.routing_view()
            if len(view) == 3 and all(m["address"] for m in view):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("workers never became routable")
        print("ok: three workers advertised routable addresses")

        # greedy references before any probe has a chance to mismatch
        prompts = [f"integrity check {i}" for i in range(4)]
        refs = {}
        for p in prompts:
            status, _, data = chat(lport, p, max_tokens=8)
            assert status == 201, (status, data[:200])
            refs[p] = json.loads(data)["data"]["tokens"]
            assert refs[p], p

        # keep passes flowing until every host has served probes and
        # the leader's vote quarantines the corrupt one
        deadline = time.time() + 120
        quarantined = None
        i = 0
        while time.time() < deadline and quarantined is None:
            status, _, data = chat(lport, f"tick {i}", max_tokens=4)
            assert status == 201, (status, data[:200])
            i += 1
            q = leader.fleet_status()["integrity"]["quarantined"]
            if q:
                quarantined = dict(q)
            time.sleep(0.05)
        assert quarantined is not None, "no host was ever quarantined"
        assert sorted(quarantined) == [BAD], quarantined
        assert quarantined[BAD]["majority"] is not None
        print(f"ok: the vote quarantined {BAD} on golden probe "
              f"{quarantined[BAD]['golden_id']}")

        # exactly one divergence event naming the outlier, exactly one
        # incident bundle — however many heartbeats repeated the bad
        # digest before the vote landed
        divergences = leader.events.snapshot(
            kind="fleet.integrity_divergence")
        assert len(divergences) == 1, divergences
        assert divergences[0]["attrs"]["outlier"] == BAD
        bundles = [b for b in leader.incidents.list()
                   if b["reason"] == "integrity_divergence"]
        assert len(bundles) == 1, bundles
        print("ok: exactly one fleet.integrity_divergence event and "
              "one incident bundle")

        # the corrupt host saw its own local mismatch episode too —
        # opened ONCE despite every probe mismatching since
        bad_state = engines[BAD].integrity_state()
        assert bad_state["probes"]["mismatch"] >= 1, bad_state
        assert bad_state["episodes"] == 1, bad_state
        assert engines[BAD].stats["integrity_failures"] == 1
        healthy = [h for h in WORKERS if h != BAD]
        for h in healthy:
            state = engines[h].integrity_state()
            assert state["probes"]["mismatch"] == 0, (h, state)
        print("ok: local mismatch episode opened once on the corrupt "
              "host, zero on the healthy pair")

        # routed share -> 0: post-quarantine traffic lands only on the
        # healthy pair, bit-identical to the pre-fault references
        statuses = {m["host_id"]: m["status"]
                    for m in leader.routing_view()}
        assert statuses[BAD] == "QUARANTINED", statuses
        before = dict(router.debug_state()["routed"])
        for p in prompts:
            status, _, data = chat(lport, p, max_tokens=8)
            assert status == 201, (status, data[:200])
            got = json.loads(data)["data"]["tokens"]
            assert got == refs[p], (p, got, refs[p])
        routed = router.debug_state()["routed"]
        assert routed.get(BAD, 0) == before.get(BAD, 0), \
            (before, routed)
        assert sum(routed.get(h, 0) - before.get(h, 0)
                   for h in healthy) == len(prompts)
        print("ok: 4/4 post-quarantine outputs bit-identical, routed "
              f"share of {BAD} pinned at zero")

        # canary pricing: probe device time is integrity_probe waste
        # and the conservation identity stays exact on every host
        for h in WORKERS:
            goodput = engines[h].goodput.state()
            assert goodput["conservation_error_s"] == 0.0, (h, goodput)
            assert goodput["waste_s"].get("integrity_probe", 0) > 0, \
                (h, goodput["waste_s"])
        print("ok: integrity_probe waste priced on all hosts, "
              "conservation_error_s == 0.0")

        # the debug + metrics surfaces ship the story
        wport = dict(workers)[BAD].port
        status, _, data = request(wport, "GET", "/debug/integrity")
        assert status == 200, status
        integ = json.loads(data)["data"]["llm"]
        assert integ["episode"] and integ["golden"]["count"] == 3, integ
        status, _, data = request(dict(workers)[BAD].metrics_port,
                                  "GET", "/metrics")
        assert status == 200 and \
            b"app_engine_integrity_failures" in data
        status, _, data = request(leader_thread.metrics_port, "GET",
                                  "/metrics")
        assert status == 200, status
        text = data.decode()
        assert "app_fleet_quarantined_hosts" in text
        assert "app_fleet_quarantines" in text
        print("ok: /debug/integrity + quarantine metrics surfaces")
    except AssertionError as exc:
        print(f"FAIL: {exc}", file=sys.stderr)
        return 1
    finally:
        for _host, thread in workers:
            thread.stop()
        leader_thread.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
