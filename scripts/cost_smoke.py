"""CI smoke: the pass-cost observatory end to end on a live app.

Boots one served model with the whole cost plane ON (cost model,
drift sentinel, auto-profiler, events, incidents) and drills the
tentpole story — "p95 regressed, which kernel?" answered from one
endpoint with the trace already captured:

1. **Baselines seal from serving traffic.** Greedy requests run until
   ``GET /debug/costs`` shows a sealed baseline for the decode
   signature; conservation holds: the cost table's ``total_s`` equals
   the goodput meter's busy seconds net of bubble waste.
2. **Induced drift is deterministic and bit-identical.** A
   ``cost_skew`` fault scoped to the decode signature inflates the
   OBSERVED duration only (no sleep, no token change): the re-run of
   the same greedy prompt produces byte-identical text while the
   sentinel opens EXACTLY ONE drift episode — one ``obs.cost_drift``
   event, one ``cost_drift`` incident bundle.
3. **The anomaly arms the profiler once.** The drift arms a bounded
   auto-capture whose artifact directory exists on disk, is referenced
   from exactly one incident bundle (``attrs.autoprof_dir``), and
   matches ``/debug/costs``' ``last_artifact``; the bundle's state
   snapshots carry the cost table that named the kernel.

Exits nonzero on any failure; one line per check on success.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.events import EventLedgerConfig, parse_events
from gofr_tpu.serving.faults import FaultPlan
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.tokenizer import ByteTokenizer
from router_smoke import AppThread, make_app, request

PROMPT = list(b"observe!")  # 8 tokens == the compiled prefill bucket
BASELINE_PASSES = 6
SKEW_S = 0.5  # >> any CPU pass; one skewed pass trips a 2.0x ratio


def get_json(port, path):
    status, _, data = request(port, "GET", path)
    assert status == 200, (path, status, data[:200])
    return json.loads(data)["data"]


def run_greedy(engine, max_new_tokens=24):
    req = engine.submit(PROMPT, SamplingParams(
        temperature=0.0, max_new_tokens=max_new_tokens))
    deadline = time.time() + 60
    while req.finished_at is None and req.error is None:
        assert time.time() < deadline, "greedy request stalled"
        time.sleep(0.002)
    assert req.error is None, req.error
    return list(req.generated)


def main() -> int:
    autoprof_dir = f"/tmp/gofr_cost_smoke_{os.getpid()}"
    app = make_app("cost-smoke")
    engine = demo_llama_engine(EngineConfig(
        max_batch=4, max_seq=256, kv_layout="paged", page_size=8,
        prefill_buckets=(8,), seed=5,
        cost_baseline_passes=BASELINE_PASSES,
        cost_drift_ratio=2.0, cost_drift_sigma=6.0,
        autoprof_passes=4, autoprof_debounce_s=0.0,
        autoprof_dir=autoprof_dir,
        events=EventLedgerConfig(incident_window_s=0.0,
                                 incident_debounce_s=0.0)))
    # compile ahead of traffic so serving-path baselines measure warm
    # passes (the model never folds warmup timings — they'd be
    # compile-laden — so an unwarmed engine's first collects would
    # inflate the baseline std instead)
    engine.warmup(prompt_lens=(8,))
    app.serve_model("llm", engine, ByteTokenizer())
    thread = AppThread(app).start()
    port = thread.port
    try:
        # ----------------- phase 1: baselines seal, busy_s conserves
        baseline = run_greedy(engine)
        # fused decode emits several tokens per pass, so one request
        # is a few passes — keep serving until the baseline seals
        for _ in range(12):
            costs = get_json(port, "/debug/costs")["llm"]["costs"]
            sigs = costs["signatures"]
            decode_sig = next(s for s, rec in sigs.items()
                              if rec["kind"] == "decode")
            if "baseline_s" in sigs[decode_sig]:
                break
            assert run_greedy(engine) == baseline, "greedy diverged"
        assert "baseline_s" in sigs[decode_sig], \
            f"decode baseline did not seal after " \
            f"{sigs[decode_sig]['n']} passes: {sigs[decode_sig]}"
        assert any(rec["kind"] == "prefill" for rec in sigs.values()), \
            f"no prefill signature observed: {sorted(sigs)}"
        gp = engine.goodput
        accounted = gp.busy_s - gp.waste_s.get("bubble", 0.0)
        drift_off = costs["total_s"] - costs["synthetic_s"]
        assert abs(drift_off - accounted) < 1e-6, \
            (costs["total_s"], costs["synthetic_s"], gp.busy_s)
        assert costs["synthetic_s"] == 0.0
        print(f"ok: baseline sealed for {decode_sig} after "
              f"{sigs[decode_sig]['n']} passes; cost total "
              f"{costs['total_s']:.4f}s conserves against busy "
              f"seconds net of bubbles")

        # ------------- phase 2: induced drift, bit-identical outputs
        engine.faults = FaultPlan.parse(
            f"cost_skew:at=1,times=0,seconds={SKEW_S},"
            f"request={decode_sig}")
        rerun = run_greedy(engine)
        assert rerun == baseline, \
            "cost_skew perturbed greedy tokens: " \
            f"{baseline[:8]} vs {rerun[:8]}"
        print("ok: greedy rerun is bit-identical with the whole cost "
              "plane ON and the cost_skew fault firing")

        state = get_json(port, "/debug/costs")["llm"]
        costs, autoprof = state["costs"], state["autoprof"]
        assert costs["drift_episodes"] == 1, costs["drift_episodes"]
        assert costs["signatures"][decode_sig]["drifting"]
        assert costs["synthetic_s"] > 0
        gp = engine.goodput
        accounted = gp.busy_s - gp.waste_s.get("bubble", 0.0)
        assert abs(costs["total_s"] - costs["synthetic_s"]
                   - accounted) < 1e-6, \
            (costs["total_s"], costs["synthetic_s"], gp.busy_s)
        status, _, data = request(
            port, "GET", "/debug/events?kind=obs.cost_drift")
        assert status == 200, (status, data[:200])
        _, drift_events = parse_events(data.decode())
        assert len(drift_events) == 1, drift_events
        ev_attrs = drift_events[0].get("attrs") or {}
        assert ev_attrs["signature"] == decode_sig, drift_events[0]
        assert ev_attrs["ratio"] > 2.0, drift_events[0]
        print(f"ok: exactly one drift episode and one obs.cost_drift "
              f"event naming {decode_sig} (ratio {ev_attrs['ratio']})")

        # --------------- phase 3: one capture, one bundle, on disk
        deadline = time.time() + 30
        while autoprof.get("last_artifact") is None \
                and time.time() < deadline:
            run_greedy(engine, max_new_tokens=8)  # drain pass budget
            autoprof = get_json(port, "/debug/costs")["llm"]["autoprof"]
        artifact = autoprof["last_artifact"]
        assert artifact and artifact["ok"], autoprof
        assert artifact["reason"] == "cost_drift", artifact
        assert autoprof["captures"] == 1, autoprof
        files = [os.path.join(root, f)
                 for root, _, names in os.walk(artifact["dir"])
                 for f in names]
        assert files, f"capture dir {artifact['dir']} is empty"

        incidents = get_json(port, "/debug/incidents")["llm"]["incidents"]
        drifts = [m for m in incidents if m["reason"] == "cost_drift"]
        assert len(drifts) == 1, incidents
        bundle = get_json(port,
                          f"/debug/incidents?id={drifts[0]['id']}")
        assert bundle["attrs"]["autoprof_dir"] == artifact["dir"], \
            (bundle["attrs"], artifact)
        assert bundle["attrs"]["signature"] == decode_sig
        bundle_sigs = bundle["state"]["costs"]["costs"]["signatures"]
        assert decode_sig in bundle_sigs, sorted(bundle_sigs)
        print(f"ok: one auto-capture ({len(files)} artifact files) "
              f"referenced from exactly one cost_drift bundle "
              f"{bundle['id']}, which carries the cost table")
    except AssertionError as exc:
        print(f"FAIL: {exc}", file=sys.stderr)
        return 1
    finally:
        thread.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
