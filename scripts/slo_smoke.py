"""CI smoke: tenant metering + SLO + exemplars against a LIVE app.

Boots a real App with API-key auth (two named tenants) and a tiny
serving engine, drives authed chat requests from both tenants, then
asserts the whole accounting plane end to end:

- tenant-labeled ``app_tenant_*`` series on /metrics, with no raw key
  anywhere in the exposition,
- ``GET /debug/usage`` per-tenant token totals equal to the sum of the
  chat responses' ``usage`` fields,
- ``GET /debug/slo`` burn-rate state with a full error budget,
- an OpenMetrics scrape (content-negotiated) carrying an exemplar that
  resolves to a real ``engine.request`` trace id.

Exits nonzero on any failure; one line per check on success.
"""

import asyncio
import http.client
import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from gofr_tpu.app import App
from gofr_tpu.config import DictConfig
from gofr_tpu.serving.engine import EngineConfig
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.tokenizer import ByteTokenizer

KEYS = {"alpha-key": "team-alpha", "beta-key": "team-beta"}


def request(port: int, method: str, path: str, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    headers = dict(headers or {})
    if isinstance(body, dict):
        body = json.dumps(body)
        headers.setdefault("Content-Type", "application/json")
    try:
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def main() -> int:
    engine = demo_llama_engine(EngineConfig(max_batch=4, max_seq=128,
                                            seed=0))
    app = App(config=DictConfig({
        "HTTP_PORT": "0", "METRICS_PORT": "0",
        "APP_NAME": "slo-smoke", "TRACE_EXPORTER": "memory",
        "GOFR_TELEMETRY": "false"}))
    app.enable_api_key_auth(key_names=KEYS)
    app.serve_model("llm", engine, ByteTokenizer())

    loop = asyncio.new_event_loop()
    started = threading.Event()

    def runner():
        asyncio.set_event_loop(loop)

        async def main_coro():
            await app.start()
            started.set()
            await app._stop_event.wait()

        loop.run_until_complete(main_coro())

    thread = threading.Thread(target=runner, daemon=True)
    thread.start()
    if not started.wait(60):
        print("FAIL: app did not start", file=sys.stderr)
        return 1
    try:
        port = app.http_server.bound_port
        mport = app.metrics_server.bound_port
        trace_id = "fe" * 16
        usages = []
        for i, (key, prompt) in enumerate((
                ("alpha-key", "tenant smoke alpha one"),
                ("alpha-key", "tenant smoke alpha two"),
                ("beta-key", "tenant smoke beta"))):
            headers = {"X-Api-Key": key}
            if i == 0:
                headers["traceparent"] = f"00-{trace_id}-{'cd' * 8}-01"
            status, _, data = request(
                port, "POST", "/chat",
                {"prompt": prompt, "max_tokens": 6, "temperature": 0.0},
                headers=headers)
            assert status == 201, (status, data[:200])
            usages.append(json.loads(data)["data"]["usage"])
        assert [u["tenant"] for u in usages] == \
            ["team-alpha", "team-alpha", "team-beta"]
        status, _, _ = request(port, "POST", "/chat",
                               {"prompt": "x", "max_tokens": 2})
        assert status == 401, "unauthenticated chat must bounce"
        print("ok: 3 authed /chat requests across 2 tenants (+401 bare)")

        status, _, data = request(port, "GET", "/debug/usage",
                                  headers={"X-Api-Key": "alpha-key"})
        assert status == 200, status
        tenants = json.loads(data)["data"]["llm"]["tenants"]
        for label in ("team-alpha", "team-beta"):
            want_p = sum(u["prompt_tokens"] for u in usages
                         if u["tenant"] == label)
            want_c = sum(u["completion_tokens"] for u in usages
                         if u["tenant"] == label)
            assert tenants[label]["prompt_tokens"] == want_p, label
            assert tenants[label]["completion_tokens"] == want_c, label
            assert tenants[label]["device_s"] > 0, label
        print("ok: /debug/usage totals == sum of chat usage fields")

        status, _, data = request(port, "GET", "/debug/slo",
                                  headers={"X-Api-Key": "alpha-key"})
        assert status == 200, status
        slo = json.loads(data)["data"]["llm"]
        assert slo["lifetime"]["total"] >= 3
        assert slo["budget"]["remaining"] == 1.0, slo["budget"]
        print("ok: /debug/slo tracking with full error budget")

        status, _, data = request(mport, "GET", "/metrics")
        assert status == 200, status
        text = data.decode()
        assert 'app_tenant_requests{status="ok",tenant="team-alpha"} 2' \
            in text, "tenant-labeled request counter missing"
        assert 'tenant="team-beta"' in text
        assert "alpha-key" not in text and "beta-key" not in text, \
            "raw API key leaked into the exposition"
        assert "trace_id" not in text, "plain scrape must not carry exemplars"
        print("ok: /metrics tenant series, no raw keys, plain format clean")

        status, headers, data = request(
            mport, "GET", "/metrics",
            headers={"Accept": "application/openmetrics-text"})
        assert status == 200, status
        assert "application/openmetrics-text" in \
            headers.get("Content-Type", ""), headers
        om = data.decode()
        assert om.rstrip().endswith("# EOF")
        assert f'trace_id="{trace_id}"' in om, \
            "traced request's exemplar missing from OpenMetrics scrape"
        spans = app.container.tracer.exporter.spans
        assert any(s.name == "engine.request" and s.trace_id == trace_id
                   for s in spans), "exemplar trace id has no engine span"
        print("ok: OpenMetrics exemplar resolves to an engine.request trace")
    except AssertionError as exc:
        print(f"FAIL: {exc}", file=sys.stderr)
        return 1
    finally:
        asyncio.run_coroutine_threadsafe(app.stop(), loop).result(30)
        thread.join(10)
    return 0


if __name__ == "__main__":
    sys.exit(main())
