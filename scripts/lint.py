#!/usr/bin/env python
"""gofrlint CLI — run the repo-native AST invariant analyzer.

    python scripts/lint.py gofr_tpu/ scripts/ bench.py
    python scripts/lint.py --format=json gofr_tpu/serving/engine.py
    python scripts/lint.py --rule hot-path-purity gofr_tpu/
    python scripts/lint.py --self-test        # seeded violation must fail

Exit codes: 0 clean (suppressed findings don't fail), 1 violations,
2 usage error. Imports only gofr_tpu.analysis (stdlib-ast; never the
code under analysis), so it runs before anything else is importable.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from gofr_tpu.analysis import RULE_IDS, run_analysis  # noqa: E402

# a deliberately rotten snippet: one violation per rule, plus a
# reason-less allow. --self-test lints it and FAILS if gofrlint stops
# seeing any of them — the CI gate's guard against silent rule rot.
SELF_TEST_SNIPPET = '''\
import time
import numpy as np
import jax
import jax.numpy as jnp
from gofr_tpu.analysis import hot_path


@hot_path
def dispatch(state, logits):
    t0 = time.time()
    host = np.asarray(state)
    n = int(jnp.sum(logits))
    return host, n, t0


class Pool:
    def locked_write(self, v):
        with self._lock:
            self._items = v

    def racy_write(self, v):
        self._items = v


async def agent_tick():
    time.sleep(0.1)


def serve(req):
    f = jax.jit(lambda x, n: x, static_argnums=(1,))
    return f(req.tokens, len(req.tokens))


def meter(metrics):
    metrics.increment_counter("app_never_registered_anywhere")


def hushed(metrics):
    metrics.set_gauge("app_also_never_registered", 1.0)  # gofrlint: allow(metric-hygiene)
'''

EXPECTED_SELF_TEST_RULES = {
    "hot-path-purity", "lock-discipline", "blocking-in-async",
    "metric-hygiene", "recompile-hazard", "bad-suppression",
}


def self_test() -> int:
    with tempfile.TemporaryDirectory() as td:
        bad = Path(td) / "rotten.py"
        bad.write_text(SELF_TEST_SNIPPET)
        findings, _ = run_analysis([bad], root=Path(td))
    hit = {f.rule for f in findings if not f.suppressed}
    missing = EXPECTED_SELF_TEST_RULES - hit
    if missing:
        print(f"gofrlint SELF-TEST FAILED: seeded violations not "
              f"detected for rule(s): {sorted(missing)}", file=sys.stderr)
        for f in findings:
            print("  " + f.render(), file=sys.stderr)
        return 1
    print(f"gofrlint self-test ok: {len(findings)} seeded findings "
          f"across {len(hit)} rules all detected")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="gofrlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", help="files or directories to lint")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--rule", action="append", dest="rules",
                    metavar="RULE", help=f"restrict to a rule "
                    f"(repeatable); one of: {', '.join(RULE_IDS)}")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print allow()'d findings with reasons")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--self-test", action="store_true",
                    help="lint a seeded-violation snippet; exit nonzero "
                         "unless every rule fires")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in RULE_IDS:
            print(r)
        return 0
    if args.self_test:
        return self_test()
    if not args.paths:
        ap.error("no paths given")
    missing = [p for p in args.paths if not Path(p).exists()]
    if missing:
        # a typo'd path exiting 0 would rot the CI gate silently
        ap.error(f"path(s) do not exist: {missing}")
    if args.rules:
        unknown = set(args.rules) - set(RULE_IDS)
        if unknown:
            ap.error(f"unknown rule(s): {sorted(unknown)}")

    findings, project = run_analysis(args.paths, rules=args.rules,
                                     root=REPO_ROOT)
    violations = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]

    if args.format == "json":
        print(json.dumps({
            "files": len(project.modules),
            "violations": [f.to_dict() for f in violations],
            "suppressed": [f.to_dict() for f in suppressed],
            "counts": _counts(violations),
        }, indent=2))
    else:
        for f in violations:
            print(f.render())
        if args.show_suppressed:
            for f in suppressed:
                print(f.render())
        tail = (f"{len(project.modules)} files, "
                f"{len(violations)} violation(s), "
                f"{len(suppressed)} allowed")
        print(("FAIL: " if violations else "ok: ") + tail)
    return 1 if violations else 0


def _counts(findings) -> dict[str, int]:
    out: dict[str, int] = {}
    for f in findings:
        out[f.rule] = out.get(f.rule, 0) + 1
    return dict(sorted(out.items()))


if __name__ == "__main__":
    sys.exit(main())
