"""CI smoke: chaos — deterministic fault injection end to end.

Three acts against the real stack, every fault fired by invocation
count (serving/faults.py — no wall clock, no RNG, reproducible under
bisect):

1. **Crash recovery**: a pass exception mid-traffic restarts the
   engine within its ``RestartPolicy`` budget; requests salvaged
   before their first token replay BIT-IDENTICALLY to a fault-free
   run, mid-stream casualties draw the typed retryable
   ``engine_restart`` reject and land bit-identically on retry; the
   goodput ledger still conserves (useful + sum(waste) == busy).
2. **Stall -> evict -> heal -> rejoin**: a wedged pass drives
   health to DEGRADED, the leader evicts on the gossip, and the
   worker rejoins on its own once the stall clears.
3. **Page exhaustion over HTTP**: an injected KV-pool exhaustion is a
   typed 503 with ``Retry-After`` + ``details.code`` on /chat and the
   OpenAI surface — never a crash; the next request serves 201.

Exits nonzero on any failure; one line per check on success.
"""

import asyncio
import http.client
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from gofr_tpu.app import App
from gofr_tpu.config import DictConfig
from gofr_tpu.serving.engine import (EngineConfig, RestartPolicy,
                                     SamplingParams)
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.tokenizer import ByteTokenizer

GREEDY = SamplingParams(temperature=0.0, max_new_tokens=6)


def request(port: int, method: str, path: str, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    headers = dict(headers or {})
    if isinstance(body, dict):
        body = json.dumps(body)
        headers.setdefault("Content-Type", "application/json")
    try:
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def run_app(app):
    """Boot ``app`` on a background loop; returns (loop, thread)."""
    loop = asyncio.new_event_loop()
    started = threading.Event()

    def runner():
        asyncio.set_event_loop(loop)

        async def main_coro():
            await app.start()
            started.set()
            await app._stop_event.wait()

        loop.run_until_complete(main_coro())

    thread = threading.Thread(target=runner, daemon=True)
    thread.start()
    if not started.wait(60):
        raise AssertionError("app did not start")
    return loop, thread


def stop_app(app, loop, thread):
    asyncio.run_coroutine_threadsafe(app.stop(), loop).result(30)
    thread.join(10)


def wait_all(reqs, timeout=120.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if all(r.finished_at is not None or r.error is not None
               for r in reqs):
            return True
        time.sleep(0.01)
    return False


# ------------------------------------------------- act 1: crash recovery
def act_crash_recovery() -> None:
    # 20 tokens = several fused decode passes per request, so decode
    # collects exist for nan_logits to corrupt mid-stream
    sp = SamplingParams(temperature=0.0, max_new_tokens=20)
    prompts = [[1 + i, 2, 3] for i in range(6)]
    ref = demo_llama_engine(EngineConfig(max_batch=2, max_seq=64, seed=0))
    ref.start()
    want = [ref.submit_sync(p, sp).generated for p in prompts]
    ref.stop()
    assert all(len(w) == 20 for w in want), "fault-free reference broken?"

    # pass_raise crashes before any token is in flight (replay path);
    # nan_logits crashes at decode collect (mid-stream typed-reject
    # path) — one run covers both recovery branches deterministically
    budget = RestartPolicy(max_restarts=3, backoff_s=0.02)
    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=64, seed=0,
        faults="pass_raise:at=3;nan_logits:at=4",
        restart_policy=budget))
    eng.start()
    t0 = time.time()
    reqs = [eng.submit(p, sp) for p in prompts]
    assert wait_all(reqs), "chaos traffic never settled"
    retried = 0
    for i, (prompt, req) in enumerate(zip(prompts, reqs)):
        if req.error is not None:
            rej = req.reject
            assert rej is not None and rej.code == "engine_restart", \
                (i, req.error)
            assert rej.retry_after_s > 0, rej
            retried += 1
            req = eng.submit(prompt, sp)
            assert wait_all([req]) and req.error is None, req.error
        assert req.generated == want[i], \
            f"recovered output diverged on prompt {i}"
    assert retried >= 1, "nan_logits never drew a mid-stream reject"
    health = eng.health_check()
    assert health["status"] == "UP", health
    assert 2 <= health["restarts"] <= budget.max_restarts, health
    assert "injected fault" in health["last_crash"], health
    elapsed = time.time() - t0
    assert elapsed < 60, f"recovery blew the budget: {elapsed:.1f}s"
    print(f"ok: crash -> restart {health['restarts']}/"
          f"{budget.max_restarts} in {elapsed:.1f}s; {len(prompts)} "
          f"outputs bit-identical ({retried} via typed retry)")

    gp = eng.goodput.state()
    waste_sum = sum(gp["waste_s"].values())
    assert gp["busy_s"] > 0, gp
    assert abs(gp["useful_s"] + waste_sum - gp["busy_s"]) < 5e-6, gp
    assert abs(gp["conservation_error_s"]) < 1e-9, gp
    eng.stop()
    print(f"ok: goodput conserves across the restart "
          f"(busy={gp['busy_s']}s, waste={round(waste_sum, 6)}s)")


# ------------------------------------ act 2: stall -> evict -> rejoin
def act_stall_evict_rejoin() -> None:
    from gofr_tpu.serving.control_plane import (ControlPlaneLeader,
                                                WorkerAgent,
                                                engine_fleet_sources)
    leader = ControlPlaneLeader(coordinator="127.0.0.1:8476")
    leader_app = App(config=DictConfig({
        "HTTP_PORT": "0", "METRICS_PORT": "0",
        "APP_NAME": "chaos-leader", "GOFR_TELEMETRY": "false"}))
    leader.install(leader_app)
    loop, thread = run_app(leader_app)
    eng = None
    agent = None
    try:
        port = leader_app.http_server.bound_port
        eng = demo_llama_engine(EngineConfig(
            max_batch=2, max_seq=128, stall_threshold_s=0.3,
            faults="pass_stall:at=4,seconds=2.5"))
        health_src, summary_src, metrics_src = engine_fleet_sources(eng)
        agent = WorkerAgent(f"http://127.0.0.1:{port}", host_id="chaos-w",
                            heartbeat_interval_s=0.1,
                            health_source=health_src,
                            summary_source=summary_src)
        eng.start()
        agent.start()
        assert agent.assignment is not None, "initial join failed"
        req = eng.submit(list(range(2, 10)), SamplingParams(
            temperature=0.0, max_new_tokens=30))
        # the 4th pass wedges 2.5s >> the 0.3s stall threshold: the
        # DEGRADED gossip must get this host evicted
        deadline = time.time() + 20
        while time.time() < deadline \
                and leader.topology()["world_size"] != 0:
            time.sleep(0.05)
        assert leader.topology()["world_size"] == 0, \
            "stalled host never evicted"
        assert leader.metrics.get("app_fleet_evictions").get(
            reason="degraded") == 1.0
        print("ok: pass_stall -> DEGRADED gossip -> leader evicted "
              "the wedged host")
        # the stall clears, the request completes, health heals, and
        # the agent's own loop rejoins without operator action
        deadline = time.time() + 30
        while time.time() < deadline and agent.assignment is None:
            time.sleep(0.05)
        assert agent.assignment is not None, "healed host never rejoined"
        assert leader.topology()["world_size"] == 1
        assert wait_all([req], timeout=30)
        assert req.error is None and len(req.generated) == 30, req.error
        print("ok: stall cleared -> health UP -> worker rejoined; the "
              "in-flight stream survived untouched")
    finally:
        if agent is not None:
            agent.stop()
        if eng is not None:
            eng.stop()
        stop_app(leader_app, loop, thread)


# ------------------------------------- act 3: page exhaustion over HTTP
def act_page_exhaustion_http() -> None:
    eng = demo_llama_engine(EngineConfig(
        max_batch=2, max_seq=128, kv_layout="paged", page_size=16,
        faults="page_exhaustion:at=1,times=2"))
    app = App(config=DictConfig({
        "HTTP_PORT": "0", "METRICS_PORT": "0",
        "APP_NAME": "chaos-smoke", "GOFR_TELEMETRY": "false"}))
    app.serve_model("llm", eng, ByteTokenizer())
    from gofr_tpu.serving.openai_compat import install_openai_routes
    install_openai_routes(app, eng, ByteTokenizer(), model="chaos")
    loop, thread = run_app(app)
    try:
        port = app.http_server.bound_port
        body = {"prompt": "kv pressure", "max_tokens": 4,
                "temperature": 0.0}
        status, headers, data = request(port, "POST", "/chat", body)
        assert status == 503, (status, data[:200])
        assert headers.get("Retry-After"), headers
        err = json.loads(data)["error"]
        details = err.get("details") or {}
        assert details.get("code") == "kv_exhausted", err
        print("ok: injected page exhaustion -> typed 503 on /chat "
              "(Retry-After + details.code=kv_exhausted)")
        status, headers, data = request(
            port, "POST", "/v1/completions",
            {"model": "chaos", "prompt": "kv pressure",
             "max_tokens": 4})
        assert status == 503, (status, data[:200])
        assert headers.get("Retry-After"), headers
        oa_err = json.loads(data)["error"]
        assert (oa_err.get("details") or {}).get("type") \
            == "server_error", oa_err
        print("ok: same fault maps to a 503 server_error on the "
              "OpenAI surface, Retry-After intact")
        # the plan window (times=2) is spent: the engine never crashed
        status, _, data = request(port, "POST", "/chat", body)
        assert status == 201, (status, data[:200])
        assert eng.health_check()["status"] == "UP"
        print("ok: engine survived — next /chat is 201, health UP")
    finally:
        stop_app(app, loop, thread)


def main() -> int:
    try:
        act_crash_recovery()
        act_stall_evict_rejoin()
        act_page_exhaustion_http()
    except AssertionError as exc:
        print(f"FAIL: {exc}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
