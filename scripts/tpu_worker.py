"""Persistent TPU probe + job-queue worker.

The axon TPU tunnel intermittently hangs forever at backend init, so a
single probe at bench time is not enough persistence.  This worker runs
for the whole round in the background:

  * every PROBE_INTERVAL_S it probes the TPU backend in a bounded,
    fresh subprocess (never inline — a hung init would wedge the loop);
  * when the probe succeeds, it drains `scripts/tpu_queue/*.py` in
    lexical order, running each job in its own bounded subprocess with
    the TPU backend, writing stdout/stderr + rc to
    `scripts/tpu_results/<job>.json`, and moving the job file to
    `scripts/tpu_done/`;
  * all probe attempts and outcomes append to `scripts/tpu_state.jsonl`
    so the session can check tunnel health at a glance;
  * every result is stamped with the git SHA it ran against, and when
    HEAD moves (a new commit lands) the whole canonical job set in
    `scripts/tpu_jobs/` is re-enqueued so measurements never rot
    against stale code.

Jobs are plain python scripts run with cwd=repo root; they should print
whatever artifact they produce (one JSON line by convention).  A job
that times out or crashes is moved to tpu_done with ok=false — re-queue
by copying it back.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
QUEUE = os.path.join(HERE, "tpu_queue")
JOBS = os.path.join(HERE, "tpu_jobs")
DONE = os.path.join(HERE, "tpu_done")
RESULTS = os.path.join(HERE, "tpu_results")
STATE = os.path.join(HERE, "tpu_state.jsonl")

PROBE_INTERVAL_S = int(os.environ.get("GOFR_TPU_PROBE_INTERVAL", "120"))
PROBE_TIMEOUT_S = int(os.environ.get("GOFR_TPU_PROBE_TIMEOUT", "180"))
JOB_TIMEOUT_S = int(os.environ.get("GOFR_TPU_JOB_TIMEOUT", "1800"))
MAX_RUNTIME_S = int(os.environ.get("GOFR_TPU_WORKER_MAX_S", str(11 * 3600)))

PROBE_CODE = """
import jax
d = jax.devices()
print("PROBE_OK", jax.default_backend(), len(d), d[0].device_kind)
"""


def _env_tpu() -> dict:
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["GOFR_TELEMETRY"] = "false"
    # jobs run as `python scripts/tpu_queue/<job>.py`, which puts the
    # QUEUE dir (not the repo) on sys.path — gofr_tpu must resolve
    env["PYTHONPATH"] = REPO + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    # ONE shared persistent compile-cache dir for every job child, so
    # warmup compiles amortize across the whole drain instead of being
    # re-paid per job (the r5 window went ~10:1 to recompiles)
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from gofr_tpu.config.env import (COMPILE_CACHE_ENV,
                                     resolve_compile_cache_dir)
    env.setdefault(COMPILE_CACHE_ENV,
                   resolve_compile_cache_dir() or "off")
    return env


def _log(rec: dict) -> None:
    rec["ts"] = round(time.time(), 1)
    with open(STATE, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def _probe() -> dict | None:
    """Return {"backend","n","kind"} on success, else None."""
    try:
        p = subprocess.run([sys.executable, "-c", PROBE_CODE], env=_env_tpu(),
                           capture_output=True, text=True,
                           timeout=PROBE_TIMEOUT_S, cwd=REPO)
    except subprocess.TimeoutExpired:
        _log({"event": "probe", "ok": False, "why": f"timeout {PROBE_TIMEOUT_S}s"})
        return None
    toks = p.stdout.split()
    if p.returncode == 0 and "PROBE_OK" in toks:
        i = toks.index("PROBE_OK")
        backend, n = toks[i + 1], int(toks[i + 2])
        kind = " ".join(toks[i + 3:])
        if backend != "cpu":
            _log({"event": "probe", "ok": True, "backend": backend,
                  "n": n, "kind": kind})
            return {"backend": backend, "n": n, "kind": kind}
        _log({"event": "probe", "ok": False, "why": "cpu-only backend"})
        return None
    tail = (p.stderr or p.stdout).strip().splitlines()[-1:] or ["?"]
    _log({"event": "probe", "ok": False, "why": f"rc={p.returncode} {tail[0][:200]}"})
    return None


def _head_sha() -> str:
    try:
        p = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                           capture_output=True, text=True, cwd=REPO, timeout=10)
        return p.stdout.strip() if p.returncode == 0 else "?"
    except Exception:
        return "?"


def _reenqueue_all(sha: str) -> int:
    """Copy the canonical job set back into the queue (overwriting any
    still-queued stale copy with fresh job code, attempts reset) so
    the new commit gets measured; returns #jobs enqueued."""
    n = 0
    for name in sorted(os.listdir(JOBS)):
        if not name.endswith(".py"):
            continue
        shutil.copy(os.path.join(JOBS, name), os.path.join(QUEUE, name))
        if name.startswith("_"):
            # _-prefixed files are shared helpers (e.g. _profiling.py):
            # copied so queued job copies can import them, never run
            continue
        _attempts.pop(name, None)
        n += 1
    if n:
        _log({"event": "reenqueue", "sha": sha, "n": n})
    return n


_attempts: dict[str, int] = {}
MAX_ATTEMPTS = 3


def _parse_payload(stdout: str) -> dict | None:
    """Last JSON-object line of a job's stdout (jobs print one JSON
    artifact line by convention; bench lines may carry a BENCH_JSON
    prefix). None when nothing parses."""
    for line in reversed((stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("BENCH_JSON "):
            line = line[len("BENCH_JSON "):]
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict):
            return obj
    return None


def _job_ok(rc, stdout: str) -> tuple[bool, str]:
    """ok requires rc == 0 AND a parsed, non-error payload — a job
    that prints an error payload and exits 0 (bench.py's containment
    does exactly that) is a failed measurement, not a success."""
    if rc != 0:
        return False, f"rc={rc}"
    payload = _parse_payload(stdout)
    if payload is None:
        return False, "no JSON payload in stdout"
    if payload.get("error"):
        return False, "payload carries an error field"
    return True, ""


def _run_job(path: str) -> None:
    name = os.path.basename(path)
    _attempts[name] = _attempts.get(name, 0) + 1
    _log({"event": "job_start", "job": name,
          "attempt": _attempts[name]})
    t0 = time.time()
    try:
        p = subprocess.run([sys.executable, path], env=_env_tpu(),
                           capture_output=True, text=True,
                           timeout=JOB_TIMEOUT_S, cwd=REPO)
        rc, out, err = p.returncode, p.stdout, p.stderr
    except subprocess.TimeoutExpired as e:
        rc = None
        out = e.stdout.decode() if isinstance(e.stdout, bytes) else (e.stdout or "")
        err = (e.stderr.decode() if isinstance(e.stderr, bytes) else (e.stderr or "")) \
            + f"\n[timeout after {JOB_TIMEOUT_S}s]"
    wall = round(time.time() - t0, 1)
    ok, why = _job_ok(rc, out)
    result = {"job": name, "ok": ok, "rc": rc, "wall_s": wall,
              "attempt": _attempts[name], "git_sha": _head_sha(),
              "stdout": out[-20000:], "stderr": err[-8000:],
              "ts": round(time.time(), 1)}
    if not ok:
        result["not_ok_why"] = why
    with open(os.path.join(RESULTS, name + ".json"), "w") as f:
        json.dump(result, f, indent=1)
    if not ok and _attempts[name] < MAX_ATTEMPTS:
        # most failures here are the tunnel dying mid-job — leave it
        # queued for the next healthy window (bounded, so a
        # deterministic crash cannot eat every window)
        _log({"event": "job_retry_queued", "job": name,
              "attempt": _attempts[name], "wall_s": wall})
        return
    shutil.move(path, os.path.join(DONE, name))
    _log({"event": "job_done", "job": name, "ok": ok, "wall_s": wall})


def main() -> None:
    for d in (QUEUE, DONE, RESULTS):
        os.makedirs(d, exist_ok=True)
    t_start = time.time()
    _log({"event": "worker_start", "pid": os.getpid(), "sha": _head_sha()})
    last_sha = _head_sha()
    while time.time() - t_start < MAX_RUNTIME_S:
        sha = _head_sha()
        if sha != "?" and sha != last_sha:  # "?" = transient git hiccup
            last_sha = sha
            _reenqueue_all(sha)
        jobs = sorted(f for f in os.listdir(QUEUE)
                      if f.endswith(".py") and not f.startswith("_"))
        drained = False
        if jobs and _probe() is not None:
            # tunnel healthy right now — drain while it lasts, but
            # re-probe between jobs: a mid-drain tunnel death must not
            # burn a full init-timeout per remaining queued job
            # (observed r5: jobs 05/06/07 each waited ~25 min against
            # a dead backend after 04 outlived the tunnel)
            for i, name in enumerate(jobs):
                path = os.path.join(QUEUE, name)
                if not os.path.exists(path):
                    continue
                if i > 0 and _probe() is None:
                    _log({"event": "drain_abort",
                          "why": "tunnel died mid-drain"})
                    drained = False  # back off (PROBE_INTERVAL_S), the
                    break            # tunnel was just observed dead
                _run_job(path)
                drained = True
        # only hurry when the tunnel just proved healthy; a failed
        # probe already burned PROBE_TIMEOUT_S — don't hammer it
        time.sleep(30 if drained else PROBE_INTERVAL_S)
    _log({"event": "worker_exit"})


if __name__ == "__main__":
    main()
