"""CI smoke: two-tenant contention against a LIVE scheduler.

Boots a real App with API-key auth (a hot tenant and a victim) and a
fair-share scheduler with a tight rate limit on the hot tenant, then
drives a flood from the hot tenant interleaved with polite victim
traffic and asserts the admission plane end to end:

- the hot tenant's flood draws typed 429s, every one carrying a
  ``Retry-After`` header and a ``rate_limited`` error code,
- the victim's requests all succeed and its per-tenant fast-burn
  column on ``GET /debug/scheduler`` never trips,
- ``/debug/scheduler`` reports both tenants with device-time shares
  and the admission counters account for the rejections,
- ``app_sched_rejections`` lands on /metrics with cause/tenant labels.

Exits nonzero on any failure; one line per check on success.
"""

import asyncio
import http.client
import json
import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from gofr_tpu.app import App
from gofr_tpu.config import DictConfig
from gofr_tpu.serving.engine import EngineConfig
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.scheduler import RateLimit, SchedulerConfig
from gofr_tpu.serving.tokenizer import ByteTokenizer

KEYS = {"hot-key": "team-hot", "victim-key": "team-victim"}
FAST_BURN_THRESHOLD = 14.4


def request(port: int, method: str, path: str, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    headers = dict(headers or {})
    if isinstance(body, dict):
        body = json.dumps(body)
        headers.setdefault("Content-Type", "application/json")
    try:
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def chat(port, key, prompt, max_tokens=4):
    return request(port, "POST", "/chat",
                   {"prompt": prompt, "max_tokens": max_tokens,
                    "temperature": 0.0},
                   headers={"X-Api-Key": key})


def main() -> int:
    engine = demo_llama_engine(EngineConfig(max_batch=2, max_seq=128,
                                            seed=0))
    app = App(config=DictConfig({
        "HTTP_PORT": "0", "METRICS_PORT": "0",
        "APP_NAME": "contention-smoke", "TRACE_EXPORTER": "memory",
        "GOFR_TELEMETRY": "false"}))
    app.enable_api_key_auth(key_names=KEYS)
    app.serve_model("llm", engine, ByteTokenizer(),
                    scheduler=SchedulerConfig(
                        rate_limits={"team-hot": RateLimit(rps=2.0,
                                                           burst=2.0)}))

    loop = asyncio.new_event_loop()
    started = threading.Event()

    def runner():
        asyncio.set_event_loop(loop)

        async def main_coro():
            await app.start()
            started.set()
            await app._stop_event.wait()

        loop.run_until_complete(main_coro())

    thread = threading.Thread(target=runner, daemon=True)
    thread.start()
    if not started.wait(60):
        print("FAIL: app did not start", file=sys.stderr)
        return 1
    try:
        port = app.http_server.bound_port
        mport = app.metrics_server.bound_port

        # the hot tenant floods past its 2 rps / burst 2 budget while
        # the victim interleaves polite traffic
        hot_ok = hot_429 = 0
        for i in range(10):
            status, headers, data = chat(port, "hot-key",
                                         f"hot flood {i}")
            if status == 201:
                hot_ok += 1
                continue
            assert status == 429, (status, data[:200])
            hot_429 += 1
            retry_after = headers.get("Retry-After")
            assert retry_after and int(retry_after) >= 1, headers
            err = json.loads(data)["error"]
            details = err.get("details") or {}
            assert details.get("code") == "rate_limited", err
            assert details.get("tenant") == "team-hot", err
        assert hot_ok >= 1, "the burst budget admits nothing?"
        assert hot_429 >= 1, "10-deep flood never hit the 2/s limit"
        print(f"ok: hot flood drew {hot_429} typed 429s "
              f"(Retry-After + rate_limited code), {hot_ok} admitted")

        for i in range(3):
            status, _, data = chat(port, "victim-key", f"victim {i}")
            assert status == 201, (status, data[:200])
        print("ok: victim traffic all 201 beside the flood")

        # ?fresh=1 forces a ledger-share refresh past the 0.5s cache
        # window, so the victim's retires are visible with no sleep
        status, _, data = request(port, "GET", "/debug/scheduler?fresh=1",
                                  headers={"X-Api-Key": "victim-key"})
        assert status == 200, status
        sched = json.loads(data)["data"]["llm"]
        assert sched["policy"] == "fair", sched["policy"]
        tenants = sched["tenants"]
        assert {"team-hot", "team-victim"} <= set(tenants), tenants
        for name in ("team-hot", "team-victim"):
            assert "device_share" in tenants[name], tenants[name]
            assert tenants[name]["device_share_s"] > 0, name
        victim_burn = tenants["team-victim"]["burn"]
        assert victim_burn["total"] >= 3, victim_burn
        assert victim_burn["bad"] == 0, victim_burn
        assert victim_burn["burn_rate"] < FAST_BURN_THRESHOLD, \
            victim_burn
        rejected = sched["counters"]["rejected"]
        assert rejected["rate_limited"] == hot_429, (rejected, hot_429)
        assert "rps_bucket_level" in tenants["team-hot"]
        print("ok: /debug/scheduler shares + victim fast-burn clean "
              f"(burn_rate={victim_burn['burn_rate']}, "
              f"rejections accounted: {rejected['rate_limited']})")

        status, _, data = request(port, "GET", "/debug/slo",
                                  headers={"X-Api-Key": "victim-key"})
        assert status == 200, status
        slo = json.loads(data)["data"]["llm"]
        assert not slo["fast_burn"]["tripped"], slo["fast_burn"]
        print("ok: global fast burn untouched by the 429 flood "
              f"(burn_rate={slo['fast_burn']['burn_rate']})")

        status, _, data = request(mport, "GET", "/metrics")
        assert status == 200, status
        text = data.decode()
        assert 'app_sched_rejections{cause="rate_limited",' \
            'tenant="team-hot"}' in text, \
            "typed rejection counter missing from the exposition"
        assert "hot-key" not in text and "victim-key" not in text, \
            "raw API key leaked into the exposition"
        print("ok: app_sched_rejections{cause,tenant} on /metrics, "
              "no raw keys")
    except AssertionError as exc:
        print(f"FAIL: {exc}", file=sys.stderr)
        return 1
    finally:
        asyncio.run_coroutine_threadsafe(app.stop(), loop).result(30)
        thread.join(10)
    return 0


if __name__ == "__main__":
    sys.exit(main())
