"""CI smoke: scrape a LIVE app's /metrics and assert the engine series.

Boots a real App with a tiny serving engine on ephemeral ports, drives
one chat request with a traceparent, scrapes the Prometheus text off
the metrics port, parses it, and asserts the engine observability
surface is present with samples — the end-to-end check that the
registry, the engine write sites and the exposition format agree.
Also hits /debug/engine for the flight-recorder ring. Exits nonzero on
any failure; one line per check on success.
"""

import asyncio
import http.client
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from gofr_tpu.app import App
from gofr_tpu.config import DictConfig
from gofr_tpu.serving.engine import EngineConfig
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.tokenizer import ByteTokenizer

REQUIRED_SERIES = (
    "app_chat_ttft_seconds_count",
    "app_chat_queue_seconds_count",
    "app_chat_tpot_seconds_count",
    "app_chat_e2e_seconds_count",
    "app_engine_batch_occupancy_count",
    "app_engine_kv_pool_utilization",
    "app_engine_active_slots",
    "app_engine_tokens_per_second",
)


def parse_prometheus(text: str) -> dict[str, float]:
    """name{labels} value -> {name: value} (labels dropped, last wins)."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value = line.rpartition(" ")
        name = name_part.split("{", 1)[0]
        try:
            out[name] = float(value)
        except ValueError:
            continue
    return out


def request(port: int, method: str, path: str, body=None, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    headers = dict(headers or {})
    if isinstance(body, dict):
        body = json.dumps(body)
        headers.setdefault("Content-Type", "application/json")
    try:
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def main() -> int:
    engine = demo_llama_engine(EngineConfig(
        max_batch=4, max_seq=128, seed=0, kv_layout="paged",
        page_size=16, prefix_cache=True, paged_attention="view"))
    app = App(config=DictConfig({
        "HTTP_PORT": "0", "METRICS_PORT": "0",
        "APP_NAME": "metrics-smoke", "TRACE_EXPORTER": "memory",
        "GOFR_TELEMETRY": "false"}))
    app.serve_model("llm", engine, ByteTokenizer())

    loop = asyncio.new_event_loop()
    started = threading.Event()

    def runner():
        asyncio.set_event_loop(loop)

        async def main_coro():
            await app.start()
            started.set()
            await app._stop_event.wait()

        loop.run_until_complete(main_coro())

    thread = threading.Thread(target=runner, daemon=True)
    thread.start()
    if not started.wait(60):
        print("FAIL: app did not start", file=sys.stderr)
        return 1
    try:
        port = app.http_server.bound_port
        mport = app.metrics_server.bound_port
        traceparent = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
        status, data = request(
            port, "POST", "/chat",
            {"prompt": "observability smoke prompt", "max_tokens": 8,
             "temperature": 0.0},
            headers={"traceparent": traceparent})
        assert status == 201, (status, data[:200])
        print("ok: /chat 201")
        time.sleep(0.6)  # let the throttled gauges refresh post-retire

        status, data = request(port, "GET", "/debug/engine?n=16")
        assert status == 200, (status, data[:200])
        flight = json.loads(data)["data"]["llm"]["flight"]
        assert flight["passes"], "flight recorder ring is empty"
        print(f"ok: /debug/engine ({len(flight['passes'])} pass records)")

        status, data = request(mport, "GET", "/metrics")
        assert status == 200, status
        series = parse_prometheus(data.decode())
        missing = [s for s in REQUIRED_SERIES if s not in series]
        assert not missing, f"missing series: {missing}"
        zero = [s for s in ("app_chat_queue_seconds_count",
                            "app_chat_tpot_seconds_count",
                            "app_engine_batch_occupancy_count",
                            "app_engine_kv_pool_utilization")
                if series.get(s, 0.0) <= 0.0]
        assert not zero, f"series present but zero: {zero}"
        print(f"ok: /metrics ({len(series)} series, engine surface live)")

        spans = app.container.tracer.exporter.spans
        engine_spans = [s for s in spans if s.name.startswith("engine.")
                        and s.trace_id == "ab" * 16]
        assert engine_spans, "no engine.* spans linked to the traceparent"
        print(f"ok: {len(engine_spans)} engine.* spans on the inbound trace")
    except AssertionError as exc:
        print(f"FAIL: {exc}", file=sys.stderr)
        return 1
    finally:
        asyncio.run_coroutine_threadsafe(app.stop(), loop).result(30)
        thread.join(10)
    return 0


if __name__ == "__main__":
    sys.exit(main())
