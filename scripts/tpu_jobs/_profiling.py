"""Env-gated xprof capture for TPU job entrypoints.

``GOFR_JOB_PROFILE=1`` wraps a job's measured region in
``jax.profiler.start_trace/stop_trace``, landing an xprof trace under
``GOFR_JOB_PROFILE_DIR`` (default ``/tmp/gofr_tpu_profiles``) — the
same capture the serving app exposes at ``POST /debug/profile/start``
(gofr_tpu/serving/observability.py), so the next TPU window gets
profiler traces for free alongside the jobs' JSON lines.

Usage in a job (after the sys.path/jax setup)::

    from profiling import profile_start, profile_stop
    trace_dir = profile_start("decode_microprof")
    ...  # measured region
    profile_stop(trace_dir)
    out["xprof_trace"] = trace_dir  # None when disabled
"""

import os
import sys
import time


def profile_start(job: str) -> str | None:
    """Start an xprof capture when GOFR_JOB_PROFILE=1; returns the
    trace directory, or None when profiling is off or failed (a broken
    profiler must never take the measurement down with it)."""
    if os.environ.get("GOFR_JOB_PROFILE") != "1":
        return None
    try:
        import jax
        base = os.environ.get("GOFR_JOB_PROFILE_DIR",
                              "/tmp/gofr_tpu_profiles")
        trace_dir = os.path.join(
            base, f"{job}-{time.strftime('%Y%m%d-%H%M%S')}")
        os.makedirs(trace_dir, exist_ok=True)
        jax.profiler.start_trace(trace_dir)
        print(f"# xprof capture -> {trace_dir}", file=sys.stderr)
        return trace_dir
    except Exception as exc:
        print(f"# xprof start failed: {exc!r}", file=sys.stderr)
        return None


def profile_stop(trace_dir: str | None) -> None:
    if trace_dir is None:
        return
    try:
        import jax
        jax.profiler.stop_trace()
        print(f"# xprof trace written: {trace_dir}", file=sys.stderr)
    except Exception as exc:
        print(f"# xprof stop failed: {exc!r}", file=sys.stderr)
