"""TPU job: run the standard bench pinned to the TPU platform."""
import os
import runpy

os.environ["GOFR_BENCH_PLATFORM"] = "tpu"
runpy.run_path("bench.py", run_name="__main__")
