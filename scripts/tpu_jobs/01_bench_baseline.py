"""TPU job: run the standard bench pinned to the TPU platform."""
import os
import runpy
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))
# shared persistent compile cache for the bench children (jax-free
# resolve — this wrapper, like bench's parent, never imports jax)
from gofr_tpu.config.env import (COMPILE_CACHE_ENV,
                                 resolve_compile_cache_dir)

os.environ.setdefault(COMPILE_CACHE_ENV,
                      resolve_compile_cache_dir() or "off")
os.environ["GOFR_BENCH_PLATFORM"] = "tpu"
runpy.run_path("bench.py", run_name="__main__")
