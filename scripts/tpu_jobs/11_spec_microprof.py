"""TPU job: tree-verify pass cost vs plain decode on the ragged kernel.

Adaptive speculation's economics rest on one chip fact: a W-node
tree-verify pass streams the same KV history as a 1-row decode pass,
so while the kernel stays memory-bound its cost is ~flat in W and
every accepted draft token is nearly free. This job measures, on a
real chip, the bare ragged kernels: paged_tree_attention_pallas at
each pow-2 verify width the engine buckets to (2..16) against
paged_decode_attention_pallas at the same history depths. It reports
per-width pass-cost ratios (the SpecController's row-cost EWMA in
vitro), the break-even tokens-per-pass each width needs, and the tree
kernel's overhead against the plain causal chunk kernel at the same
row count (what the ancestor-bitmask select ladder costs). One JSON
line.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax
import jax.numpy as jnp

SMOKE = os.environ.get("GOFR_JOB_SMOKE") == "1"
if SMOKE:
    jax.config.update("jax_platforms", "cpu")
if not SMOKE:
    assert jax.default_backend() != "cpu", "TPU job ran on CPU"

from gofr_tpu.config.env import enable_compile_cache
enable_compile_cache()

from gofr_tpu.models.llama import LlamaConfig
from gofr_tpu.ops.paged_attention import (paged_chunk_attention_pallas,
                                          paged_decode_attention_pallas,
                                          paged_tree_attention_pallas)
from gofr_tpu.ops.paged_kv import quantize_pool

out = {"job": "spec_microprof", "backend": jax.default_backend(),
       "device": jax.devices()[0].device_kind}

# GOFR_JOB_PROFILE=1: xprof capture of the whole measured region
from _profiling import profile_start, profile_stop
_trace_dir = profile_start("spec_microprof")

c = LlamaConfig.tiny() if SMOKE else LlamaConfig.llama3_1b().scaled(
    max_seq=2048)
B = 2 if SMOKE else 16
PAGE = 16 if SMOKE else 64
MAX_SEQ = 128 if SMOKE else 2048
REPS = 2 if SMOKE else 20
WIDTHS = (2, 4) if SMOKE else (2, 4, 8, 16)
hd = c.head_dim


def timed(fn, *args, reps=REPS):
    r = fn(*args)
    jax.block_until_ready(r)
    walls = []
    for _ in range(reps):
        t0 = time.perf_counter()
        r = fn(*args)
        jax.block_until_ready(r)
        walls.append(time.perf_counter() - t0)
    walls.sort()
    return walls[len(walls) // 2]


# ---- one layer's pool, every slot's table pointing at distinct pages
mp = MAX_SEQ // PAGE
n_pages = B * mp
key = jax.random.key(0)
kk, kv, kq = jax.random.split(key, 3)
kp = jax.random.normal(kk, (c.n_kv_heads, n_pages, PAGE, hd), jnp.bfloat16)
vp = jax.random.normal(kv, (c.n_kv_heads, n_pages, PAGE, hd), jnp.bfloat16)
kp8, vp8 = quantize_pool(kp), quantize_pool(vp)
tables = jnp.arange(B * mp, dtype=jnp.int32).reshape(B, mp)

dec = jax.jit(lambda q, k, v, t, ln: paged_decode_attention_pallas(
    q, k, v, t, ln, interpret=SMOKE))
tree = jax.jit(lambda q, k, v, t, h, l, m: paged_tree_attention_pallas(
    q, k, v, t, h, l, m, interpret=SMOKE))
chk = jax.jit(lambda q, k, v, t, h, l: paged_chunk_attention_pallas(
    q, k, v, t, h, l, interpret=SMOKE))


def chain_masks(w):
    # a linear chain: node i sees ancestors 0..i — densest realistic
    # mask row (deep accepted paths), worst case for the select ladder
    bits = (1 << (jnp.arange(w, dtype=jnp.int32) + 1)) - 1
    return jnp.broadcast_to(bits, (B, w)).astype(jnp.int32)


q1 = jax.random.normal(kq, (B, c.n_heads, hd), jnp.bfloat16)
for hist in (MAX_SEQ // 4, MAX_SEQ - 16):
    lens = jnp.full((B,), hist, jnp.int32)
    t_dec = timed(dec, q1, kp, vp, tables, lens)
    out[f"decode_h{hist}_ms"] = round(t_dec * 1e3, 3)
    for w in WIDTHS:
        qw = jax.random.normal(kq, (B, w, c.n_heads, hd), jnp.bfloat16)
        cl = jnp.full((B,), w, jnp.int32)
        t_tree = timed(tree, qw, kp, vp, tables, lens, cl,
                       chain_masks(w))
        ratio = t_tree / t_dec
        out[f"tree_w{w}_h{hist}_ms"] = round(t_tree * 1e3, 3)
        # pass-cost ratio: the controller's verify row economics — a
        # verify pass must yield >= this many tokens (accepted + the
        # bonus) to beat `ratio` decode passes emitting 1 each
        out[f"tree_w{w}_h{hist}_cost_ratio"] = round(ratio, 3)
        out[f"tree_w{w}_h{hist}_breakeven_tok_per_pass"] = round(ratio,
                                                                 3)

# ---- tree-mask overhead vs the plain causal chunk kernel at the same
# row count (same pages walked, same flash accumulation — the delta is
# the ancestor-bitmask visibility ladder)
hist = MAX_SEQ - 16
hl = jnp.full((B,), hist, jnp.int32)
for w in WIDTHS:
    qw = jax.random.normal(kq, (B, w, c.n_heads, hd), jnp.bfloat16)
    cl = jnp.full((B,), w, jnp.int32)
    t_tree = timed(tree, qw, kp, vp, tables, hl, cl, chain_masks(w))
    t_chk = timed(chk, qw, kp, vp, tables, hl, cl)
    out[f"tree_vs_chunk_w{w}_overhead"] = round(t_tree / t_chk, 3)

# ---- int8 pool: verify must ride the same quantized-page DMA win the
# decode kernel gets (acceptance moves raw codes, so spec + int8 KV is
# the production config)
w = WIDTHS[-1]
qw = jax.random.normal(kq, (B, w, c.n_heads, hd), jnp.bfloat16)
cl = jnp.full((B,), w, jnp.int32)
t_b = timed(tree, qw, kp, vp, tables, hl, cl, chain_masks(w))
t_i = timed(tree, qw, kp8, vp8, tables, hl, cl, chain_masks(w))
out[f"tree_w{w}_int8_speedup"] = round(t_b / t_i, 3)

out["config"] = (f"B={B} hq={c.n_heads} hkv={c.n_kv_heads} hd={hd} "
                 f"page={PAGE} max_seq={MAX_SEQ} widths={WIDTHS} "
                 f"impl={'interpret' if SMOKE else 'pallas'}")

profile_stop(_trace_dir)
out["xprof_trace"] = _trace_dir
print(json.dumps(out))
