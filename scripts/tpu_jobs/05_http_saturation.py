"""TPU job: saturation through the REAL HTTP stack on the 1B model
(VERDICT r3 #9): 96 concurrent /chat requests against the app server +
engine on the chip; reports req/s, p50/p99 TTFT, fairness ratio.
"""

import json
import os
import sys

# jobs run as `python scripts/tpu_queue/<job>.py` — put the repo root
# (three levels up) on sys.path so gofr_tpu resolves standalone
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))
import statistics
import threading
import time

import jax

SMOKE = os.environ.get("GOFR_JOB_SMOKE") == "1"
if SMOKE:
    # the env var alone does not beat the axon plugin
    jax.config.update("jax_platforms", "cpu")
if not SMOKE:
    assert jax.default_backend() != "cpu", "TPU job ran on CPU"

# shared persistent XLA compile cache: this job's warmup compiles
# amortize across every child in the round (config/env.py)
from gofr_tpu.config.env import enable_compile_cache
enable_compile_cache()

from gofr_tpu.models.llama import LlamaConfig, llama_init
from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import llama_engine
from gofr_tpu.serving.handlers import make_chat_handler
from gofr_tpu.serving.tokenizer import ByteTokenizer

import sys
sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "tests"))
from apputil import AppRunner  # noqa: E402  (the test harness runner)

# smoke vocab must cover the ByteTokenizer's bos/eos ids (257/258)
config = LlamaConfig.tiny().scaled(vocab_size=512) if SMOKE \
    else LlamaConfig.llama3_1b().scaled(max_seq=1024)
params = llama_init(jax.random.key(0), config)
jax.block_until_ready(params)

engine = llama_engine(params, config, EngineConfig(
    max_batch=4 if SMOKE else 32, max_seq=config.max_seq, seed=0,
    prefill_buckets=(16, 64) if SMOKE else (64, 128, 256, 512)))
engine.warmup(prompt_lens=(16 if SMOKE else 64,))
engine.start()

N, GEN = (12, 6) if SMOKE else (96, 32)
results, errors = [], []
lock = threading.Lock()

with AppRunner() as runner:
    runner.app.post("/chat", make_chat_handler(engine, ByteTokenizer()))

    def one(i):
        t0 = time.perf_counter()
        try:
            status, _, data = runner.request(
                "POST", "/chat",
                # BOS brings the token count to exactly the warmed
                # bucket (16 smoke / 64 real) — no inline compiles in
                # the measured window
                body={"prompt": "x" * (15 if SMOKE else 63),
                      "max_tokens": GEN,
                      "temperature": 0.0}, timeout=600)
            body = json.loads(data)
            with lock:
                if status == 201:
                    results.append({
                        "wall": time.perf_counter() - t0,
                        "ttft_ms": body["data"]["usage"]["ttft_ms"]})
                else:
                    errors.append(f"{status}: {data[:100]}")
        except Exception as exc:
            with lock:
                errors.append(repr(exc))

    t0 = time.time()
    threads = [threading.Thread(target=one, args=(i,)) for i in range(N)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=900)
    wall = time.time() - t0

engine.stop()
ttfts = sorted(r["ttft_ms"] for r in results if r["ttft_ms"])
out = {
    "job": "http_saturation", "device": jax.devices()[0].device_kind,
    "n": N, "ok": len(results), "errors": len(errors),
    "error_sample": errors[:3],
    "wall_s": round(wall, 2),
    "req_per_s": round(len(results) / wall, 2),
    "tok_per_s": round(len(results) * GEN / wall, 1),
    "p50_ttft_ms": round(statistics.median(ttfts), 1) if ttfts else -1,
    "p99_ttft_ms": round(ttfts[int(0.99 * (len(ttfts) - 1))], 1)
    if ttfts else -1,
    "fairness_max_over_p50": round(ttfts[-1] / max(1e-9,
                                   statistics.median(ttfts)), 1)
    if ttfts else -1,
}
print("RESULT_JSON " + json.dumps(out))
