"""TPU job: serving-engine saturation sweep on the 1B bench model.

Sweeps max_batch x K (decode_steps_per_pass) and kv layout on the real
chip, recording tok/s, req/s, p50 TTFT, phase attribution, MFU and the
HBM decode roofline per point (VERDICT r3 #2/#4). One JSON line at the
end carries every point; intermediate lines stream per point so a
tunnel death mid-sweep still leaves data.
"""

import json
import os
import sys

# jobs run as `python scripts/tpu_queue/<job>.py` — put the repo root
# (three levels up) on sys.path so gofr_tpu resolves standalone
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))
import statistics
import time

import jax
import numpy as np

SMOKE = os.environ.get("GOFR_JOB_SMOKE") == "1"
if SMOKE:
    # the env var alone does not beat the axon plugin
    jax.config.update("jax_platforms", "cpu")
if not SMOKE:
    assert jax.default_backend() != "cpu", "TPU job ran on CPU"

# shared persistent XLA compile cache: this job's warmup compiles
# amortize across every child in the round (config/env.py)
from gofr_tpu.config.env import enable_compile_cache
enable_compile_cache()

from gofr_tpu.models.llama import LlamaConfig, llama_init, param_count
from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import llama_engine

# GOFR_JOB_PROFILE=1: xprof capture spanning the sweep points
from _profiling import profile_start, profile_stop
_trace_dir = profile_start("engine_sweep")

DEV = jax.devices()[0].device_kind
PEAK_FLOPS = {"TPU v5 lite": 197e12, "TPU v5": 459e12, "TPU v5p": 459e12,
              "TPU v4": 275e12, "TPU v6 lite": 918e12}
HBM_GBS = {"TPU v5 lite": 819, "TPU v5": 2765, "TPU v5p": 2765,
           "TPU v4": 1228, "TPU v6 lite": 1640}
peak = next((v for kname, v in sorted(PEAK_FLOPS.items(),
                                      key=lambda kv: -len(kv[0]))
             if DEV.startswith(kname)), None)
hbm = next((v for kname, v in sorted(HBM_GBS.items(),
                                     key=lambda kv: -len(kv[0]))
            if DEV.startswith(kname)), None)

config = LlamaConfig.tiny() if SMOKE \
    else LlamaConfig.llama3_1b().scaled(max_seq=1024)
params = llama_init(jax.random.key(0), config)
jax.block_until_ready(params)
n_params = param_count(params)
# decode roofline: each generated token must stream every parameter
# (2 bytes bf16) + the request's KV rows; params dominate at this
# scale, so tokens/s <= HBM_bw / (2 * n_params / batch) per batch row
param_bytes = 2.0 * n_params

points = []


def run_point(max_batch, k_steps, layout, n_requests=None,
              prompt_len=64, gen_len=64, paged_attention="auto",
              quantize=None):
    if SMOKE:
        max_batch = min(max_batch, 4)
        prompt_len, gen_len = 16, 8
        if paged_attention == "kernel":
            paged_attention = "interpret"
    n_requests = n_requests or max_batch * 4
    eng_cfg = EngineConfig(
        max_batch=max_batch, max_seq=config.max_seq,
        prefill_buckets=(16, 64) if SMOKE else (64, 128, 256, 512),
        seed=0, decode_steps_per_pass=k_steps, kv_layout=layout,
        page_size=16 if SMOKE else 64, paged_attention=paged_attention,
        # prompt+gen stay under 128 rows; windowed attention keeps
        # slot-layout decode reads O(live rows), not O(max_seq)
        decode_windows=() if SMOKE else (128, 256))
    engine = llama_engine(params, config, eng_cfg, quantize=quantize)
    sp = SamplingParams(temperature=0.0, max_new_tokens=gen_len)
    prompt = list(range(1, prompt_len + 1))
    engine.warmup(prompt_lens=(prompt_len,))
    engine.start()
    # rinse: one sub-batch end-to-end so lazy-compile stragglers and
    # first-dispatch overhead are out of the measured window
    rinse = [engine.submit(prompt, sp) for _ in range(2)]
    while any(r.finished_at is None and r.error is None for r in rinse):
        time.sleep(0.005)
    # the pipelined loop may still hold one dispatched pass whose
    # collect would land in the reset stats — let it settle first
    settle = time.time() + 5
    while engine._pending and time.time() < settle:
        time.sleep(0.01)
    engine.stats = {k: 0 if isinstance(v, int) else 0.0
                    for k, v in engine.stats.items()}
    t0 = time.time()
    reqs = [engine.submit(prompt, sp) for _ in range(n_requests)]
    while any(r.finished_at is None and r.error is None for r in reqs):
        time.sleep(0.005)
    wall = time.time() - t0
    stats = dict(engine.stats)
    engine.stop()
    ok = [r for r in reqs if r.error is None]
    toks = sum(len(r.generated) for r in ok)
    ttfts = sorted(r.ttft_ms for r in ok if r.ttft_ms is not None)
    flops = 2.0 * n_params * ((toks - len(ok)) + len(ok) * prompt_len)
    decode_s = stats["decode_s"]
    decode_toks = toks - len(ok)
    # roofline: in pure decode the pass streams all params once per
    # K-step x batch tokens — the bound this point is judged against.
    # Weight-only int8 halves the streamed bytes (int4 quarters them).
    point_bytes = param_bytes * {"int8": 0.5, "int4": 0.25}.get(
        quantize, 1.0)
    roof_toks = (hbm * 1e9) / (point_bytes / max_batch) if hbm else None
    point = {
        "layout": layout, "paged_attention": paged_attention,
        "quantize": quantize,
        "max_batch": max_batch, "k": k_steps,
        "n_requests": n_requests, "ok": len(ok), "wall_s": round(wall, 2),
        "tok_per_s": round(toks / wall, 1),
        "req_per_s": round(len(ok) / wall, 2),
        "p50_ttft_ms": round(statistics.median(ttfts), 1) if ttfts else -1,
        "p99_ttft_ms": round(ttfts[int(0.99 * (len(ttfts) - 1))], 1)
        if ttfts else -1,
        "mfu": round(flops / (wall * peak), 4) if peak else None,
        "decode_tok_per_s": round(decode_toks / decode_s, 1)
        if decode_s > 0 else None,
        "roofline_tok_per_s": round(roof_toks, 1) if roof_toks else None,
        "pct_of_roofline": round(100 * (toks / wall) / roof_toks, 1)
        if roof_toks else None,
        "phases": {k: (round(v, 2) if isinstance(v, float) else v)
                   for k, v in stats.items()},
    }
    points.append(point)
    print("POINT " + json.dumps(point), flush=True)
    return point


# batch sweep at K=8, slot layout (the r02 configuration, now
# pipelined); under SMOKE the clamp collapses the batches — dedupe
batches = sorted({min(mb, 4) if SMOKE else mb for mb in (16, 32, 64)})
for mb in batches:
    run_point(mb, 8, "slot")
# K sweep
for k in (16, 32):
    run_point(32, k, "slot")
# paged: gather/scatter view path vs the native ragged kernel path
run_point(32, 8, "paged", paged_attention="view")
run_point(32, 8, "paged", paged_attention="kernel")
# weight-only int8: half the HBM param traffic — the decode-roofline
# lever (ops/quant.py)
run_point(32, 8, "slot", quantize="int8")
# the best-known composition: ragged kernel reads only live KV rows,
# int8 halves the weight stream
run_point(32, 8, "paged", paged_attention="kernel", quantize="int8")
# int4: a quarter of the weight stream — the aggressive roofline point
run_point(32, 8, "slot", quantize="int4")

profile_stop(_trace_dir)
print("RESULT_JSON " + json.dumps({
    "job": "engine_sweep", "device": DEV, "n_params": n_params,
    "peak_flops": peak, "hbm_gbs": hbm, "points": points,
    "xprof_trace": _trace_dir}))
