"""TPU job: the standard bench with weight-only int8 — the quantized
headline number next to 01's bf16 baseline."""
import os
import runpy
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, _REPO)
# shared persistent compile cache for the bench children (jax-free
# resolve — this wrapper, like bench's parent, never imports jax)
from gofr_tpu.config.env import (COMPILE_CACHE_ENV,
                                 resolve_compile_cache_dir)

os.environ.setdefault(COMPILE_CACHE_ENV,
                      resolve_compile_cache_dir() or "off")
os.environ["GOFR_BENCH_PLATFORM"] = "tpu"
os.environ["GOFR_BENCH_QUANT"] = "int8"
runpy.run_path(os.path.join(_REPO, "bench.py"), run_name="__main__")
