"""TPU job: the standard bench with weight-only int8 — the quantized
headline number next to 01's bf16 baseline."""
import os
import runpy

os.environ["GOFR_BENCH_PLATFORM"] = "tpu"
os.environ["GOFR_BENCH_QUANT"] = "int8"
runpy.run_path(os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "bench.py"), run_name="__main__")
