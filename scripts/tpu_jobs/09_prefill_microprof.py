"""TPU job: decompose chunk-prefill time on real hardware.

PR 2 moved chunked prefill / prefix reattach / speculative verify off
the gather_view dense round-trip onto the ragged paged chunk kernel
(ops/paged_attention.paged_chunk_attention). This job measures, on a
real chip, (a) the bare chunk-attention kernel against the XLA gather
reference at several history lengths, and (b) the full model-level
chunk step: native paged (pages written/read in place) vs the view
path (gather whole allocation -> dense chunk -> scatter back). The
view path's cost is O(pool allocation) per chunk; the kernel's is
O(history + chunk) — the gap is what TTFT for long prompts buys.
One JSON line.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax
import jax.numpy as jnp

SMOKE = os.environ.get("GOFR_JOB_SMOKE") == "1"
if SMOKE:
    jax.config.update("jax_platforms", "cpu")
if not SMOKE:
    assert jax.default_backend() != "cpu", "TPU job ran on CPU"

from gofr_tpu.config.env import enable_compile_cache
enable_compile_cache()

from gofr_tpu.models.llama import (LlamaConfig, llama_init,
                                   llama_prefill_chunk,
                                   llama_prefill_chunk_paged)
from gofr_tpu.ops.paged_attention import (paged_chunk_attention_pallas,
                                          paged_chunk_attention_xla)
from gofr_tpu.ops.paged_kv import gather_view, scatter_decode

out = {"job": "prefill_microprof", "backend": jax.default_backend(),
       "device": jax.devices()[0].device_kind}

# GOFR_JOB_PROFILE=1: xprof capture of the whole measured region
from _profiling import profile_start, profile_stop
_trace_dir = profile_start("prefill_microprof")

c = LlamaConfig.tiny() if SMOKE else LlamaConfig.llama3_1b().scaled(
    max_seq=1024)
B = 2 if SMOKE else 8
PAGE = 16 if SMOKE else 64
MAX_SEQ = 128 if SMOKE else 1024
CHUNK = 16 if SMOKE else 256
REPS = 2 if SMOKE else 20
IMPL = "interpret" if SMOKE else "pallas"

params = llama_init(jax.random.key(0), c)
jax.block_until_ready(params)


def timed(fn, *args, reps=REPS):
    r = fn(*args)
    jax.block_until_ready(r)
    walls = []
    for _ in range(reps):
        t0 = time.perf_counter()
        r = fn(*args)
        jax.block_until_ready(r)
        walls.append(time.perf_counter() - t0)
    walls.sort()
    return walls[len(walls) // 2]


# ---- pool + tables sized to the full per-slot allocation
mp = MAX_SEQ // PAGE
n_pages = B * mp
hd = c.head_dim
kp = jnp.zeros((c.n_layers, c.n_kv_heads, n_pages, PAGE, hd), c.dtype)
vp = jnp.zeros_like(kp)
tables = jnp.arange(B * mp, dtype=jnp.int32).reshape(B, mp)
tokens = jnp.ones((B, CHUNK), jnp.int32)
chunk_lens = jnp.full((B,), CHUNK, jnp.int32)

# ---- 1) bare chunk-attention kernel vs the XLA gather reference at
# several history depths (one layer's pool)
kp1 = jnp.zeros((c.n_kv_heads, n_pages, PAGE, hd), c.dtype)
vp1 = jnp.zeros_like(kp1)
q = jnp.ones((B, CHUNK, c.n_heads, hd), c.dtype)
for hist in (0, MAX_SEQ // 4, MAX_SEQ - CHUNK):
    hl = jnp.full((B,), hist, jnp.int32)
    k_fn = jax.jit(lambda q, k, v, t, h, cl: paged_chunk_attention_pallas(
        q, k, v, t, h, cl, interpret=SMOKE))
    x_fn = jax.jit(paged_chunk_attention_xla)
    out[f"kernel_attn_h{hist}_ms"] = round(
        timed(k_fn, q, kp1, vp1, tables, hl, chunk_lens) * 1e3, 3)
    out[f"xla_attn_h{hist}_ms"] = round(
        timed(x_fn, q, kp1, vp1, tables, hl, chunk_lens) * 1e3, 3)

# ---- 2) full model chunk step: native paged vs view round trip
offsets = jnp.full((B,), MAX_SEQ - CHUNK, jnp.int32)  # worst-case hist


def native_step(params, tokens, kp, vp, tables, offsets, chunk_lens):
    return llama_prefill_chunk_paged(params, tokens, kp, vp, tables,
                                     offsets, chunk_lens, c,
                                     implementation=IMPL)


def view_step(params, tokens, kp, vp, tables, offsets, chunk_lens):
    k_view = gather_view(kp, tables)
    v_view = gather_view(vp, tables)
    logits, k_view, v_view = llama_prefill_chunk(
        params, tokens, k_view, v_view, offsets, chunk_lens, c,
        implementation="xla")
    # the scatter owns the pool dtype (quantize-on-write for int8)
    kp = scatter_decode(kp, tables, k_view, offsets, tokens.shape[1])
    vp = scatter_decode(vp, tables, v_view, offsets, tokens.shape[1])
    return logits, kp, vp


def timed_donated(fn, kp, vp, reps=REPS):
    jfn = jax.jit(fn, donate_argnums=(2, 3))
    logits, kp, vp = jfn(params, tokens, kp, vp, tables, offsets,
                         chunk_lens)
    jax.block_until_ready(logits)
    walls = []
    for _ in range(reps):
        t0 = time.perf_counter()
        logits, kp, vp = jfn(params, tokens, kp, vp, tables, offsets,
                             chunk_lens)
        jax.block_until_ready(logits)
        walls.append(time.perf_counter() - t0)
    walls.sort()
    return walls[len(walls) // 2]


t_native = timed_donated(native_step, kp, vp)
out["native_chunk_step_ms"] = round(t_native * 1e3, 2)
out["native_chunk_tok_per_s"] = round(B * CHUNK / t_native, 1)
kp = jnp.zeros((c.n_layers, c.n_kv_heads, n_pages, PAGE, hd), c.dtype)
vp = jnp.zeros_like(kp)
t_view = timed_donated(view_step, kp, vp)
out["view_chunk_step_ms"] = round(t_view * 1e3, 2)
out["view_chunk_tok_per_s"] = round(B * CHUNK / t_view, 1)
out["native_vs_view_speedup"] = round(t_view / t_native, 3)
out["config"] = (f"B={B} chunk={CHUNK} max_seq={MAX_SEQ} "
                 f"page={PAGE} impl={IMPL}")

profile_stop(_trace_dir)
out["xprof_trace"] = _trace_dir
print(json.dumps(out))
