"""TPU job: long-context serving — prompts far beyond the widest
prefill bucket walk the chunked-prefill path against the growing
cache; measures prefill throughput, TTFT, and decode rate at 2k-token
contexts for the slot layout and the paged layout (ragged kernel).
One JSON line.
"""

import json
import os
import sys

# jobs run as `python scripts/tpu_queue/<job>.py` — put the repo root
# (three levels up) on sys.path so gofr_tpu resolves standalone
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))
import statistics
import time

import jax

SMOKE = os.environ.get("GOFR_JOB_SMOKE") == "1"
if SMOKE:
    # the env var alone does not beat the axon plugin
    jax.config.update("jax_platforms", "cpu")
if not SMOKE:
    assert jax.default_backend() != "cpu", "TPU job ran on CPU"

# shared persistent XLA compile cache: this job's warmup compiles
# amortize across every child in the round (config/env.py)
from gofr_tpu.config.env import enable_compile_cache
enable_compile_cache()

from gofr_tpu.models.llama import LlamaConfig, llama_init
from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import llama_engine

if SMOKE:
    config = LlamaConfig.tiny().scaled(max_seq=256)
    PROMPT_LEN, GEN, N_REQ, MB = 96, 8, 4, 2
    BUCKETS = (32,)
else:
    config = LlamaConfig.llama3_1b().scaled(max_seq=4096)
    PROMPT_LEN, GEN, N_REQ, MB = 2048, 32, 8, 8
    BUCKETS = (256, 512)

params = llama_init(jax.random.key(0), config)
jax.block_until_ready(params)
points = []


def run_point(layout, paged_attention="auto"):
    if SMOKE and paged_attention == "kernel":
        paged_attention = "interpret"
    eng_cfg = EngineConfig(
        max_batch=MB, max_seq=config.max_seq, prefill_buckets=BUCKETS,
        seed=0, kv_layout=layout, page_size=16 if SMOKE else 64,
        paged_attention=paged_attention,
        prefill_chunks_per_pass=2)
    engine = llama_engine(params, config, eng_cfg)
    engine.warmup(prompt_lens=(max(BUCKETS),), chunked=True)
    engine.start()
    sp = SamplingParams(temperature=0.0, max_new_tokens=GEN)

    def prompt(i):
        # distinct LEADING token per request: the prefix cache cannot
        # hit, so the paged point measures the chunk walk itself
        return [201 + i] + [1 + (j % 200) for j in range(PROMPT_LEN - 1)]
    rinse = engine.submit(prompt(98), sp)
    while rinse.finished_at is None and rinse.error is None:
        time.sleep(0.005)
    settle = time.time() + 5
    while engine._pending and time.time() < settle:
        time.sleep(0.01)
    engine.stats = {k: 0 if isinstance(v, int) else 0.0
                    for k, v in engine.stats.items()}
    t0 = time.time()
    reqs = [engine.submit(prompt(i), sp) for i in range(N_REQ)]
    while any(r.finished_at is None and r.error is None for r in reqs):
        time.sleep(0.005)
    wall = time.time() - t0
    stats = dict(engine.stats)
    engine.stop()
    ok = [r for r in reqs if r.error is None]
    ttfts = sorted(r.ttft_ms for r in ok if r.ttft_ms is not None)
    prefill_tokens = len(ok) * PROMPT_LEN
    point = {
        "layout": layout, "paged_attention": paged_attention,
        "prompt_len": PROMPT_LEN, "ok": len(ok),
        "wall_s": round(wall, 2),
        "prefill_tok_per_s": round(
            prefill_tokens / stats["prefill_s"], 1)
        if stats["prefill_s"] > 0 else None,
        "prefill_calls": stats["prefill_calls"],
        "p50_ttft_ms": round(statistics.median(ttfts), 1) if ttfts else -1,
        "gen_tok_per_s": round(
            sum(len(r.generated) for r in ok) / wall, 1),
        "phases": {k: (round(v, 2) if isinstance(v, float) else v)
                   for k, v in stats.items()},
    }
    points.append(point)
    print("POINT " + json.dumps(point), flush=True)


run_point("slot")
run_point("paged", paged_attention="kernel")

print("RESULT_JSON " + json.dumps({
    "job": "long_context", "device": jax.devices()[0].device_kind,
    "points": points}))
