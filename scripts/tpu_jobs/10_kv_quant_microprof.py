"""TPU job: int8 vs bf16 KV page DMA bandwidth in the ragged kernels.

The quantized KV pool (EngineConfig.kv_dtype="int8") stores pages as
int8 codes + per-row f32 scales and dequantizes in-register after each
per-page DMA — per history row the kernels move hd+4 bytes instead of
2*hd. This job measures, on a real chip, the bare ragged decode and
chunk kernels over a bf16 pool vs the SAME values quantized to int8:
median step time at several history depths, the implied HBM read
bandwidth for the KV stream, and the realized speedup against the 1.88x
byte-ratio roofline (hd=64). Numbers feed the kv_capacity bench
scenario's tok/s story: capacity is guaranteed by arithmetic, the DMA
win is what this job checks. One JSON line.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax
import jax.numpy as jnp

SMOKE = os.environ.get("GOFR_JOB_SMOKE") == "1"
if SMOKE:
    jax.config.update("jax_platforms", "cpu")
if not SMOKE:
    assert jax.default_backend() != "cpu", "TPU job ran on CPU"

from gofr_tpu.config.env import enable_compile_cache
enable_compile_cache()

from gofr_tpu.models.llama import LlamaConfig
from gofr_tpu.ops.paged_attention import (paged_chunk_attention_pallas,
                                          paged_decode_attention_pallas)
from gofr_tpu.ops.paged_kv import quantize_pool

out = {"job": "kv_quant_microprof", "backend": jax.default_backend(),
       "device": jax.devices()[0].device_kind}

# GOFR_JOB_PROFILE=1: xprof capture of the whole measured region
from _profiling import profile_start, profile_stop
_trace_dir = profile_start("kv_quant_microprof")

c = LlamaConfig.tiny() if SMOKE else LlamaConfig.llama3_1b().scaled(
    max_seq=2048)
B = 2 if SMOKE else 16
# int8 pages need page % 32 == 0 on the compiled path; interpret
# (smoke) is unconstrained
PAGE = 16 if SMOKE else 64
MAX_SEQ = 128 if SMOKE else 2048
CHUNK = 16 if SMOKE else 256
REPS = 2 if SMOKE else 20
hd = c.head_dim


def timed(fn, *args, reps=REPS):
    r = fn(*args)
    jax.block_until_ready(r)
    walls = []
    for _ in range(reps):
        t0 = time.perf_counter()
        r = fn(*args)
        jax.block_until_ready(r)
        walls.append(time.perf_counter() - t0)
    walls.sort()
    return walls[len(walls) // 2]


# ---- one layer's pool, every slot's table pointing at distinct pages
mp = MAX_SEQ // PAGE
n_pages = B * mp
key = jax.random.key(0)
kk, kv, kq = jax.random.split(key, 3)
kp = jax.random.normal(kk, (c.n_kv_heads, n_pages, PAGE, hd), jnp.bfloat16)
vp = jax.random.normal(kv, (c.n_kv_heads, n_pages, PAGE, hd), jnp.bfloat16)
kp8, vp8 = quantize_pool(kp), quantize_pool(vp)
tables = jnp.arange(B * mp, dtype=jnp.int32).reshape(B, mp)

# per-row KV bytes each kernel DMAs (K + V): the roofline the measured
# speedup chases
row_bytes_bf16 = 2 * c.n_kv_heads * hd * 2
row_bytes_int8 = 2 * c.n_kv_heads * (hd + 4)
out["row_bytes_bf16"] = row_bytes_bf16
out["row_bytes_int8"] = row_bytes_int8
out["dma_byte_ratio"] = round(row_bytes_bf16 / row_bytes_int8, 3)

# ---- 1) ragged decode kernel: one query row reads the whole history
q1 = jax.random.normal(kq, (B, c.n_heads, hd), jnp.bfloat16)
dec = jax.jit(lambda q, k, v, t, ln: paged_decode_attention_pallas(
    q, k, v, t, ln, interpret=SMOKE))
for hist in (MAX_SEQ // 4, MAX_SEQ):
    lens = jnp.full((B,), hist, jnp.int32)
    t_b = timed(dec, q1, kp, vp, tables, lens)
    t_i = timed(dec, q1, kp8, vp8, tables, lens)
    out[f"decode_bf16_h{hist}_ms"] = round(t_b * 1e3, 3)
    out[f"decode_int8_h{hist}_ms"] = round(t_i * 1e3, 3)
    out[f"decode_speedup_h{hist}"] = round(t_b / t_i, 3)
    # KV-stream read bandwidth implied by the step time
    out[f"decode_bf16_h{hist}_gbs"] = round(
        B * hist * row_bytes_bf16 / t_b / 1e9, 2)
    out[f"decode_int8_h{hist}_gbs"] = round(
        B * hist * row_bytes_int8 / t_i / 1e9, 2)

# ---- 2) ragged chunk kernel at worst-case history
qc = jax.random.normal(kq, (B, CHUNK, c.n_heads, hd), jnp.bfloat16)
hist = MAX_SEQ - CHUNK
hl = jnp.full((B,), hist, jnp.int32)
cl = jnp.full((B,), CHUNK, jnp.int32)
chk = jax.jit(lambda q, k, v, t, h, l: paged_chunk_attention_pallas(
    q, k, v, t, h, l, interpret=SMOKE))
t_b = timed(chk, qc, kp, vp, tables, hl, cl)
t_i = timed(chk, qc, kp8, vp8, tables, hl, cl)
out["chunk_bf16_ms"] = round(t_b * 1e3, 3)
out["chunk_int8_ms"] = round(t_i * 1e3, 3)
out["chunk_speedup"] = round(t_b / t_i, 3)

out["config"] = (f"B={B} hkv={c.n_kv_heads} hd={hd} page={PAGE} "
                 f"max_seq={MAX_SEQ} chunk={CHUNK} "
                 f"impl={'interpret' if SMOKE else 'pallas'}")

profile_stop(_trace_dir)
out["xprof_trace"] = _trace_dir
print(json.dumps(out))
