"""TPU job: measure speculative decoding + prefix cache (VERDICT r3 #6).

Workload: repeated system prompt + greedy generation (the regime both
features exist for). Reports acceptance rate, tokens/pass, tok/s and
TTFT deltas vs vanilla, on the real chip. One JSON line.
"""

import json
import os
import sys

# jobs run as `python scripts/tpu_queue/<job>.py` — put the repo root
# (three levels up) on sys.path so gofr_tpu resolves standalone
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))
import statistics
import time

import jax

SMOKE = os.environ.get("GOFR_JOB_SMOKE") == "1"
if SMOKE:
    # the env var alone does not beat the axon plugin
    jax.config.update("jax_platforms", "cpu")
if not SMOKE:
    assert jax.default_backend() != "cpu", "TPU job ran on CPU"

# shared persistent XLA compile cache: this job's warmup compiles
# amortize across every child in the round (config/env.py)
from gofr_tpu.config.env import enable_compile_cache
enable_compile_cache()

from gofr_tpu.models.llama import LlamaConfig, llama_init
from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import llama_engine

config = LlamaConfig.tiny() if SMOKE \
    else LlamaConfig.llama3_1b().scaled(max_seq=1024)
params = llama_init(jax.random.key(0), config)
jax.block_until_ready(params)

# shared REPETITIVE system prompt + per-request suffix, greedy — the
# regime both features exist for: prefix caching shares the system
# prompt's KV, and prompt-lookup drafting thrives on repetition
PATTERN = [11, 22, 33, 44, 55, 66, 77, 88]
SYSTEM = PATTERN * (4 if SMOKE else 32)
N_REQ, GEN = (8, 16) if SMOKE else (32, 64)


def run(name, suffix=True, **cfg_kw):
    """``suffix=False`` keeps every prompt purely repetitive — the
    spec scenario needs the prompt TAIL to recur earlier so
    prompt-lookup can draft; a unique per-request suffix would break
    exactly that. Prefix scenarios keep suffixes (shared system
    prompt, distinct continuations — the cache's use case)."""
    eng_cfg = EngineConfig(
        max_batch=4 if SMOKE else 16, max_seq=config.max_seq,
        prefill_buckets=(16, 64) if SMOKE else (64, 128, 256, 512),
        seed=0, **cfg_kw)
    engine = llama_engine(params, config, eng_cfg)
    engine.warmup(prompt_lens=(len(SYSTEM) + 4,),
                  chunked=eng_cfg.kv_layout == "paged")
    engine.start()
    sp = SamplingParams(temperature=0.0, max_new_tokens=GEN)

    def prompt(i):
        return SYSTEM + ([100 + i, 7, 3] if suffix else [])
    # rinse: one sub-batch end-to-end so stragglers of lazy compilation
    # (spec verify graph, chunk-with-history) are out of the window
    rinse = [engine.submit(prompt(98), sp) for _ in range(2)]
    while any(r.finished_at is None and r.error is None for r in rinse):
        time.sleep(0.005)
    # the pipelined loop may still hold one dispatched pass whose
    # collect would land in the reset stats — let it settle first
    settle = time.time() + 5
    while engine._pending and time.time() < settle:
        time.sleep(0.01)
    engine.stats = {k: 0 if isinstance(v, int) else 0.0
                    for k, v in engine.stats.items()}
    t0 = time.time()
    reqs = [engine.submit(prompt(i), sp) for i in range(N_REQ)]
    while any(r.finished_at is None and r.error is None for r in reqs):
        time.sleep(0.005)
    wall = time.time() - t0
    stats = dict(engine.stats)
    engine.stop()
    ok = [r for r in reqs if r.error is None]
    toks = sum(len(r.generated) for r in ok)
    ttfts = sorted(r.ttft_ms for r in ok if r.ttft_ms is not None)
    out = {
        "scenario": name, "ok": len(ok), "wall_s": round(wall, 2),
        "tok_per_s": round(toks / wall, 1),
        "p50_ttft_ms": round(statistics.median(ttfts), 1) if ttfts else -1,
        "spec_passes": stats.get("spec_passes", 0),
        "spec_accepted": stats.get("spec_accepted", 0),
        "decode_passes": stats.get("decode_passes", 0),
        "prefix_hits": stats.get("prefix_hits", 0),
        "prefill_calls": stats.get("prefill_calls", 0),
    }
    if out["spec_passes"]:
        # per-ROW metrics: spec_passes counts BATCHED passes (G rows
        # each), so passes-based denominators overstated both numbers
        # by the rows per pass (the r5 TPU artifact showed 6.33)
        rows = stats.get("spec_rows", 0)
        drafted = stats.get("spec_drafted", 0)
        # accepted drafts + the one bonus token each row-verify emits
        out["tokens_per_verify"] = round(
            (out["spec_accepted"] + rows) / rows, 2) if rows else None
        out["acceptance_rate"] = round(
            out["spec_accepted"] / drafted, 3) if drafted else None
    print("POINT " + json.dumps(out), flush=True)
    return out


PG = 16 if SMOKE else 64
results = [
    run("vanilla_repetitive", kv_layout="slot", suffix=False),
    run("speculative", kv_layout="slot", speculative=True,
        suffix=False),
    run("vanilla_slot", kv_layout="slot"),
    run("paged_prefix_cache", kv_layout="paged", page_size=PG,
        prefix_cache=True),
    run("paged_no_prefix", kv_layout="paged", page_size=PG,
        prefix_cache=False),
]
print("RESULT_JSON " + json.dumps({
    "job": "spec_prefix", "device": jax.devices()[0].device_kind,
    "scenarios": results}))
