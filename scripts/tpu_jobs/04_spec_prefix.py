"""TPU job: measure speculative decoding + prefix cache (VERDICT r3 #6).

Workload: repeated system prompt + greedy generation (the regime both
features exist for). Reports acceptance rate, tokens/pass, tok/s and
TTFT deltas vs vanilla, on the real chip. One JSON line.
"""

import json
import statistics
import time

import jax

assert jax.default_backend() != "cpu", "TPU job ran on CPU"

from gofr_tpu.models.llama import LlamaConfig, llama_init
from gofr_tpu.serving.engine import EngineConfig, SamplingParams
from gofr_tpu.serving.glue import llama_engine

config = LlamaConfig.llama3_1b().scaled(max_seq=1024)
params = llama_init(jax.random.key(0), config)
jax.block_until_ready(params)

SYSTEM = list(range(1, 257))          # 256-token shared system prompt
N_REQ, GEN = 32, 64


def run(name, **cfg_kw):
    eng_cfg = EngineConfig(max_batch=16, max_seq=config.max_seq,
                           prefill_buckets=(64, 128, 256, 512), seed=0,
                           **cfg_kw)
    engine = llama_engine(params, config, eng_cfg)
    engine.warmup(prompt_lens=(320,))
    engine.start()
    engine.stats = {k: 0 if isinstance(v, int) else 0.0
                    for k, v in engine.stats.items()}
    sp = SamplingParams(temperature=0.0, max_new_tokens=GEN)
    t0 = time.time()
    reqs = [engine.submit(SYSTEM + [1000 + i, 7, 3], sp)
            for i in range(N_REQ)]
    while any(r.finished_at is None and r.error is None for r in reqs):
        time.sleep(0.005)
    wall = time.time() - t0
    stats = dict(engine.stats)
    engine.stop()
    ok = [r for r in reqs if r.error is None]
    toks = sum(len(r.generated) for r in ok)
    ttfts = sorted(r.ttft_ms for r in ok if r.ttft_ms is not None)
    out = {
        "scenario": name, "ok": len(ok), "wall_s": round(wall, 2),
        "tok_per_s": round(toks / wall, 1),
        "p50_ttft_ms": round(statistics.median(ttfts), 1) if ttfts else -1,
        "spec_passes": stats.get("spec_passes", 0),
        "spec_accepted": stats.get("spec_accepted", 0),
        "decode_passes": stats.get("decode_passes", 0),
        "prefix_hits": stats.get("prefix_hits", 0),
        "prefill_calls": stats.get("prefill_calls", 0),
    }
    if out["spec_passes"]:
        # accepted drafts + the always-emitted bonus token per pass
        out["tokens_per_spec_pass"] = round(
            (out["spec_accepted"] + out["spec_passes"])
            / out["spec_passes"], 2)
        out["acceptance_rate"] = round(
            out["spec_accepted"]
            / (out["spec_passes"] * eng_cfg.spec_draft), 3)
    print("POINT " + json.dumps(out), flush=True)
    return out


results = [
    run("vanilla_slot", kv_layout="slot"),
    run("speculative", kv_layout="slot", speculative=True),
    run("paged_prefix_cache", kv_layout="paged", page_size=64,
        prefix_cache=True),
    run("paged_no_prefix", kv_layout="paged", page_size=64,
        prefix_cache=False),
]
print("RESULT_JSON " + json.dumps({
    "job": "spec_prefix", "device": jax.devices()[0].device_kind,
    "scenarios": results}))
