"""TPU job: decompose the decode-pass time budget on real hardware.

The r5 sweep measured ~790 tok/s at batch 16 on the 1B config vs a
~5,300 tok/s HBM roofline (15%). This job isolates where the other
85% goes: raw achievable HBM bandwidth, the bare jitted decode step,
the K-step scan wrapper, attention's share (full-pass vs no-attention
model), sampling, and the head matmul. One JSON line.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

import jax
import jax.numpy as jnp

SMOKE = os.environ.get("GOFR_JOB_SMOKE") == "1"
if SMOKE:
    jax.config.update("jax_platforms", "cpu")
if not SMOKE:
    assert jax.default_backend() != "cpu", "TPU job ran on CPU"

# shared persistent XLA compile cache: this job's warmup compiles
# amortize across every child in the round (config/env.py)
from gofr_tpu.config.env import enable_compile_cache
enable_compile_cache()

from gofr_tpu.models.llama import (LlamaConfig, llama_init, make_empty_cache,
                                   llama_decode_step, param_count)

out = {"job": "decode_microprof", "backend": jax.default_backend(),
       "device": jax.devices()[0].device_kind}

# GOFR_JOB_PROFILE=1: xprof capture of the whole measured region
from _profiling import profile_start, profile_stop
_trace_dir = profile_start("decode_microprof")

c = LlamaConfig.tiny() if SMOKE else LlamaConfig.llama3_1b().scaled(
    max_seq=1024)
B = 4 if SMOKE else 16
REPS = 2 if SMOKE else 20

params = llama_init(jax.random.key(0), c)
jax.block_until_ready(params)
n_params = param_count(params)
out["n_params"] = n_params


def timed(fn, *args, reps=REPS, donate=None):
    """Median wall of reps calls (post-warmup), seconds."""
    r = fn(*args)
    jax.block_until_ready(r)
    walls = []
    for _ in range(reps):
        t0 = time.perf_counter()
        r = fn(*args)
        jax.block_until_ready(r)
        walls.append(time.perf_counter() - t0)
    walls.sort()
    return walls[len(walls) // 2]


# ---- 1) achievable HBM bandwidth: stream ~the param bytes through a
# trivially fusable reduction (sum of a big bf16 buffer)
big = jnp.ones((max(1, n_params // (1 << 20)), 1 << 20), jnp.bfloat16)
bw_fn = jax.jit(lambda x: jnp.sum(x, dtype=jnp.float32))
t = timed(bw_fn, big)
stream_bytes = big.size * 2
out["hbm_stream_gbps"] = round(stream_bytes / t / 1e9, 1)

def timed_donated(step_fn, kc, vc, reps=REPS):
    """Median wall of a donated-cache decode step: the caches thread
    through each call (donation invalidates the previous buffers), so
    the generic timed() helper cannot be used."""
    logits, kc, vc = step_fn(params, tokens, kc, vc, lengths)
    jax.block_until_ready(logits)
    walls = []
    for _ in range(reps):
        t0 = time.perf_counter()
        logits, kc, vc = step_fn(params, tokens, kc, vc, lengths)
        jax.block_until_ready(logits)
        walls.append(time.perf_counter() - t0)
    walls.sort()
    return walls[len(walls) // 2]


# ---- 2) bare decode step (one token, no scan, no sampling)
kc, vc = make_empty_cache(c, B)
lengths = jnp.full((B,), 64 if not SMOKE else 8, jnp.int32)
tokens = jnp.full((B,), 5, jnp.int32)

step = jax.jit(lambda p, t_, k, v, l: llama_decode_step(p, t_, k, v, l, c),
               donate_argnums=(2, 3))
t_step = timed_donated(step, kc, vc)
out["bare_step_ms"] = round(t_step * 1e3, 2)
out["bare_step_tok_per_s"] = round(B / t_step, 1)
out["bare_step_pct_roofline"] = round(
    100 * (2.0 * n_params / out["hbm_stream_gbps"] / 1e9) / t_step, 1)

# ---- 3) no-attention model: same matmul chain, attention replaced by
# identity — isolates attention + cache traffic share
from gofr_tpu.models.llama import rms_norm, qmatmul, _mlp_block


def noattn_step(p, tok, l):
    x = jnp.take(p["embed"], tok, axis=0)[:, None, :].astype(c.dtype)

    def layer_fn(carry, lp):
        x, live = carry
        h = rms_norm(x, lp["attn_norm"], c.norm_eps)
        q = qmatmul(h, lp["wq"])
        k = qmatmul(h, lp["wk"])
        v = qmatmul(h, lp["wv"])
        # q/k/v folded into the carried scalar so XLA cannot DCE the
        # projections; attention itself is replaced by identity
        live = live + jnp.sum(q) + jnp.sum(k) + jnp.sum(v)
        x = x + qmatmul(h, lp["wo"])
        x = x + _mlp_block(x, lp, c)
        return (x, live), None

    (x, live), _ = jax.lax.scan(
        layer_fn, (x, jnp.zeros((), jnp.float32)), p["layers"])
    head = p.get("lm_head")
    logits = (qmatmul(x, p["embed"].T.astype(c.dtype)) if head is None
              else qmatmul(x, head))
    return logits + live.astype(logits.dtype)


na = jax.jit(noattn_step)
t_na = timed(na, params, tokens, lengths)
out["noattn_step_ms"] = round(t_na * 1e3, 2)

# ---- 4) head matmul alone (the [B, D] x [D, V] vocab projection)
x = jnp.ones((B, 1, c.dim), c.dtype)
head_w = params.get("lm_head")
if head_w is None:
    head_fn = jax.jit(lambda x, p: qmatmul(x, p["embed"].T.astype(c.dtype)))
    t_head = timed(head_fn, x, params)
else:
    head_fn = jax.jit(lambda x, w: qmatmul(x, w))
    t_head = timed(head_fn, x, head_w)
out["head_matmul_ms"] = round(t_head * 1e3, 2)

# ---- 4b) prefill: [P, 64] last-logit prefill — compute-bound at
# these shapes (1024 rows -> ~1024 flops/byte, over the MXU ridge),
# so time here vs the ~13 ms ideal is kernel/layout overhead
from gofr_tpu.models.llama import llama_prefill_last

for p_rows in ((2,) if SMOKE else (8, 16)):
    toks = jnp.ones((p_rows, 16 if SMOKE else 64), jnp.int32)
    lens = jnp.full((p_rows,), toks.shape[1], jnp.int32)
    pf = jax.jit(lambda pr, t, l: llama_prefill_last(pr, t, c,
                                                     kv_lengths=l))
    t_pf = timed(pf, params, toks, lens)
    out[f"prefill_{p_rows}x{toks.shape[1]}_ms"] = round(t_pf * 1e3, 2)

# ---- 5) sampling: all-greedy batches take _sample_batch's lax.cond
# argmax fast path; one sampled row forces the vocab-wide top_k branch
from gofr_tpu.serving.engine import _sample_batch

lg = jnp.ones((B, c.vocab_size), jnp.float32)
argmax_fn = jax.jit(lambda l: jnp.argmax(l, axis=-1))
out["argmax_ms"] = round(timed(argmax_fn, lg) * 1e3, 2)
topk_fn = jax.jit(lambda l: jax.lax.top_k(l, 64)[1])
out["topk64_ms"] = round(timed(topk_fn, lg) * 1e3, 2)
tps = jnp.ones((B,), jnp.float32)
tks = jnp.zeros((B,), jnp.int32)
greedy_t = jnp.zeros((B,), jnp.float32)
mixed_t = greedy_t.at[0].set(0.7)
samp_fn = jax.jit(lambda l, k, t: _sample_batch(l, k, t, tps, tks))
out["sample_greedy_ms"] = round(
    timed(samp_fn, lg, jax.random.key(0), greedy_t) * 1e3, 2)
out["sample_mixed_ms"] = round(
    timed(samp_fn, lg, jax.random.key(0), mixed_t) * 1e3, 2)

# ---- 6) padded-attention share: same step against a short cache
if not SMOKE:
    c_short = LlamaConfig.llama3_1b().scaled(max_seq=256)
    kc_s, vc_s = make_empty_cache(c_short, B)
    step_s = jax.jit(
        lambda p, t_, k, v, l: llama_decode_step(p, t_, k, v, l, c_short),
        donate_argnums=(2, 3))
    out["bare_step_seq256_ms"] = round(
        timed_donated(step_s, kc_s, vc_s) * 1e3, 2)

profile_stop(_trace_dir)
out["xprof_trace"] = _trace_dir
print(json.dumps(out))
