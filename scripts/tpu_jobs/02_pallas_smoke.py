"""TPU job: prove the Pallas kernels on real hardware (VERDICT r3 #3/#4).

Runs the flash prefill-attention kernel and the ragged paged
decode-attention kernel compiled on the TPU, checks numerics against
the XLA references on-chip, and times both. Prints one JSON line.
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np

assert jax.default_backend() != "cpu", "TPU job ran on CPU"
out = {"job": "pallas_smoke", "backend": jax.default_backend(),
       "device": jax.devices()[0].device_kind}

# ---- flash prefill attention (ops/flash_attention.py) on-chip
from gofr_tpu.ops.attention import xla_attention
from gofr_tpu.ops.flash_attention import flash_attention

B, S, HQ, HKV, D = 4, 1024, 32, 8, 64
ks = jax.random.split(jax.random.key(0), 3)
q = jax.random.normal(ks[0], (B, S, HQ, D), jnp.bfloat16)
k = jax.random.normal(ks[1], (B, S, HKV, D), jnp.bfloat16)
v = jax.random.normal(ks[2], (B, S, HKV, D), jnp.bfloat16)
lens = jnp.asarray([S, S // 2, 100, 7], jnp.int32)

flash = jax.jit(lambda q, k, v: flash_attention(q, k, v, kv_lengths=lens))
ref = jax.jit(lambda q, k, v: xla_attention(q, k, v, causal=True,
                                            kv_lengths=lens))
got = np.asarray(flash(q, k, v), np.float32)
want = np.asarray(ref(q, k, v), np.float32)
# bf16 inputs: compare loosely; mask rows past each kv length
err = np.abs(got - want).max()
out["flash_max_abs_err"] = float(err)
out["flash_ok"] = bool(err < 0.1)

for fn, name in ((flash, "flash_ms"), (ref, "xla_prefill_ms")):
    r = fn(q, k, v)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(10):
        r = fn(q, k, v)
    jax.block_until_ready(r)
    out[name] = round((time.perf_counter() - t0) / 10 * 1e3, 3)

# ---- ragged paged decode attention on-chip
from gofr_tpu.ops.paged_attention import (paged_decode_attention_pallas,
                                          paged_decode_attention_xla)

NP_, PG, MP = 512, 64, 16
B2 = 16
kp = jax.random.normal(ks[0], (NP_, PG, HKV, D), jnp.bfloat16)
vp = jax.random.normal(ks[1], (NP_, PG, HKV, D), jnp.bfloat16)
q2 = jax.random.normal(ks[2], (B2, HQ, D), jnp.bfloat16)
rng = np.random.default_rng(0)
tables = np.full((B2, MP), NP_, np.int32)
lengths = rng.integers(1, MP * PG, B2).astype(np.int32)
for i, ln in enumerate(lengths):
    need = -(-int(ln) // PG)
    tables[i, :need] = rng.choice(NP_, size=need, replace=False)
tables = jnp.asarray(tables)
lengths_j = jnp.asarray(lengths)

pag = jax.jit(lambda q, kp, vp: paged_decode_attention_pallas(
    q, kp, vp, tables, lengths_j))
ref2 = jax.jit(lambda q, kp, vp: paged_decode_attention_xla(
    q, kp, vp, tables, lengths_j))
got2 = np.asarray(pag(q2, kp, vp), np.float32)
want2 = np.asarray(ref2(q2, kp, vp), np.float32)
err2 = np.abs(got2 - want2).max()
out["paged_max_abs_err"] = float(err2)
out["paged_ok"] = bool(err2 < 0.1)

for fn, name in ((pag, "paged_kernel_ms"), (ref2, "paged_gather_ms")):
    r = fn(q2, kp, vp)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(50):
        r = fn(q2, kp, vp)
    jax.block_until_ready(r)
    out[name] = round((time.perf_counter() - t0) / 50 * 1e3, 3)

print("RESULT_JSON " + json.dumps(out))
