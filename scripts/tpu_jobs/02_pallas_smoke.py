"""TPU job: prove the Pallas kernels on real hardware (VERDICT r3 #3/#4).

Runs the flash prefill-attention kernel and the ragged paged
decode-attention kernel compiled on the TPU, checks numerics against
the XLA references on-chip, and times both. Prints one JSON line.
"""

import json
import os
import sys

# jobs run as `python scripts/tpu_queue/<job>.py` — put the repo root
# (three levels up) on sys.path so gofr_tpu resolves standalone
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))
import time

import jax
import jax.numpy as jnp
import numpy as np

# GOFR_JOB_SMOKE=1: tiny-shape CPU dry run (interpret-mode kernels) so
# the job's plumbing is proven before it spends the TPU window
SMOKE = os.environ.get("GOFR_JOB_SMOKE") == "1"
if SMOKE:
    # the env var alone does not beat the axon plugin
    jax.config.update("jax_platforms", "cpu")
if not SMOKE:
    assert jax.default_backend() != "cpu", "TPU job ran on CPU"

# shared persistent XLA compile cache: this job's warmup compiles
# amortize across every child in the round (config/env.py)
from gofr_tpu.config.env import enable_compile_cache
enable_compile_cache()
out = {"job": "pallas_smoke", "backend": jax.default_backend(),
       "device": jax.devices()[0].device_kind}

# ---- flash prefill attention (ops/flash_attention.py) on-chip
from gofr_tpu.ops.attention import xla_attention
from gofr_tpu.ops.flash_attention import flash_attention

B, S, HQ, HKV, D = (2, 128, 4, 2, 16) if SMOKE else (4, 1024, 32, 8, 64)
dtype = jnp.float32 if SMOKE else jnp.bfloat16
ks = jax.random.split(jax.random.key(0), 3)
q = jax.random.normal(ks[0], (B, S, HQ, D), dtype)
k = jax.random.normal(ks[1], (B, S, HKV, D), dtype)
v = jax.random.normal(ks[2], (B, S, HKV, D), dtype)
lens = jnp.asarray(([S, 7] if SMOKE else [S, S // 2, 100, 7]),
                   jnp.int32)

flash = jax.jit(lambda q, k, v: flash_attention(
    q, k, v, kv_lengths=lens, interpret=SMOKE))
ref = jax.jit(lambda q, k, v: xla_attention(q, k, v, causal=True,
                                            kv_lengths=lens))
got = np.asarray(flash(q, k, v), np.float32)
want = np.asarray(ref(q, k, v), np.float32)
# bf16 inputs: compare loosely; mask rows past each kv length
err = np.abs(got - want).max()
out["flash_max_abs_err"] = float(err)
out["flash_ok"] = bool(err < 0.1)

REPS = 1 if SMOKE else 10
for fn, name in ((flash, "flash_ms"), (ref, "xla_prefill_ms")):
    r = fn(q, k, v)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(REPS):
        r = fn(q, k, v)
    jax.block_until_ready(r)
    out[name] = round((time.perf_counter() - t0) / REPS * 1e3, 3)

# ---- ragged paged decode attention on-chip
from gofr_tpu.ops.paged_attention import (paged_decode_attention_pallas,
                                          paged_decode_attention_xla)

NP_, PG, MP = (16, 16, 4) if SMOKE else (512, 64, 16)
B2 = 2 if SMOKE else 16
# head-major pool [Hkv, Np, pg, hd] (ops/paged_kv.py r5 re-layout)
kp = jax.random.normal(ks[0], (HKV, NP_, PG, D), dtype)
vp = jax.random.normal(ks[1], (HKV, NP_, PG, D), dtype)
q2 = jax.random.normal(ks[2], (B2, HQ, D), dtype)
rng = np.random.default_rng(0)
tables = np.full((B2, MP), NP_, np.int32)
lengths = rng.integers(1, MP * PG, B2).astype(np.int32)
for i, ln in enumerate(lengths):
    need = -(-int(ln) // PG)
    tables[i, :need] = rng.choice(NP_, size=need, replace=False)
tables = jnp.asarray(tables)
lengths_j = jnp.asarray(lengths)

pag = jax.jit(lambda q, kp, vp: paged_decode_attention_pallas(
    q, kp, vp, tables, lengths_j, interpret=SMOKE))
ref2 = jax.jit(lambda q, kp, vp: paged_decode_attention_xla(
    q, kp, vp, tables, lengths_j))
got2 = np.asarray(pag(q2, kp, vp), np.float32)
want2 = np.asarray(ref2(q2, kp, vp), np.float32)
err2 = np.abs(got2 - want2).max()
out["paged_max_abs_err"] = float(err2)
out["paged_ok"] = bool(err2 < 0.1)

REPS2 = 1 if SMOKE else 50
for fn, name in ((pag, "paged_kernel_ms"), (ref2, "paged_gather_ms")):
    r = fn(q2, kp, vp)
    jax.block_until_ready(r)
    t0 = time.perf_counter()
    for _ in range(REPS2):
        r = fn(q2, kp, vp)
    jax.block_until_ready(r)
    out[name] = round((time.perf_counter() - t0) / REPS2 * 1e3, 3)

print("RESULT_JSON " + json.dumps(out))
