"""Per-phase profiling of the serving decode path on the real chip.

Times each component of the engine hot loop separately so perf work is
aimed at measured cost, not guesses:
  - prefill (bucket 64, batch 1)  [current engine shape]
  - decode pass (K steps fused, batch 16)
  - sampling alone (full-vocab sort vs lax.top_k path)
  - LM head alone (f32 vs bf16)
  - decode_attention alone (f32 upcast vs bf16)
"""
from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from gofr_tpu.models.llama import (LlamaConfig, llama_decode_step, llama_init,
                                   llama_prefill, make_empty_cache)
from gofr_tpu.serving.engine import _sample_batch

B, S, PROMPT = 16, 1024, 64
c = LlamaConfig.llama3_1b().scaled(max_seq=S)
params = llama_init(jax.random.key(0), c)
jax.block_until_ready(params)
print(f"backend={jax.default_backend()}", file=sys.stderr)


def bench(label, fn, *args, n=20, **kw):
    t0 = time.perf_counter()
    out = fn(*args, **kw)       # compile
    jax.block_until_ready(out)
    print(f"# compiled {label} in {time.perf_counter()-t0:.1f}s",
          file=sys.stderr, flush=True)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{label:46s} {dt*1e3:9.2f} ms", flush=True)
    return dt


# ---- prefill, current engine shape (batch 1, bucket 64)
tokens1 = jnp.ones((1, PROMPT), jnp.int32)
kvlen1 = jnp.array([PROMPT], jnp.int32)
pf = jax.jit(lambda p, t, l: llama_prefill(p, t, c, kv_lengths=l))
bench("prefill b=1 s=64 (full logits out)", pf, params, tokens1, kvlen1, n=5)

# prefill returning only last-position logits (what engine needs)
pf_last = jax.jit(
    lambda p, t, l: (llama_prefill(p, t, c, kv_lengths=l)[0][0, l[0] - 1],))
bench("prefill b=1 s=64 (last logits only)", pf_last, params, tokens1, kvlen1, n=5)

# batched prefill
tokens8 = jnp.ones((8, PROMPT), jnp.int32)
kvlen8 = jnp.full((8,), PROMPT, jnp.int32)
pf8 = jax.jit(lambda p, t, l: llama_prefill(p, t, c, kv_lengths=l))
bench("prefill b=8 s=64 (full logits out)", pf8, params, tokens8, kvlen8, n=5)

# ---- decode step
kc, vc = make_empty_cache(c, B, S)
lengths = jnp.full((B,), PROMPT, jnp.int32)
toks = jnp.ones((B,), jnp.int32)
dec = jax.jit(lambda p, t, k, v, l: llama_decode_step(p, t, k, v, l, c))
out = dec(params, toks, kc, vc, lengths)
jax.block_until_ready(out)
logits, kc, vc = out
t0 = time.perf_counter()
N = 20
for _ in range(N):
    logits, kc, vc = dec(params, toks, kc, vc, lengths)
jax.block_until_ready(logits)
dt = (time.perf_counter() - t0) / N
print(f"{'decode step b=16 (logits out, no sample)':46s} {dt*1e3:9.2f} ms")

# ---- sampling alone on [B, V] logits
key = jax.random.key(1)
temps = jnp.full((B,), 0.7, jnp.float32)
top_ps = jnp.full((B,), 0.9, jnp.float32)
top_ks = jnp.full((B,), 40, jnp.int32)
samp_sort = jax.jit(lambda lg, k: _sample_batch(lg, k, temps, top_ps, top_ks))
bench("sample full-vocab sort [16,128256]", samp_sort, logits, key)


def sample_topk(logits, key):
    logits = logits.astype(jnp.float32)
    vals, idx = jax.lax.top_k(logits, 64)
    scaled = vals / jnp.maximum(temps, 1e-6)[:, None]
    kth = jnp.clip(top_ks - 1, 0, 63)
    thr = jnp.take_along_axis(scaled, kth[:, None], axis=-1)
    scaled = jnp.where((top_ks[:, None] > 0) & (scaled < thr), -1e30, scaled)
    probs = jax.nn.softmax(scaled, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = jnp.roll(cum, 1, axis=-1) < top_ps[:, None]
    keep = keep.at[..., 0].set(True)
    filt = jnp.where(keep, scaled, -1e30)
    g = -jnp.log(-jnp.log(jax.random.uniform(key, filt.shape, minval=1e-20)))
    choice = jnp.argmax(filt + g, axis=-1)
    samp = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0]
    greedy = idx[:, 0]
    return jnp.where(temps <= 0.0, greedy, samp).astype(jnp.int32)


bench("sample lax.top_k(64) path", jax.jit(sample_topk), logits, key)

# ---- LM head alone
x = jnp.ones((B, c.dim), jnp.bfloat16)
head = params["embed"]
f32head = jax.jit(lambda x, h: x.astype(jnp.float32) @ h.T.astype(jnp.float32))
bench("lm head f32 x f32 [16,2048]x[2048,128256]", f32head, x, head)
bf16head = jax.jit(lambda x, h: jnp.einsum(
    "bd,vd->bv", x, h, preferred_element_type=jnp.float32))
bench("lm head bf16 (f32 accum)", bf16head, x, head)

# ---- decode attention alone (one layer's worth, cache slice)
from gofr_tpu.ops.attention import decode_attention
q = jnp.ones((B, 1, c.n_heads, c.head_dim), jnp.bfloat16)
kc1 = jnp.ones((B, S, c.n_kv_heads, c.head_dim), jnp.bfloat16)
vc1 = jnp.ones((B, S, c.n_kv_heads, c.head_dim), jnp.bfloat16)
bench("decode_attention 1 layer (f32 upcast)",
      jax.jit(lambda q, k, v: decode_attention(q, k, v, lengths)), q, kc1, vc1)


def decode_attn_bf16(q, k_cache, v_cache, kv_lengths):
    b, sq, hq, d = q.shape
    _, smax, hkv, _ = k_cache.shape
    group = hq // hkv
    scale = d ** -0.5
    qr = q.reshape(b, sq, hkv, group, d)
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qr, k_cache,
                        preferred_element_type=jnp.float32) * scale
    mask = jnp.arange(smax)[None, :] < kv_lengths[:, None]
    logits = jnp.where(mask[:, None, None, None, :], logits, -1e30)
    w = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhgqk,bkhd->bqhgd", w.astype(jnp.bfloat16), v_cache,
                     preferred_element_type=jnp.float32)
    return out.reshape(b, sq, hq, d).astype(q.dtype)


bench("decode_attention 1 layer (bf16 einsum)",
      jax.jit(lambda q, k, v: decode_attn_bf16(q, k, v, lengths)), q, kc1, vc1)

# ---- dispatch overhead: trivial jitted fn round-trip
triv = jax.jit(lambda x: x + 1)
bench("trivial dispatch round-trip", triv, jnp.zeros((16,), jnp.int32), n=50)
