"""CI smoke: the flight data recorder reconstructs a leader failover.

Boots two leader candidates (rank 0 active, rank 1 standby) with a
tuned event-ledger config (short incident window, no debounce) plus an
engine worker, then drills the observability story the ledger exists
for — a 3am incident an operator reconstructs from ONE endpoint:

1. **Kill the leader mid-traffic.** The worker's missed-ack walk
   elects the standby (epoch 2); the new leader's ``IncidentDetector``
   opens EXACTLY ONE ``failover`` bundle, and the bundle's
   ``trace_id`` resolves to a real span in the leader's in-memory
   exporter — the takeover join RPC that elected it.
2. **A stale epoch is fenced.** ``stale_epoch_replay`` is injected on
   the new leader: its next heartbeat ack carries ``epoch - 1``, the
   worker-side fence refuses it (``fleet.fence_reject``), re-discovers
   and rejoins — and the reject event rides the worker's next
   heartbeat digest into the leader's merged timeline.
3. **A crashing worker recovers.** A late-joining worker with an
   injected pass crash and a restart budget serves one request:
   ``engine.restart``/``engine.recovery`` land on its local ledger and
   federate the same way.
4. **One endpoint tells the whole story.** ``GET /debug/fleet/events``
   on the surviving leader yields a merged timeline spanning >= 3
   hosts with ``fleet.failover`` < ``fleet.fence_reject`` <
   ``engine.recovery`` in causal order; ``GET /debug/fleet/incidents``
   lists the single sealed bundle, complete with timeline, state
   snapshots and config/git digests.

Exits nonzero on any failure; one line per check on success.
"""

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from gofr_tpu.serving.control_plane import FleetConfig
from gofr_tpu.serving.engine import EngineConfig, RestartPolicy
from gofr_tpu.serving.events import EventLedgerConfig, parse_events
from gofr_tpu.serving.faults import FaultPlan
from gofr_tpu.serving.glue import demo_llama_engine
from gofr_tpu.serving.router import RouterConfig
from gofr_tpu.serving.tokenizer import ByteTokenizer
from router_smoke import AppThread, chat, make_app, request

SYSTEM = "You are the gofr-tpu events smoke. Answer in one line. "
HEARTBEAT = 0.5
LEDGER = dict(incident_window_s=3.0, incident_debounce_s=0.0)


def boot_leader(name, rank):
    app = make_app(name)
    leader = app.serve_fleet_leader(
        host_id=name, rank=rank,
        fleet=FleetConfig(),
        router=RouterConfig(max_retries=2, affinity_size=64),
        heartbeat_interval_s=HEARTBEAT,
        events=EventLedgerConfig(**LEDGER))
    return app, leader, AppThread(app).start()


def boot_worker(name, urls, *, engine_kw=None):
    app = make_app(name)
    engine = demo_llama_engine(EngineConfig(
        max_batch=4, max_seq=256, kv_layout="paged", page_size=8,
        prefill_buckets=(8,), seed=5, **(engine_kw or {})))
    app.serve_model("llm", engine, ByteTokenizer())
    app.join_fleet(urls[0], host_id=name,
                   heartbeat_interval_s=HEARTBEAT,
                   fleet=FleetConfig(leader_candidates=urls,
                                     missed_acks_before_failover=1))
    return app, engine, AppThread(app).start()


def fleet_timeline(port, **params):
    query = "&".join(f"{k}={v}" for k, v in params.items())
    path = "/debug/fleet/events" + (f"?{query}" if query else "")
    status, _, data = request(port, "GET", path)
    assert status == 200, (status, data[:200])
    _header, events = parse_events(data.decode())
    return events


def wait_for(predicate, what, deadline_s=30, interval=0.1):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        value = predicate()
        if value:
            return value
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


def main() -> int:
    app0, leader0, thread0 = boot_leader("ev-leader0", 0)
    app1, leader1, thread1 = boot_leader("ev-leader1", 1)
    urls = (f"http://127.0.0.1:{thread0.port}",
            f"http://127.0.0.1:{thread1.port}")
    for lead in (leader0, leader1):
        lead.fleet.leader_candidates = urls

    _w0app, _w0eng, w0thread = boot_worker("ev-w0", urls)
    w1thread = None
    try:
        wait_for(lambda: len(leader0.routing_view()) == 1
                 and all(m["address"] for m in leader0.routing_view()),
                 "worker to become routable")
        print("ok: rank-0 leader active at epoch 1, worker routable")

        # --------------------- phase 1: kill the leader mid-traffic
        stream_result = {}

        def run_stream():
            try:
                stream_result["response"] = chat(
                    thread0.port, SYSTEM + "ev stream", max_tokens=48,
                    stream=True)
            except Exception as exc:  # died with the leader
                stream_result["error"] = exc

        stream_thread = threading.Thread(target=run_stream)
        stream_thread.start()
        time.sleep(0.05)
        thread0.stop()
        t_down = time.time()
        wait_for(lambda: leader1.leadership()["active"],
                 "standby takeover")
        assert leader1.epoch == 2, leader1.epoch
        stream_thread.join(30)
        print(f"ok: standby took over in {time.time() - t_down:.2f}s "
              "at epoch 2")

        # exactly ONE incident bundle, reason=failover, on the
        # survivor's fleet surface
        status, _, data = request(thread1.port, "GET",
                                  "/debug/fleet/incidents")
        assert status == 200, (status, data[:200])
        incidents = json.loads(data)["data"]["incidents"]
        assert len(incidents) == 1, incidents
        meta = incidents[0]
        assert meta["reason"] == "failover", meta
        print("ok: exactly one incident bundle, reason=failover")

        # ...whose trace_id resolves to a span the new leader actually
        # exported — the takeover join RPC that elected it
        trace_id = meta["trace_id"]
        assert trace_id, f"failover bundle carries no trace_id: {meta}"
        exporter = app1.container.tracer.exporter
        wait_for(lambda: any(s.trace_id == trace_id
                             for s in exporter.spans),
                 "the failover trace to appear in the span exporter")
        span_names = sorted({s.name for s in exporter.spans
                             if s.trace_id == trace_id})
        print(f"ok: bundle trace_id {trace_id[:8]}... resolves to "
              f"exported spans {span_names}")

        # ------------------- phase 2: stale epoch ack gets fenced
        wait_for(lambda: len(leader1.routing_view()) == 1,
                 "worker to rejoin the new leader")
        leader1.faults = FaultPlan.parse("stale_epoch_replay:at=1")
        wait_for(lambda: any(e["kind"] == "fleet.fence_reject"
                             for e in fleet_timeline(thread1.port)),
                 "fence_reject to federate into the fleet timeline")
        print("ok: injected stale ack fenced by the worker; "
              "fleet.fence_reject federated over heartbeats")

        # ------------- phase 3: crashing worker restarts + recovers
        _w1app, w1eng, w1thread = boot_worker(
            "ev-w1", (urls[1],),
            engine_kw=dict(
                faults="pass_raise:at=3",
                restart_policy=RestartPolicy(max_restarts=3,
                                             backoff_s=0.02)))
        status, _, data = chat(w1thread.port, SYSTEM + "ev crash",
                               max_tokens=12)
        assert status == 201, (status, data[:200])
        assert w1eng.events.snapshot(kind="engine.recovery"), \
            "crash did not leave an engine.recovery event"
        wait_for(lambda: any(e["kind"] == "engine.recovery"
                             for e in fleet_timeline(thread1.port)),
                 "engine.recovery to federate into the fleet timeline")
        print("ok: injected pass crash salvaged within the restart "
              "budget; engine.restart/recovery federated")

        # ---------------- phase 4: one endpoint, the whole story
        timeline = fleet_timeline(thread1.port)
        hosts = {e["host"] for e in timeline if e.get("host")}
        assert len(hosts) >= 3, f"timeline spans only {sorted(hosts)}"
        firsts = {}
        for event in timeline:  # already skew-corrected + sorted
            firsts.setdefault(event["kind"], event["ts"])
        order = ("fleet.failover", "fleet.fence_reject",
                 "engine.recovery")
        for kind in order:
            assert kind in firsts, (kind, sorted(firsts))
        assert firsts[order[0]] < firsts[order[1]] < firsts[order[2]], \
            {k: firsts[k] for k in order}
        print(f"ok: merged timeline spans {len(hosts)} hosts and "
              "orders failover < fence_reject < recovery")

        # the bundle sealed itself once its window passed, and it is
        # complete: merged timeline, state snapshots, config + git
        wait_for(lambda: time.time() >
                 meta["ts"] + LEDGER["incident_window_s"] + 0.1,
                 "the incident window to pass", interval=0.05)
        status, _, data = request(
            thread1.port, "GET",
            f"/debug/fleet/incidents?id={meta['id']}")
        assert status == 200, (status, data[:200])
        bundle = json.loads(data)["data"]
        assert bundle["sealed"] is True, bundle["id"]
        assert any(e["kind"] == "fleet.failover"
                   for e in bundle["timeline"]), "timeline lost the " \
            "failover that opened the bundle"
        for key in ("state", "git", "ledger"):
            assert bundle.get(key), f"bundle missing {key}"
        print(f"ok: bundle {bundle['id']} sealed with "
              f"{len(bundle['timeline'])} timeline events, "
              f"{len(bundle['state'])} state snapshots, git digest")
    except AssertionError as exc:
        print(f"FAIL: {exc}", file=sys.stderr)
        return 1
    finally:
        w0thread.stop()
        if w1thread is not None:
            w1thread.stop()
        thread1.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
