"""Replay a captured workload file through a local engine.

Usage:
    python scripts/replay.py WORKLOAD.jsonl [--speed N] [--closed-loop C]
                             [--seed S] [--max-batch B] [--max-seq L]
                             [--events EVENTS.jsonl]
                             [--report OUT.json] [--no-fail]

Downloads from a live server land here:
    curl -s http://host:8000/debug/workload > incident.jsonl
    curl -s http://host:8000/debug/events   > incident-events.jsonl
    python scripts/replay.py incident.jsonl --events incident-events.jsonl

Builds the demo tiny-llama engine (the same model family the CPU
smokes and tests use) with the workload header's ``engine_seed``
unless ``--seed`` overrides it, re-injects the workload with original
inter-arrival timing (``--speed N`` compresses it, ``--closed-loop C``
ignores timing and keeps C in flight), and prints the divergence +
latency report JSON. Greedy requests must replay bit-identically when
the engine matches the capture (same model weights/config/seed);
exits 2 on any divergence unless ``--no-fail``.

For a production model, call ``gofr_tpu.serving.replay.replay_file``
against your own engine instead — the driver is model-agnostic.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("workload", help="workload JSONL file "
                    "(GET /debug/workload)")
    ap.add_argument("--speed", type=float, default=1.0,
                    help="inter-arrival compression factor (default 1)")
    ap.add_argument("--closed-loop", type=int, default=0, metavar="C",
                    help="ignore timing; keep C requests in flight")
    ap.add_argument("--seed", type=int, default=None,
                    help="override the header's engine_seed")
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--max-seq", type=int, default=256)
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--events", default=None, metavar="EVENTS.jsonl",
                    help="event-ledger capture recorded alongside the "
                         "workload (GET /debug/events); the report "
                         "gains an event-timeline diff")
    ap.add_argument("--report", default=None,
                    help="also write the report JSON to this path")
    ap.add_argument("--no-fail", action="store_true",
                    help="exit 0 even when streams diverged")
    args = ap.parse_args()

    from gofr_tpu.serving.engine import EngineConfig
    from gofr_tpu.serving.glue import demo_llama_engine
    from gofr_tpu.serving.replay import (load_events, load_workload,
                                         replay_workload)

    workload = load_workload(args.workload)
    events = load_events(args.events) if args.events else None
    header = workload["header"]
    seed = args.seed if args.seed is not None \
        else header.get("engine_seed")
    print(f"# workload: {len(workload['records'])} records, "
          f"engine_seed={header.get('engine_seed')}, "
          f"redacted={header.get('redacted')}", file=sys.stderr)
    engine = demo_llama_engine(EngineConfig(
        max_batch=args.max_batch, max_seq=args.max_seq,
        seed=seed if seed is not None else 0))
    try:
        report = replay_workload(engine, workload, speed=args.speed,
                                 closed_loop=args.closed_loop,
                                 timeout_s=args.timeout, events=events)
    finally:
        engine.stop()
    text = json.dumps(report, indent=2, default=str)
    print(text)
    if args.report:
        with open(args.report, "w") as f:
            f.write(text + "\n")
    for div in report.get("efficiency_divergence") or []:
        # advisory, not an exit condition: replay hardware/config may
        # legitimately differ — but a doubled waste share is worth a
        # line even when every token matched
        print(f"# EFFICIENCY DIVERGED: {div['cause']} waste share "
              f"{div['recorded_share']:.1%} -> "
              f"{div['replayed_share']:.1%}", file=sys.stderr)
    for div in report.get("cost_divergence") or []:
        # advisory too: replay hardware legitimately differs from the
        # capture host, but a single signature's pass cost doubling
        # while the rest hold is a kernel regression with a name
        print(f"# COST DIVERGED: {div['signature']} mean pass "
              f"{div['recorded_mean_s'] * 1e3:.3f}ms -> "
              f"{div['replayed_mean_s'] * 1e3:.3f}ms "
              f"(x{div['ratio']})", file=sys.stderr)
    for div in report.get("digest_divergence") or []:
        # advisory like the others — NEVER an exit condition: a digest
        # mismatch with matching tokens means the fingerprint inputs
        # drifted (params quantization, digest version), which would
        # make golden probes sealed from this capture misfire
        match = "tokens matched" if div.get("tokens_match") \
            else "tokens also diverged"
        print(f"# DIGEST DIVERGED: request {div['index']} "
              f"{div['recorded'][:12]}... -> {div['replayed'][:12]}... "
              f"({match})", file=sys.stderr)
    ev_div = report.get("event_divergence")
    if ev_div and ev_div.get("diverged"):
        # advisory like the efficiency diff: replay timing legitimately
        # shifts some events, but a new kind (engine.restart where the
        # capture had none) deserves a line even when tokens matched
        for kind in ev_div.get("kinds_extra") or []:
            print(f"# EVENTS DIVERGED: replay emitted {kind} the "
                  "capture never saw", file=sys.stderr)
        for kind in ev_div.get("kinds_missing") or []:
            print(f"# EVENTS DIVERGED: capture's {kind} never fired "
                  "in replay", file=sys.stderr)
        for kind, cnt in (ev_div.get("count_divergence") or {}).items():
            print(f"# EVENTS DIVERGED: {kind} x{cnt['recorded']} -> "
                  f"x{cnt['replayed']}", file=sys.stderr)
    if report["divergent"] and not args.no_fail:
        print(f"# DIVERGED: {report['divergent']} request(s)",
              file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
