from .errors import (
    ErrorClientClosedRequest,
    ErrorEntityAlreadyExists,
    ErrorEntityNotFound,
    ErrorInvalidParam,
    ErrorInvalidRoute,
    ErrorMissingParam,
    ErrorPanicRecovery,
    ErrorRequestTimeout,
    HTTPError,
)
from .request import HTTPRequest
from .responder import Responder
from .response import File, Partial, Raw, Redirect, Response, Template
from .router import Route, Router

__all__ = [
    "ErrorClientClosedRequest", "ErrorEntityAlreadyExists", "ErrorEntityNotFound",
    "ErrorInvalidParam", "ErrorInvalidRoute", "ErrorMissingParam",
    "ErrorPanicRecovery", "ErrorRequestTimeout", "HTTPError",
    "HTTPRequest", "Responder", "File", "Partial", "Raw", "Redirect",
    "Response", "Template", "Route", "Router",
]
