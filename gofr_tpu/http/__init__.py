from .errors import (
    ErrorClientClosedRequest,
    ErrorEntityAlreadyExists,
    ErrorEntityNotFound,
    ErrorInvalidParam,
    ErrorInvalidRoute,
    ErrorMethodNotAllowed,
    ErrorMissingParam,
    ErrorPanicRecovery,
    ErrorRequestTimeout,
    ErrorServiceUnavailable,
    HTTPError,
)
from .request import HTTPRequest
from .responder import Responder
from .response import File, Partial, Raw, Redirect, Response, Template, XML
from .router import Route, Router

__all__ = [
    "ErrorClientClosedRequest", "ErrorEntityAlreadyExists", "ErrorEntityNotFound",
    "ErrorInvalidParam", "ErrorInvalidRoute", "ErrorMissingParam",
    "ErrorMethodNotAllowed", "ErrorPanicRecovery", "ErrorRequestTimeout",
    "ErrorServiceUnavailable", "HTTPError",
    "HTTPRequest", "Responder", "File", "Partial", "Raw", "Redirect",
    "Response", "Template", "XML", "Route", "Router",
]
