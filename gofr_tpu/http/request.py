"""The transport-independent Request abstraction + HTTP implementation.

Mirrors reference pkg/gofr/request.go:10-17: ``Request`` is what a
Context exposes regardless of transport (HTTP, CLI argv, pub/sub
message, websocket frame): ``param``/``path_param``/``bind``/
``host_name``/``params``. The HTTP implementation carries the parsed
request line, headers, query and body, with JSON / form / multipart
binding (reference http/request.go:29-181, form_data_binder.go).
"""

from __future__ import annotations

import dataclasses
import json
import types
import typing
from typing import Any, Mapping, Protocol
from urllib.parse import parse_qs, unquote, urlsplit


class Request(Protocol):
    def param(self, key: str) -> str: ...
    def path_param(self, key: str) -> str: ...
    def params(self, key: str) -> list[str]: ...
    def bind(self, target: Any = None) -> Any: ...
    def host_name(self) -> str: ...


class BindError(Exception):
    status_code = 400

    def __init__(self, message: str) -> None:
        super().__init__(message)


def _coerce(value: Any, hint: Any) -> Any:
    """Coerce a string/JSON value toward a type hint; best-effort."""
    if hint in (None, Any, typing.Any):
        return value
    origin = typing.get_origin(hint)
    if origin is typing.Union or origin is types.UnionType:
        args = [a for a in typing.get_args(hint) if a is not type(None)]
        if value is None:
            return None
        hint = args[0] if args else Any
        origin = typing.get_origin(hint)
    try:
        if hint is bool or hint == "bool":
            if isinstance(value, bool):
                return value
            return str(value).strip().lower() in ("1", "true", "yes", "on")
        if hint is int:
            return int(value)
        if hint is float:
            return float(value)
        if hint is str:
            return str(value)
        if origin in (list, tuple) and isinstance(value, (list, tuple)):
            inner = (typing.get_args(hint) or (Any,))[0]
            return [_coerce(v, inner) for v in value]
        if dataclasses.is_dataclass(hint) and isinstance(value, Mapping):
            return bind_dataclass(value, hint)
    except (TypeError, ValueError) as exc:
        raise BindError(f"cannot coerce {value!r} to {hint}: {exc}") from exc
    return value


def bind_dataclass(data: Mapping[str, Any], cls: type) -> Any:
    """Build a dataclass from a mapping, coercing field types.

    The Python analog of the reference's reflection form binder
    (http/form_data_binder.go): nested dataclasses, lists, optionals.
    Unknown keys are ignored; missing keys fall back to field defaults.
    """
    hints = typing.get_type_hints(cls)
    kwargs: dict[str, Any] = {}
    for f in dataclasses.fields(cls):
        if f.name in data:
            kwargs[f.name] = _coerce(data[f.name], hints.get(f.name, Any))
        elif (f.default is dataclasses.MISSING
              and f.default_factory is dataclasses.MISSING):
            raise BindError(f"missing required field {f.name!r}")
    return cls(**kwargs)


class HTTPRequest:
    """Parsed HTTP request implementing the Request protocol."""

    def __init__(self, method: str, target: str, headers: Mapping[str, str],
                 body: bytes = b"", path_params: Mapping[str, str] | None = None,
                 client_addr: str = "") -> None:
        self.method = method.upper()
        split = urlsplit(target)
        self.path = unquote(split.path) or "/"
        self.query = parse_qs(split.query, keep_blank_values=True)
        # header names are case-insensitive
        self.headers = {k.lower(): v for k, v in headers.items()}
        self.body = body
        self._path_params = dict(path_params or {})
        self.client_addr = client_addr

    # -- Request protocol
    def param(self, key: str) -> str:
        values = self.query.get(key)
        return values[0] if values else ""

    def params(self, key: str) -> list[str]:
        """All values for a key, splitting comma-separated entries
        (reference http/request.go Params)."""
        out: list[str] = []
        for v in self.query.get(key, []):
            out.extend(p for p in v.split(",") if p != "")
        return out

    def path_param(self, key: str) -> str:
        return self._path_params.get(key, "")

    def set_path_params(self, params: Mapping[str, str]) -> None:
        self._path_params = dict(params)

    def host_name(self) -> str:
        return self.headers.get("host", "")

    def header(self, key: str) -> str:
        return self.headers.get(key.lower(), "")

    @property
    def content_type(self) -> str:
        return self.headers.get("content-type", "").split(";")[0].strip().lower()

    # -- binding (reference http/request.go:58-79)
    def bind(self, target: Any = None) -> Any:
        ctype = self.content_type
        if ctype in ("", "application/json", "text/json"):
            if not self.body:
                data: Any = {}
            else:
                try:
                    data = json.loads(self.body)
                except json.JSONDecodeError as exc:
                    raise BindError(f"invalid JSON body: {exc}") from exc
        elif ctype in ("application/x-www-form-urlencoded",):
            parsed = parse_qs(self.body.decode("utf-8", "replace"),
                              keep_blank_values=True)
            data = {k: v[0] if len(v) == 1 else v for k, v in parsed.items()}
        elif ctype.startswith("multipart/"):
            data = self._parse_multipart()
        elif ctype in ("application/octet-stream",):
            data = self.body
        elif ctype.startswith("text/"):
            data = self.body.decode("utf-8", "replace")
        else:
            data = self.body
        if target is None:
            return data
        if dataclasses.is_dataclass(target) and isinstance(target, type):
            if not isinstance(data, Mapping):
                raise BindError(f"cannot bind {type(data).__name__} body to "
                                f"{target.__name__}")
            return bind_dataclass(data, target)
        if isinstance(target, type):
            return _coerce(data, target)
        return data

    def _parse_multipart(self) -> dict[str, Any]:
        """Minimal multipart/form-data parser: fields + file parts."""
        full = self.headers.get("content-type", "")
        boundary = None
        for piece in full.split(";"):
            piece = piece.strip()
            if piece.startswith("boundary="):
                boundary = piece[len("boundary="):].strip('"')
        if not boundary:
            raise BindError("multipart body missing boundary")
        delim = b"--" + boundary.encode()
        out: dict[str, Any] = {}
        for part in self.body.split(delim):
            part = part.strip(b"\r\n")
            if not part or part == b"--":
                continue
            if b"\r\n\r\n" in part:
                raw_headers, content = part.split(b"\r\n\r\n", 1)
            else:
                raw_headers, content = part, b""
            disposition = ""
            part_ctype = ""
            for line in raw_headers.decode("utf-8", "replace").split("\r\n"):
                low = line.lower()
                if low.startswith("content-disposition:"):
                    disposition = line.split(":", 1)[1]
                elif low.startswith("content-type:"):
                    part_ctype = line.split(":", 1)[1].strip()
            name, filename = None, None
            for attr in disposition.split(";"):
                attr = attr.strip()
                if attr.startswith("name="):
                    name = attr[5:].strip('"')
                elif attr.startswith("filename="):
                    filename = attr[9:].strip('"')
            if name is None:
                continue
            if filename is not None:
                out[name] = {"filename": filename, "content": content,
                             "content_type": part_ctype}
            else:
                out[name] = content.decode("utf-8", "replace")
        return out
