"""Response rendering: status-code policy + the ``{data, error}`` envelope.

Mirrors reference pkg/gofr/http/responder.go:17-269:
- POST -> 201, DELETE -> 204 (responder.go:133-146)
- data + error together -> 206 Partial Content (responder.go:197-199)
- Redirect: 302 for GET/HEAD, 303 otherwise (responder.go:99-110)
- typed errors supply their own status (errors.py)
- success envelope ``{"data": ...}``; error envelope
  ``{"error": {"message": ...}}`` (responder.go:248-252)
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, AsyncIterator

from .errors import status_and_level_for
from .response import (File, Partial, Raw, Redirect, Response, Stream,
                       Template, XML)


@dataclass
class ResponseData:
    status: int = 200
    headers: dict[str, str] = field(default_factory=dict)
    body: bytes = b""
    stream: AsyncIterator | None = None
    content_type: str = "application/json"


def _json_bytes(obj: Any) -> bytes:
    return json.dumps(obj, default=_default).encode()


def _default(obj: Any) -> Any:
    if hasattr(obj, "__dict__"):
        return obj.__dict__
    if hasattr(obj, "_asdict"):
        return obj._asdict()
    if hasattr(obj, "tolist"):  # numpy / jax arrays in handler results
        return obj.tolist()
    return str(obj)


class Responder:
    """Stateless renderer from handler (result, error) to ResponseData."""

    def respond(self, result: Any, error: BaseException | None,
                method: str = "GET") -> ResponseData:
        method = method.upper()

        if isinstance(result, Partial):
            error = error or result.error
            body = {"data": result.data,
                    "error": self._error_obj(error)}
            return ResponseData(status=206, body=_json_bytes(body))

        if error is not None:
            status, _ = status_and_level_for(error)
            envelope: dict[str, Any] = {"error": self._error_obj(error)}
            return ResponseData(status=status, body=_json_bytes(envelope),
                                headers=dict(getattr(error, "headers",
                                                     None) or {}))

        if isinstance(result, Redirect):
            status = 302 if method in ("GET", "HEAD") else 303
            return ResponseData(status=status, headers={"Location": result.url},
                                body=b"", content_type="text/plain")

        if isinstance(result, File):
            return ResponseData(status=200, body=result.content,
                                content_type=result.content_type)

        if isinstance(result, Template):
            return ResponseData(status=200, body=result.render().encode(),
                                content_type="text/html; charset=utf-8")

        if isinstance(result, XML):
            status = {"POST": 201}.get(method, 200)
            return ResponseData(status=status, body=result.render().encode(),
                                content_type="application/xml; charset=utf-8")

        if isinstance(result, Raw):
            status = {"POST": 201}.get(method, 200)
            return ResponseData(status=status, body=_json_bytes(result.data))

        if isinstance(result, Stream):
            return ResponseData(status=200, stream=result.iterator,
                                content_type=result.content_type)

        if isinstance(result, ResponseData):
            return result

        # plain data success path
        status = {"POST": 201, "DELETE": 204}.get(method, 200)
        if status == 204 and result is None:
            return ResponseData(status=204, body=b"")
        headers: dict[str, str] = {}
        metadata = None
        if isinstance(result, Response):
            headers = dict(result.headers)
            metadata = result.metadata
            result = result.data
        envelope = {"data": result}
        if metadata:
            envelope["metadata"] = metadata
        return ResponseData(status=status, headers=headers,
                            body=_json_bytes(envelope))

    @staticmethod
    def _error_obj(error: BaseException) -> dict[str, Any]:
        obj: dict[str, Any] = {"message": str(error) or error.__class__.__name__}
        details = getattr(error, "details", None)
        if details is not None:
            obj["details"] = details
        return obj
