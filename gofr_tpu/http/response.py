"""Rich response types a handler can return.

Mirrors reference pkg/gofr/http/response/: ``File``, ``Raw``,
``Redirect``, ``Template``, and the metadata-carrying ``Response``;
plus ``Partial`` for the data+error -> 206 policy
(reference http/responder.go:197-199).
"""

from __future__ import annotations

import mimetypes
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any


@dataclass
class Response:
    """Data plus optional metadata/headers envelope member."""

    data: Any
    metadata: dict[str, Any] | None = None
    headers: dict[str, str] = field(default_factory=dict)


@dataclass
class Raw:
    """Marshal ``data`` as JSON without the ``{"data": ...}`` envelope."""

    data: Any


@dataclass
class File:
    """Serve bytes with a content type (reference response/file.go)."""

    content: bytes
    content_type: str = "application/octet-stream"

    @classmethod
    def from_path(cls, path: str | Path) -> "File":
        p = Path(path)
        ctype = mimetypes.guess_type(str(p))[0] or "application/octet-stream"
        return cls(content=p.read_bytes(), content_type=ctype)


@dataclass
class Redirect:
    """302 for GET/HEAD, 303 for mutating methods (responder.go:99-110)."""

    url: str


@dataclass
class Template:
    """Render ``./templates/<name>`` with ``data`` via str.format-style
    ``$var`` substitution (stdlib string.Template; the reference uses
    html/template, response/template.go)."""

    name: str
    data: dict[str, Any] = field(default_factory=dict)
    directory: str = "templates"

    def render(self) -> str:
        import string
        text = (Path(self.directory) / self.name).read_text()
        return string.Template(text).safe_substitute(
            {k: str(v) for k, v in self.data.items()})


@dataclass
class Partial:
    """Data AND error together -> 206 Partial Content."""

    data: Any
    error: BaseException


@dataclass
class Stream:
    """Server-sent token stream: an async iterator of chunks.

    The TPU-native addition: ``/chat`` handlers return a ``Stream``
    whose iterator yields decoded tokens as they leave the device; the
    server writes them as SSE events (or chunked text).
    """

    iterator: Any  # AsyncIterator[str | bytes | dict]
    content_type: str = "text/event-stream"
