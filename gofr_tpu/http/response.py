"""Rich response types a handler can return.

Mirrors reference pkg/gofr/http/response/: ``File``, ``Raw``,
``Redirect``, ``Template``, and the metadata-carrying ``Response``;
plus ``Partial`` for the data+error -> 206 policy
(reference http/responder.go:197-199).
"""

from __future__ import annotations

import mimetypes
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any
from xml.sax.saxutils import escape as _xml_escape


@dataclass
class Response:
    """Data plus optional metadata/headers envelope member."""

    data: Any
    metadata: dict[str, Any] | None = None
    headers: dict[str, str] = field(default_factory=dict)


@dataclass
class Raw:
    """Marshal ``data`` as JSON without the ``{"data": ...}`` envelope."""

    data: Any


@dataclass
class File:
    """Serve bytes with a content type (reference response/file.go)."""

    content: bytes
    content_type: str = "application/octet-stream"

    @classmethod
    def from_path(cls, path: str | Path) -> "File":
        p = Path(path)
        ctype = mimetypes.guess_type(str(p))[0] or "application/octet-stream"
        return cls(content=p.read_bytes(), content_type=ctype)


@dataclass
class Redirect:
    """302 for GET/HEAD, 303 for mutating methods (responder.go:99-110)."""

    url: str


@dataclass
class Template:
    """Render ``./templates/<name>`` with ``data`` via str.format-style
    ``$var`` substitution (stdlib string.Template; the reference uses
    html/template, response/template.go)."""

    name: str
    data: dict[str, Any] = field(default_factory=dict)
    directory: str = "templates"

    def render(self) -> str:
        import string
        text = (Path(self.directory) / self.name).read_text()
        return string.Template(text).safe_substitute(
            {k: str(v) for k, v in self.data.items()})


@dataclass
class XML:
    """Marshal ``data`` as an XML document (reference response/xml.go).

    Dicts become child elements, lists repeat the ``item`` element, and
    scalars become text nodes; attribute-free by design — handlers that
    need full control return :class:`Raw` bytes with an XML content
    type instead.
    """

    data: Any
    root: str = "response"

    def render(self) -> str:
        return ('<?xml version="1.0" encoding="UTF-8"?>'
                f"{_xml_element(self.root, self.data)}")


_XML_TAG_BAD = re.compile(r"[^A-Za-z0-9_.-]")


def _xml_tag(name: str) -> str:
    """Sanitize a data-driven key into a well-formed element name.

    Keys can come from user payloads a handler echoes back; passing
    them through raw would let ``"k></x><admin>"`` inject elements.
    """
    tag = _XML_TAG_BAD.sub("_", str(name)) or "_"
    if not (tag[0].isalpha() or tag[0] == "_"):
        tag = "_" + tag
    return tag


def _xml_element(tag: str, value: Any) -> str:
    tag = _xml_tag(tag)
    if isinstance(value, dict):
        inner = "".join(_xml_element(str(k), v) for k, v in value.items())
    elif isinstance(value, (list, tuple)):
        inner = "".join(_xml_element("item", v) for v in value)
    elif value is None:
        inner = ""
    else:
        inner = _xml_escape(str(value))
    return f"<{tag}>{inner}</{tag}>"


@dataclass
class Partial:
    """Data AND error together -> 206 Partial Content."""

    data: Any
    error: BaseException


@dataclass
class Stream:
    """Server-sent token stream: an async iterator of chunks.

    The TPU-native addition: ``/chat`` handlers return a ``Stream``
    whose iterator yields decoded tokens as they leave the device; the
    server writes them as SSE events (or chunked text).
    """

    iterator: Any  # AsyncIterator[str | bytes | dict]
    content_type: str = "text/event-stream"
