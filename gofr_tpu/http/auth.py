"""Auth middleware: basic, API-key, and OAuth (JWT/JWKS) providers.

Mirrors reference pkg/gofr/http/middleware/{auth,basic_auth,
apikey_auth,oauth}.go and pkg/gofr/auth.go: a generic
``auth_middleware(provider)`` wraps the chain; providers authenticate
the request and attach auth info that surfaces as ``ctx.auth_info``
(reference context.go:121 GetAuthInfo). ``/.well-known`` paths are
exempt (reference middleware/validate.go:5-7).

OAuth validates ``Authorization: Bearer <jwt>`` tokens against a JWKS
key set, refreshed in the background (reference oauth.go:69-138); both
RS256 (via ``cryptography``) and HS256 are supported.
"""

from __future__ import annotations

import asyncio
import base64
import binascii
import hashlib
import hmac
import json
import time
from typing import Any, Awaitable, Callable, Mapping, Protocol

from .request import HTTPRequest
from .responder import ResponseData
from .server import Handler, Middleware

EXEMPT_PREFIXES = ("/.well-known/",)


class AuthProvider(Protocol):
    """Returns auth info on success, None on failure."""

    def authenticate(self, request: HTTPRequest) -> Any: ...


def credential_fingerprint(secret: str) -> str:
    """Short stable hash standing in for a raw credential anywhere it
    could be logged, traced or used as a metric label. Long enough to
    correlate a tenant across restarts, far too short to recover or
    use as the key."""
    return hashlib.sha256(secret.encode()).hexdigest()[:12]


class TenantResolver:
    """Auth principal -> bounded-cardinality tenant label.

    The accounting identity for usage metering and per-tenant metrics:
    maps whatever an auth provider attached (``ctx.auth_info``) to one
    short string safe to use as a Prometheus label. Resolution order:

    - ``tenant`` key (set by ``APIKeyAuthProvider(key_names=...)``)
    - basic-auth ``username``
    - JWT claims: the first of ``claim_keys`` (default ``org`` then
      ``sub``)
    - hashed ``api_key`` (providers already store the fingerprint,
      never the raw key)
    - anything else hashes into a ``t-<fingerprint>`` bucket; an empty
      principal is ``anonymous``.

    Cardinality is HARD-bounded: after ``max_tenants`` distinct labels
    have been seen, new ones collapse to ``other`` — a credential
    stuffing run cannot blow up the label space. Labels are
    sanitized to ``[A-Za-z0-9_.:-]`` and capped at 64 chars.
    """

    OTHER = "other"
    ANONYMOUS = "anonymous"

    def __init__(self, max_tenants: int = 256,
                 claim_keys: tuple = ("org", "sub")) -> None:
        self.max_tenants = max(1, int(max_tenants))
        self.claim_keys = tuple(claim_keys)
        self._seen: set[str] = set()
        self._lock = __import__("threading").Lock()

    @staticmethod
    def _sanitize(label: str) -> str:
        clean = "".join(c if (c.isalnum() or c in "_.:-") else "_"
                        for c in str(label))
        return clean[:64] or TenantResolver.ANONYMOUS

    def label_for(self, info: Mapping[str, Any] | None) -> str:
        """Raw label before the cardinality bound."""
        if not info:
            return self.ANONYMOUS
        if info.get("tenant"):
            return self._sanitize(info["tenant"])
        if info.get("username"):
            return self._sanitize(info["username"])
        claims = info.get("claims")
        if isinstance(claims, Mapping):
            for key in self.claim_keys:
                if claims.get(key):
                    return self._sanitize(claims[key])
        if info.get("api_key"):
            # providers store the fingerprint; label it recognizably
            return self._sanitize(f"key-{info['api_key']}")
        # unknown principal shape: a stable hashed bucket, never the
        # repr (which could leak credentials into labels)
        try:
            basis = json.dumps(info, sort_keys=True, default=str)
        except (TypeError, ValueError):
            basis = str(sorted(info))
        return f"t-{credential_fingerprint(basis)}"

    def resolve(self, info: Mapping[str, Any] | None) -> str:
        label = self.label_for(info)
        with self._lock:
            if label in self._seen:
                return label
            if len(self._seen) >= self.max_tenants:
                return self.OTHER
            self._seen.add(label)
        return label


def _unauthorized(message: str = "Unauthorized",
                  scheme: str = "Basic") -> ResponseData:
    body = json.dumps({"error": {"message": message}}).encode()
    return ResponseData(status=401, body=body,
                        headers={"WWW-Authenticate": scheme})


def is_exempt(path: str) -> bool:
    return any(path.startswith(p) for p in EXEMPT_PREFIXES)


async def run_provider(provider: AuthProvider,
                       request: HTTPRequest) -> bool:
    """Authenticate and attach auth info to the request. The single
    authority for provider semantics — the middleware chain and the
    websocket upgrade path both call this."""
    info = provider.authenticate(request)
    if asyncio.iscoroutine(info):
        info = await info
    if info is None:
        return False
    # surfaced as ctx.auth_info by the core handler
    request.auth_info = info if isinstance(info, dict) else {"auth": info}
    return True


def auth_middleware(provider: AuthProvider,
                    scheme: str = "Basic") -> Middleware:
    """Generic auth wrapper (reference middleware/auth.go:39)."""

    def mw(next_handler: Handler) -> Handler:
        async def wrapped(request: HTTPRequest) -> ResponseData:
            if is_exempt(request.path):
                return await next_handler(request)
            if not await run_provider(provider, request):
                return _unauthorized(scheme=scheme)
            return await next_handler(request)
        return wrapped
    return mw


# --------------------------------------------------------------- basic

class BasicAuthProvider:
    """Username/password table or custom validator
    (reference basic_auth.go:116)."""

    def __init__(self, users: Mapping[str, str] | None = None,
                 validator: Callable[[str, str], bool | Awaitable[bool]] | None = None) -> None:
        self.users = dict(users or {})
        self.validator = validator

    def authenticate(self, request: HTTPRequest) -> dict | None:
        header = request.header("authorization")
        if not header.startswith("Basic "):
            return None
        try:
            decoded = base64.b64decode(header[6:], validate=True).decode()
        except (binascii.Error, UnicodeDecodeError):
            return None
        username, sep, password = decoded.partition(":")
        if not sep:
            return None
        if self.validator is not None:
            result = self.validator(username, password)
            if asyncio.iscoroutine(result):
                async def check():
                    return {"username": username} if await result else None
                return check()  # type: ignore[return-value]
            return {"username": username} if result else None
        expected = self.users.get(username)
        if expected is None or not hmac.compare_digest(expected.encode(),
                                                       password.encode()):
            return None
        return {"username": username}


# ------------------------------------------------------------- api key

class APIKeyAuthProvider:
    """Static key set or custom validator (reference apikey_auth.go:89).

    Keys ride in the ``X-Api-Key`` header. The raw key NEVER reaches
    the principal: ``auth_info["api_key"]`` carries its
    :func:`credential_fingerprint`, so nothing downstream (logs,
    spans, metric labels, /debug surfaces) can leak it. An optional
    ``key_names`` mapping (key -> tenant label) additionally stamps a
    human-chosen ``tenant`` into the principal — the label the tenant
    resolver and usage ledger account under."""

    def __init__(self, keys: list[str] | None = None,
                 validator: Callable[[str], bool | Awaitable[bool]] | None = None,
                 key_names: Mapping[str, str] | None = None) -> None:
        self.keys = set(keys or []) | set(key_names or {})
        self.validator = validator
        self.key_names = dict(key_names or {})

    def _info(self, key: str) -> dict:
        info = {"api_key": credential_fingerprint(key)}
        name = self.key_names.get(key)
        if name:
            info["tenant"] = name
        return info

    def authenticate(self, request: HTTPRequest) -> dict | None:
        key = request.header("x-api-key")
        if not key:
            return None
        if self.validator is not None:
            result = self.validator(key)
            if asyncio.iscoroutine(result):
                async def check():
                    return self._info(key) if await result else None
                return check()  # type: ignore[return-value]
            return self._info(key) if result else None
        if any(hmac.compare_digest(key.encode(), k.encode())
               for k in self.keys):
            return self._info(key)
        return None


# ----------------------------------------------------------------- jwt

def _b64url_decode(segment: str) -> bytes:
    pad = "=" * (-len(segment) % 4)
    return base64.urlsafe_b64decode(segment + pad)


def _b64url_to_int(segment: str) -> int:
    return int.from_bytes(_b64url_decode(segment), "big")


class JWTError(Exception):
    pass


def _verify_rs256(signing_input: bytes, signature: bytes, key: Any) -> bool:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding
    try:
        key.verify(signature, signing_input, padding.PKCS1v15(),
                   hashes.SHA256())
        return True
    except InvalidSignature:
        return False


def jwk_to_public_key(jwk: Mapping[str, Any]) -> Any:
    """RSA JWK (n, e) -> cryptography public key
    (reference oauth.go:183 key parsing)."""
    from cryptography.hazmat.primitives.asymmetric.rsa import RSAPublicNumbers
    if jwk.get("kty") != "RSA":
        raise JWTError(f"unsupported kty {jwk.get('kty')!r}")
    n = _b64url_to_int(jwk["n"])
    e = _b64url_to_int(jwk["e"])
    return RSAPublicNumbers(e, n).public_key()


def jwt_decode(token: str) -> tuple[dict, dict, bytes, bytes]:
    """Split a compact JWT -> (header, claims, signing_input, signature)."""
    parts = token.split(".")
    if len(parts) != 3:
        raise JWTError("token is not a compact JWT")
    try:
        header = json.loads(_b64url_decode(parts[0]))
        claims = json.loads(_b64url_decode(parts[1]))
        signature = _b64url_decode(parts[2])
    except (ValueError, binascii.Error) as exc:
        raise JWTError(f"malformed token: {exc}") from exc
    signing_input = f"{parts[0]}.{parts[1]}".encode()
    return header, claims, signing_input, signature


def jwt_verify(token: str, keys: Mapping[str, Any], *,
               audience: str | None = None, issuer: str | None = None,
               leeway: float = 30.0, now: float | None = None) -> dict:
    """Verify signature + registered claims; returns the claim set.

    ``keys`` maps kid -> RSA public key (cryptography object) or
    bytes/str HS256 secret. A single key under kid ``""`` is used when
    the token has no kid.
    """
    header, claims, signing_input, signature = jwt_decode(token)
    alg = header.get("alg")
    kid = header.get("kid", "")
    key = keys.get(kid)
    if key is None and len(keys) == 1:
        key = next(iter(keys.values()))
    if key is None:
        raise JWTError(f"no key for kid {kid!r}")

    if alg == "RS256":
        if not hasattr(key, "verify"):  # str/bytes secret, raw JWK dict…
            raise JWTError("RS256 token but the key is not an RSA "
                           "public key object")
        if not _verify_rs256(signing_input, signature, key):
            raise JWTError("signature verification failed")
    elif alg == "HS256":
        # an RSA public key must never act as an HMAC secret — that is
        # the classic algorithm-confusion attack (attacker signs with
        # the PUBLIC key bytes and downgrades alg to HS256)
        if not isinstance(key, (str, bytes)):
            raise JWTError("HS256 token but the key is not a secret")
        secret = key.encode() if isinstance(key, str) else key
        expected = hmac.new(secret, signing_input, hashlib.sha256).digest()
        if not hmac.compare_digest(expected, signature):
            raise JWTError("signature verification failed")
    else:
        raise JWTError(f"unsupported alg {alg!r}")

    t = time.time() if now is None else now
    if "exp" in claims and t > float(claims["exp"]) + leeway:
        raise JWTError("token expired")
    if "nbf" in claims and t < float(claims["nbf"]) - leeway:
        raise JWTError("token not yet valid")
    if audience is not None:
        aud = claims.get("aud")
        auds = aud if isinstance(aud, list) else [aud]
        if audience not in auds:
            raise JWTError("audience mismatch")
    if issuer is not None and claims.get("iss") != issuer:
        raise JWTError("issuer mismatch")
    return claims


def jwt_sign_hs256(claims: Mapping[str, Any], secret: str | bytes,
                   headers: Mapping[str, Any] | None = None) -> str:
    """Mint an HS256 token (used by tests and service-to-service auth)."""
    secret = secret.encode() if isinstance(secret, str) else secret
    header = {"alg": "HS256", "typ": "JWT", **(headers or {})}

    def enc(obj: Mapping[str, Any]) -> str:
        raw = json.dumps(obj, separators=(",", ":")).encode()
        return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()

    signing_input = f"{enc(header)}.{enc(dict(claims))}"
    sig = hmac.new(secret, signing_input.encode(), hashlib.sha256).digest()
    return signing_input + "." + base64.urlsafe_b64encode(sig).rstrip(b"=").decode()


class OAuthProvider:
    """Bearer-JWT validation against a JWKS set
    (reference oauth.go:69-138).

    Keys come from a ``jwks_url`` (refreshed at most every
    ``refresh_interval`` seconds, fetched lazily on demand — the
    background-goroutine analog without a dedicated thread), from a
    static ``jwks`` document, or from explicit ``keys``.
    """

    FAILURE_BACKOFF = 30.0

    def __init__(self, jwks_url: str | None = None, *,
                 jwks: Mapping[str, Any] | None = None,
                 keys: Mapping[str, Any] | None = None,
                 refresh_interval: float = 300.0,
                 audience: str | None = None, issuer: str | None = None,
                 logger: Any = None) -> None:
        self.jwks_url = jwks_url
        self.refresh_interval = refresh_interval
        self.audience = audience
        self.issuer = issuer
        self.logger = logger
        self._keys: dict[str, Any] = dict(keys or {})
        self._fetched_at = 0.0
        self._refresh_lock = __import__("threading").Lock()
        self._refreshing = False
        if jwks is not None:
            self._load_jwks(jwks)
            self._fetched_at = time.time()

    def _load_jwks(self, document: Mapping[str, Any]) -> None:
        for jwk in document.get("keys", []):
            try:
                self._keys[jwk.get("kid", "")] = jwk_to_public_key(jwk)
            except (JWTError, KeyError) as exc:
                if self.logger:
                    self.logger.warn(f"skipping unusable JWK: {exc}")

    def _fetch(self) -> None:
        import urllib.request
        try:
            with urllib.request.urlopen(self.jwks_url, timeout=5) as resp:
                self._load_jwks(json.loads(resp.read()))
            self._fetched_at = time.time()
        except Exception as exc:
            # advance the clock so a JWKS outage retries on a backoff
            # instead of on every request
            self._fetched_at = (time.time() - self.refresh_interval
                                + self.FAILURE_BACKOFF)
            if self.logger:
                self.logger.error(f"JWKS fetch failed: {exc!r}")
        finally:
            # cross-thread flag (background refresh thread vs request
            # threads taking _refresh_lock): reset under the same lock
            # that guards the test-and-set in _refresh_if_stale
            with self._refresh_lock:
                self._refreshing = False

    def _refresh_if_stale(self) -> None:
        if self.jwks_url is None:
            return
        stale = time.time() - self._fetched_at >= self.refresh_interval
        if not stale and self._keys:
            return
        with self._refresh_lock:
            if self._refreshing:
                return
            self._refreshing = True
        if self._keys:
            # have keys: refresh in the background, keep serving
            import threading
            threading.Thread(target=self._fetch, daemon=True).start()
        else:
            # cold start: nothing to validate against, fetch inline
            self._fetch()

    def authenticate(self, request: HTTPRequest) -> dict | None:
        header = request.header("authorization")
        if not header.startswith("Bearer "):
            return None
        self._refresh_if_stale()
        try:
            claims = jwt_verify(header[7:], self._keys,
                                audience=self.audience, issuer=self.issuer)
        except JWTError as exc:
            if self.logger:
                self.logger.debug(f"JWT rejected: {exc}")
            return None
        return {"claims": claims}
