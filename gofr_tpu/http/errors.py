"""HTTP error taxonomy with status codes and log levels.

Mirrors the reference's error set (pkg/gofr/http/errors.go): each error
knows its HTTP status code and the level it should be logged at
(reference handler.go:154-178 maps errors to log levels).  Handlers
raise these; the responder turns them into the error envelope.
"""

from __future__ import annotations

from ..logging.logger import DEBUG, ERROR, INFO, WARN, Level


class HTTPError(Exception):
    """Base class: carries status_code + log_level + reason."""

    status_code: int = 500
    log_level: Level = ERROR

    def __init__(self, message: str = "", *, status_code: int | None = None,
                 details: object = None,
                 headers: dict | None = None) -> None:
        super().__init__(message or self.default_message())
        if status_code is not None:
            self.status_code = status_code
        self.details = details
        #: extra response headers the responder forwards verbatim
        #: (e.g. Retry-After on overload rejections)
        self.headers = dict(headers or {})

    def default_message(self) -> str:
        return "internal server error"

    @property
    def message(self) -> str:
        return str(self)


class ErrorEntityNotFound(HTTPError):
    status_code = 404
    log_level = INFO

    def __init__(self, name: str = "entity", value: str = "") -> None:
        super().__init__(f"No entity found with {name}: {value}" if value
                         else f"No entity found: {name}")


class ErrorEntityAlreadyExists(HTTPError):
    status_code = 409
    log_level = WARN

    def default_message(self) -> str:
        return "entity already exists"


class ErrorInvalidParam(HTTPError):
    status_code = 400
    log_level = INFO

    def __init__(self, *params: str) -> None:
        names = ", ".join(params) or "unknown"
        super().__init__(f"Incorrect value for parameter: {names}")


class ErrorMissingParam(HTTPError):
    status_code = 400
    log_level = INFO

    def __init__(self, *params: str) -> None:
        names = ", ".join(params) or "unknown"
        super().__init__(f"Parameter {names} is required")


class ErrorInvalidRoute(HTTPError):
    status_code = 404
    log_level = DEBUG

    def default_message(self) -> str:
        return "route not registered"


class ErrorMethodNotAllowed(HTTPError):
    status_code = 405
    log_level = DEBUG

    def default_message(self) -> str:
        return "method not allowed"


class ErrorRequestTimeout(HTTPError):
    status_code = 408
    log_level = INFO

    def default_message(self) -> str:
        return "request timed out"


class ErrorClientClosedRequest(HTTPError):
    status_code = 499
    log_level = DEBUG

    def default_message(self) -> str:
        return "client closed request"


class ErrorPanicRecovery(HTTPError):
    status_code = 500
    log_level = ERROR

    def default_message(self) -> str:
        return "internal server error"


class ErrorServiceUnavailable(HTTPError):
    status_code = 503
    log_level = WARN

    def default_message(self) -> str:
        return "service unavailable"


class ErrorTooManyRequests(HTTPError):
    """Per-tenant rate limit exceeded (token buckets in
    serving/scheduler.py). INFO, not WARN: a tenant hitting its own
    configured limit is the limiter working, not service distress —
    the scheduler WARNs separately when SLO-driven shedding starts."""

    status_code = 429
    log_level = INFO

    def default_message(self) -> str:
        return "too many requests"


def status_and_level_for(err: BaseException) -> tuple[int, Level]:
    """Status + log level for an arbitrary handler exception.

    Mirrors the mapping at reference handler.go:154-178: typed HTTP
    errors carry their own; unknown exceptions are 500/ERROR; objects
    with a ``status_code`` attribute (custom errors) are honored.
    """
    if isinstance(err, HTTPError):
        return err.status_code, err.log_level
    status = getattr(err, "status_code", 500)
    if not isinstance(status, int) or not (100 <= status <= 599):
        status = 500
    # client errors default to INFO (matching the taxonomy above);
    # server errors to ERROR
    level = getattr(err, "log_level", INFO if status < 500 else ERROR)
    return status, level
