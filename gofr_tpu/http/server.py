"""Asyncio HTTP/1.1 server: parsing, keep-alive, chunked streaming, upgrade.

The transport under the framework's HTTP layer — the role net/http plays
for the reference (pkg/gofr/http_server.go:36-58). Built directly on
asyncio streams so the serving hot path (continuous-batching /chat
handlers) gets an event loop we control: no thread-per-request, SSE
token streaming via chunked transfer, and a websocket upgrade hook.

The request pipeline is an onion of async middleware around a core
``handle(request) -> ResponseData`` — same order as the reference:
tracer -> logging -> CORS -> metrics -> auth -> websocket upgrade
(reference http_server.go:36-41).
"""

from __future__ import annotations

import asyncio
from typing import Awaitable, Callable

from .request import HTTPRequest
from .responder import ResponseData

MAX_HEADER_BYTES = 64 * 1024
MAX_BODY_BYTES = 64 * 1024 * 1024

Handler = Callable[[HTTPRequest], Awaitable[ResponseData]]
Middleware = Callable[[Handler], Handler]

_STATUS_REASONS = {
    200: "OK", 201: "Created", 202: "Accepted", 204: "No Content",
    206: "Partial Content", 301: "Moved Permanently", 302: "Found",
    303: "See Other", 304: "Not Modified", 400: "Bad Request",
    401: "Unauthorized", 403: "Forbidden", 404: "Not Found",
    405: "Method Not Allowed", 408: "Request Timeout", 409: "Conflict",
    413: "Payload Too Large", 426: "Upgrade Required",
    429: "Too Many Requests",
    431: "Request Header Fields Too Large",
    499: "Client Closed Request", 500: "Internal Server Error",
    501: "Not Implemented", 503: "Service Unavailable",
}


class HTTPProtocolError(Exception):
    def __init__(self, status: int, message: str) -> None:
        super().__init__(message)
        self.status = status


class StreamInterrupted(Exception):
    """A response stream iterator failed mid-flight; the connection must
    be torn down without the chunked terminator."""


async def read_request(reader: asyncio.StreamReader,
                       client_addr: str = "") -> HTTPRequest | None:
    """Parse one HTTP/1.1 request off the stream. None on clean EOF."""
    try:
        head = await reader.readuntil(b"\r\n\r\n")
    except asyncio.IncompleteReadError as exc:
        if not exc.partial:
            return None
        raise HTTPProtocolError(400, "truncated request head") from exc
    except asyncio.LimitOverrunError as exc:
        raise HTTPProtocolError(431, "headers too large") from exc
    if len(head) > MAX_HEADER_BYTES:
        raise HTTPProtocolError(431, "headers too large")

    lines = head.decode("latin-1").split("\r\n")
    request_line = lines[0]
    parts = request_line.split(" ")
    if len(parts) != 3 or not parts[2].startswith("HTTP/1"):
        raise HTTPProtocolError(400, f"malformed request line: {request_line!r}")
    method, target, _version = parts

    headers: dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        if ":" not in line:
            raise HTTPProtocolError(400, f"malformed header: {line!r}")
        key, _, value = line.partition(":")
        headers[key.strip().lower()] = value.strip()

    body = b""
    if "content-length" in headers:
        try:
            length = int(headers["content-length"])
        except ValueError as exc:
            raise HTTPProtocolError(400, "bad content-length") from exc
        if length > MAX_BODY_BYTES:
            raise HTTPProtocolError(413, "body too large")
        if length:
            body = await reader.readexactly(length)
    elif headers.get("transfer-encoding", "").lower() == "chunked":
        chunks = []
        total = 0
        while True:
            try:
                size_line = (await reader.readline()).strip()
            except ValueError as exc:  # LimitOverrunError wrapped: huge line
                raise HTTPProtocolError(400, "bad chunk framing") from exc
            try:
                size = int(size_line.split(b";")[0], 16)
            except ValueError as exc:
                raise HTTPProtocolError(400, "bad chunk size") from exc
            if size == 0:
                await reader.readline()  # trailing CRLF
                break
            total += size
            if total > MAX_BODY_BYTES:
                raise HTTPProtocolError(413, "body too large")
            chunks.append(await reader.readexactly(size))
            await reader.readexactly(2)  # chunk CRLF
        body = b"".join(chunks)

    return HTTPRequest(method=method, target=target, headers=headers,
                       body=body, client_addr=client_addr)


def _render_head(status: int, headers: dict[str, str]) -> bytes:
    reason = _STATUS_REASONS.get(status, "Unknown")
    lines = [f"HTTP/1.1 {status} {reason}"]
    lines.extend(f"{k}: {v}" for k, v in headers.items())
    return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")


async def write_response(writer: asyncio.StreamWriter, response: ResponseData,
                         *, head_only: bool = False,
                         keep_alive: bool = True) -> None:
    headers = {"Server": "gofr-tpu",
               "Connection": "keep-alive" if keep_alive else "close"}
    headers.update(response.headers)

    if response.stream is not None and not head_only:
        headers.setdefault("Content-Type", response.content_type)
        headers.setdefault("Cache-Control", "no-cache")
        headers["Transfer-Encoding"] = "chunked"
        completed = False
        try:
            # the head write sits INSIDE the try: a client that is
            # already gone fails right here, and the finally must
            # still close the producer
            writer.write(_render_head(response.status, headers))
            await writer.drain()
            async for chunk in response.stream:
                if isinstance(chunk, str):
                    chunk = chunk.encode()
                elif not isinstance(chunk, (bytes, bytearray)):
                    import json
                    chunk = (json.dumps(chunk) + "\n").encode()
                if not chunk:
                    continue
                writer.write(f"{len(chunk):x}\r\n".encode() + bytes(chunk) + b"\r\n")
                await writer.drain()
            completed = True
        except Exception as exc:
            # Do NOT send the terminal chunk: the client must see the
            # truncation instead of mistaking a partial stream for a
            # complete response.
            raise StreamInterrupted(str(exc)) from exc
        finally:
            if not completed:
                # close the iterator NOW — on errors AND cancellation
                # (server shutdown) — so stream producers (the serving
                # engine) cancel their work instead of waiting for
                # garbage collection
                closer = getattr(response.stream, "aclose", None)
                if closer is not None:
                    try:
                        await closer()
                    except BaseException:  # never mask the original
                        pass
        writer.write(b"0\r\n\r\n")
        await writer.drain()
        return

    body = b"" if (head_only or response.status == 204) else response.body
    if response.status != 204:
        headers.setdefault("Content-Type", response.content_type)
        headers["Content-Length"] = str(len(response.body))
    writer.write(_render_head(response.status, headers) + body)
    await writer.drain()


def make_ssl_context(cert_file: str, key_file: str):
    """Server-side TLS context from a PEM cert/key pair — the
    ListenAndServeTLS analog (reference pkg/gofr/http_server.go:82)."""
    import ssl
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile=cert_file, keyfile=key_file)
    return ctx


class HTTPServer:
    """Owns the listen socket and the per-connection loop. Pass
    ``ssl_context`` (see :func:`make_ssl_context`) to serve HTTPS."""

    def __init__(self, handler: Handler, *, host: str = "0.0.0.0", port: int = 8000,
                 logger=None, ssl_context=None) -> None:
        self.handler = handler
        self.host = host
        self.port = port
        self.logger = logger
        self.ssl_context = ssl_context
        self._server: asyncio.AbstractServer | None = None
        self._writers: set[asyncio.StreamWriter] = set()
        self._conn_tasks: set[asyncio.Task] = set()
        self._busy_tasks: set[asyncio.Task] = set()  # mid-request

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve_connection, self.host, self.port,
            limit=MAX_HEADER_BYTES, ssl=self.ssl_context)
        if self.logger:
            scheme = "https" if self.ssl_context else "http"
            self.logger.info(
                f"HTTP server listening on {scheme}://{self.host}:{self.port}")

    @property
    def bound_port(self) -> int:
        if self._server and self._server.sockets:
            return self._server.sockets[0].getsockname()[1]
        return self.port

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    async def shutdown(self, grace_s: float = 2.0) -> None:
        """Stop accepting; idle keep-alive connections cancel
        immediately, connections mid-request get ``grace_s`` to drain,
        and stragglers are cancelled — ``wait_closed`` on 3.12+ waits
        for EVERY connection handler, so a wedged stream would
        otherwise hang shutdown indefinitely. Cancellation lands at
        the handler's awaits, whose finally-blocks close stream
        producers (the serving engine cancels abandoned requests)."""
        if self._server is not None:
            self._server.close()
            # idle connections are parked in read_request with no work
            # in flight: nothing to drain, cancel now
            for task in list(self._conn_tasks - self._busy_tasks):
                task.cancel()
            busy = set(self._busy_tasks)
            if busy:
                await asyncio.wait(busy, timeout=grace_s)
            for task in list(self._conn_tasks):
                task.cancel()
            for writer in list(self._writers):
                try:
                    writer.close()
                except Exception:
                    pass
            try:
                # hijacked websocket transports are closed by the WS
                # manager, not tracked here — never let a straggler
                # hold wait_closed forever
                await asyncio.wait_for(self._server.wait_closed(),
                                       timeout=max(grace_s, 2.0))
            except asyncio.TimeoutError:
                if self.logger:
                    self.logger.warn(
                        "listener closed with connections still "
                        "terminating")
            self._server = None

    async def _serve_connection(self, reader: asyncio.StreamReader,
                                writer: asyncio.StreamWriter) -> None:
        peer = writer.get_extra_info("peername")
        client_addr = f"{peer[0]}:{peer[1]}" if isinstance(peer, tuple) else ""
        took_over = False
        self._writers.add(writer)
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
        try:
            while True:
                try:
                    request = await read_request(reader, client_addr)
                except HTTPProtocolError as exc:
                    import json
                    await write_response(writer, ResponseData(
                        status=exc.status,
                        body=json.dumps(
                            {"error": {"message": str(exc)}}).encode()),
                        keep_alive=False)
                    break
                if request is None:
                    break
                if task is not None:  # a request is now in flight
                    self._busy_tasks.add(task)

                if "upgrade" in request.headers.get("connection", "").lower():
                    # hand the raw socket to the chain: the innermost
                    # websocket middleware performs the handshake AFTER
                    # every other middleware (auth included) has passed
                    request.ws_reader = reader
                    request.ws_writer = writer
                try:
                    response = await self.handler(request)
                except Exception as exc:  # middleware failed catastrophically
                    if self.logger:
                        self.logger.error(f"unhandled server error: {exc!r}")
                    response = ResponseData(
                        status=500,
                        body=b'{"error": {"message": "internal server error"}}')
                if getattr(response, "hijacked", False):
                    # a websocket message loop now owns reader/writer;
                    # do not write a response or close the socket
                    took_over = True
                    return
                keep_alive = request.headers.get("connection", "").lower() != "close"
                try:
                    await write_response(writer, response,
                                         head_only=request.method == "HEAD",
                                         keep_alive=keep_alive)
                except StreamInterrupted as exc:
                    if self.logger:
                        self.logger.error(f"stream aborted mid-response: {exc}")
                    break
                finally:
                    if task is not None:  # back to idle keep-alive
                        self._busy_tasks.discard(task)
                if not keep_alive:
                    break
        except (ConnectionResetError, BrokenPipeError, asyncio.IncompleteReadError):
            pass
        finally:
            # hijacked (websocket) connections are owned by their
            # message loop now — ws_manager closes them at shutdown
            self._writers.discard(writer)
            if task is not None:
                self._conn_tasks.discard(task)
                self._busy_tasks.discard(task)
            if not took_over:
                try:
                    writer.close()
                    await writer.wait_closed()
                except (ConnectionResetError, BrokenPipeError):
                    pass


def chain(middlewares: list[Middleware], core: Handler) -> Handler:
    """Compose the middleware onion; first in list is outermost."""
    handler = core
    for mw in reversed(middlewares):
        handler = mw(handler)
    return handler
