"""HTTP router with ``{param}`` path segments and static file serving.

The role of the reference's gorilla/mux wrapper (pkg/gofr/http/router.go:24-59):
register method+pattern pairs, match incoming paths extracting params,
report 405 vs 404 correctly, serve static directories with the same
restricted-file and permission checks (router.go:66-166).
"""

from __future__ import annotations

import mimetypes
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable

RESTRICTED_FILES = {".env", ".htaccess", ".htpasswd", ".git", ".gitignore",
                    "id_rsa", "id_dsa"}


@dataclass
class Route:
    method: str
    pattern: str
    handler: Callable
    segments: list[str] = field(default_factory=list)

    def __post_init__(self) -> None:
        self.segments = [s for s in self.pattern.strip("/").split("/") if s != ""]


@dataclass
class StaticMount:
    url_prefix: str
    directory: str


class Router:
    def __init__(self) -> None:
        self._routes: list[Route] = []
        self._static: list[StaticMount] = []

    # -- registration
    def add(self, method: str, pattern: str, handler: Callable) -> Route:
        route = Route(method=method.upper(), pattern=pattern, handler=handler)
        self._routes.append(route)
        return route

    def add_static(self, url_prefix: str, directory: str) -> None:
        self._static.append(StaticMount(url_prefix.rstrip("/"), directory))

    @property
    def routes(self) -> list[Route]:
        return list(self._routes)

    def registered_methods_for(self, path: str) -> list[str]:
        methods = []
        for route in self._routes:
            if self._match_segments(route, path) is not None:
                methods.append(route.method)
        return sorted(set(methods))

    def registered_paths(self) -> list[str]:
        return sorted({r.pattern for r in self._routes})

    # -- matching
    @staticmethod
    def _match_segments(route: Route, path: str) -> dict[str, str] | None:
        parts = [p for p in path.strip("/").split("/") if p != ""]
        if len(parts) != len(route.segments):
            return None
        params: dict[str, str] = {}
        for seg, part in zip(route.segments, parts):
            if seg.startswith("{") and seg.endswith("}"):
                params[seg[1:-1]] = part
            elif seg != part:
                return None
        return params

    def match(self, method: str, path: str) -> tuple[Route, dict[str, str]] | None:
        method = method.upper()
        for route in self._routes:
            params = self._match_segments(route, path)
            if params is not None and route.method == method:
                return route, params
        # HTTP/1.1: HEAD is answered by GET handlers (the server strips
        # the body via head_only)
        if method == "HEAD":
            return self.match("GET", path)
        return None

    # -- static files (reference router.go:66-166 checks)
    def match_static(self, path: str) -> tuple[str, bytes, str] | None:
        """Return (status-reason, content, content_type) for a static hit."""
        for mount in self._static:
            if not (path == mount.url_prefix or path.startswith(mount.url_prefix + "/")):
                continue
            rel = path[len(mount.url_prefix):].lstrip("/") or "index.html"
            base = Path(mount.directory).resolve()
            target = (base / rel).resolve()
            # path traversal guard
            if not str(target).startswith(str(base) + os.sep) and target != base:
                return self._static_404(base)
            # every component is checked so files inside restricted
            # directories (.git/config etc.) can't be served
            rel_parts = target.relative_to(base).parts if target != base else ()
            if any(part in RESTRICTED_FILES for part in rel_parts):
                return self._static_404(base)
            if target.is_dir():
                target = target / "index.html"
            if not target.is_file():
                return self._static_404(base)
            if not os.access(target, os.R_OK):
                return ("403", b"access denied", "text/plain")
            ctype = mimetypes.guess_type(str(target))[0] or "application/octet-stream"
            return ("200", target.read_bytes(), ctype)
        return None

    @staticmethod
    def _static_404(base: Path) -> tuple[str, bytes, str]:
        fallback = base / "404.html"
        if fallback.is_file():
            return ("404", fallback.read_bytes(), "text/html")
        return ("404", b"404 not found", "text/plain")
