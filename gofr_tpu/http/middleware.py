"""The standard middleware chain: tracer -> logging -> CORS -> metrics.

Mirrors reference pkg/gofr/http/middleware/: request span from the
incoming ``traceparent`` (tracer.go:15-32), structured per-request log
with trace ids and probe-path muting (logger.go:93-175), env-driven
CORS (cors.go:13-60), and the ``app_http_response`` histogram
(metrics.go:22-60). Auth middleware lives in ``auth.py``.
"""

from __future__ import annotations

import time
from typing import TextIO

from ..logging.logger import Logger, current_trace_ids
from .request import HTTPRequest
from .responder import ResponseData
from .server import Handler, Middleware

WELL_KNOWN_PATHS = {"/.well-known/health", "/.well-known/alive", "/favicon.ico"}


class RequestLog:
    """One-line structured request record (reference logger.go:51-66)."""

    def __init__(self, method: str, uri: str, status: int, duration_us: int,
                 ip: str, trace_id: str = "") -> None:
        self.method = method
        self.uri = uri
        self.response = status
        self.response_time = duration_us
        self.ip = ip
        self.trace_id = trace_id

    def pretty_print(self, out: TextIO) -> None:
        color = 32 if self.response < 400 else (33 if self.response < 500 else 31)
        out.write(f"\x1b[{color}m{self.response}\x1b[0m "
                  f"{self.response_time:>8}µs {self.method:<7} {self.uri}")


def tracer_middleware(tracer) -> Middleware:
    def mw(next_handler: Handler) -> Handler:
        async def wrapped(request: HTTPRequest) -> ResponseData:
            span = tracer.start_span(
                f"{request.method} {request.path}",
                traceparent=request.header("traceparent"))
            try:
                response = await next_handler(request)
                span.set_attribute("http.status", response.status)
                if response.status >= 500:
                    span.set_status(f"ERROR: {response.status}")
                # clients correlate support tickets to traces by this
                # header — on every status, errors especially
                response.headers.setdefault("X-Trace-Id", span.trace_id)
                return response
            finally:
                span.end()
        return wrapped
    return mw


def logging_middleware(logger: Logger,
                       tenant_resolver=None) -> Middleware:
    def mw(next_handler: Handler) -> Handler:
        async def wrapped(request: HTTPRequest) -> ResponseData:
            start = time.perf_counter()
            trace = current_trace_ids()
            trace_id = trace[0] if trace else ""
            try:
                response = await next_handler(request)
            except Exception:
                logger.error(RequestLog(
                    request.method, request.path, 500,
                    int((time.perf_counter() - start) * 1e6),
                    request.client_addr, trace_id).__dict__)
                raise
            if request.path not in WELL_KNOWN_PATHS:  # probe muting
                record = RequestLog(
                    request.method, request.path, response.status,
                    int((time.perf_counter() - start) * 1e6),
                    request.client_addr, trace_id)
                # the auth middleware runs INSIDE this one, so by now
                # the principal (if any) is on the request — stamp the
                # resolved tenant label into the request log so one
                # grep answers "who was hitting this route"
                info = getattr(request, "auth_info", None)
                if tenant_resolver is not None and info:
                    record.tenant = tenant_resolver.resolve(info)
                if response.status >= 500:
                    logger.error(record)
                else:
                    logger.info(record)
            return response
        return wrapped
    return mw


def cors_middleware(config) -> Middleware:
    """Env-driven CORS (ACCESS_CONTROL_* keys, reference config.go:29-41)."""
    allow_origin = config.get_or_default("ACCESS_CONTROL_ALLOW_ORIGIN", "*")
    allow_headers = config.get_or_default(
        "ACCESS_CONTROL_ALLOW_HEADERS",
        "Authorization, Content-Type, x-requested-with, origin, true-client-ip, X-Correlation-ID")
    allow_methods = config.get_or_default(
        "ACCESS_CONTROL_ALLOW_METHODS", "GET, POST, PUT, PATCH, DELETE, OPTIONS")
    extra = {}
    for key in ("ACCESS_CONTROL_ALLOW_CREDENTIALS", "ACCESS_CONTROL_MAX_AGE",
                "ACCESS_CONTROL_EXPOSE_HEADERS"):
        value = config.get(key)
        if value:
            header = "-".join(w.capitalize() for w in key.lower().split("_"))
            extra[header] = value

    def apply(headers: dict[str, str]) -> None:
        headers.setdefault("Access-Control-Allow-Origin", allow_origin)
        headers.setdefault("Access-Control-Allow-Headers", allow_headers)
        headers.setdefault("Access-Control-Allow-Methods", allow_methods)
        for k, v in extra.items():
            headers.setdefault(k, v)

    def mw(next_handler: Handler) -> Handler:
        async def wrapped(request: HTTPRequest) -> ResponseData:
            if request.method == "OPTIONS":
                response = ResponseData(status=200, body=b"")
                apply(response.headers)
                return response
            response = await next_handler(request)
            apply(response.headers)
            return response
        return wrapped
    return mw


def metrics_middleware(metrics) -> Middleware:
    """Record app_http_response histogram by path/method/status."""
    def mw(next_handler: Handler) -> Handler:
        async def wrapped(request: HTTPRequest) -> ResponseData:
            start = time.perf_counter()
            response = await next_handler(request)
            # label with the matched route pattern (set by the core
            # handler) so client-controlled paths can't blow up label
            # cardinality; unmatched requests share one label
            pattern = getattr(request, "matched_pattern", None) or "<unmatched>"
            metrics.record_histogram(
                "app_http_response", time.perf_counter() - start,
                path=pattern, method=request.method,
                status=str(response.status))
            return response
        return wrapped
    return mw
