"""The App facade: routing verbs, lifecycle, servers, hooks.

Mirrors reference pkg/gofr/gofr.go + factory.go + run.go: ``App()``
wires config -> container -> tracer -> HTTP/metrics servers and the
default routes (health/alive/favicon, factory.go:48-52); route verbs
(rest.go:9-31); ``run()`` installs signal-driven graceful shutdown and
starts every server concurrently (run.go:15-95, shutdown.go:14-48);
``on_start`` hooks (gofr.go:54-88); ``subscribe`` (gofr.go:249);
``add_cron_job`` (gofr.go:287).
"""

from __future__ import annotations

import asyncio
import signal
import time
from typing import Any, Callable

from .config.env import EnvConfig
from .container.container import Container
from .context import Context
from .handler import build_core_handler
from .http.middleware import (
    cors_middleware,
    logging_middleware,
    metrics_middleware,
    tracer_middleware,
)
from .http.responder import ResponseData
from .http.router import Router
from .http.server import HTTPServer, chain

DEFAULT_HTTP_PORT = 8000
DEFAULT_METRICS_PORT = 2121
DEFAULT_SHUTDOWN_GRACE = 30.0


class App:
    def __init__(self, config_dir: str = "configs", config=None) -> None:
        self.config = config if config is not None else EnvConfig(config_dir)
        self.container = Container.create(self.config)
        self.logger = self.container.logger
        self.router = Router()
        self._on_start: list[Callable] = []
        self._on_shutdown: list[Callable] = []
        self._subscriptions: dict[str, Callable] = {}
        self._cron = None  # created on first add_cron_job
        self._middlewares: list[Callable] = []
        self._user_middlewares: list[Callable] = []
        self._stop_event: asyncio.Event | None = None
        self._servers: list[HTTPServer] = []
        self._tasks: list[asyncio.Task] = []
        self._shutdown_task: asyncio.Task | None = None
        self.http_server: HTTPServer | None = None
        self.metrics_server: HTTPServer | None = None
        self.grpc_server = None  # created on first register_grpc_service
        self._ws_router: Router | None = None
        self._ws_services: dict[str, Any] = {}
        self._auth_providers: list[Any] = []  # also guard the WS upgrade
        # serving lifecycle registry for the graceful SIGTERM drain:
        # engines drain (admission closed, in-flight work finishes)
        # and fleet agents deregister from their leader BEFORE the
        # shutdown hooks hard-stop everything (_graceful_stop)
        self._engines: list[Any] = []
        self._agents: list[Any] = []

        self.http_port = self.config.get_int("HTTP_PORT", DEFAULT_HTTP_PORT) \
            if hasattr(self.config, "get_int") else DEFAULT_HTTP_PORT
        self.metrics_port = self.config.get_int("METRICS_PORT", DEFAULT_METRICS_PORT) \
            if hasattr(self.config, "get_int") else DEFAULT_METRICS_PORT
        timeout = self.config.get_float("REQUEST_TIMEOUT", 0.0) \
            if hasattr(self.config, "get_float") else 0.0
        self.request_timeout = timeout if timeout > 0 else None
        self.shutdown_grace = self.config.get_float(
            "SHUTDOWN_GRACE_PERIOD", DEFAULT_SHUTDOWN_GRACE) \
            if hasattr(self.config, "get_float") else DEFAULT_SHUTDOWN_GRACE

        self._register_default_routes()

    # ------------------------------------------------------------- routes
    def _register_default_routes(self) -> None:
        self.router.add("GET", "/.well-known/health", self._health_handler)
        self.router.add("GET", "/.well-known/alive", self._alive_handler)
        # OpenAPI spec + Swagger UI (reference swagger.go:59-70)
        from .openapi import register as register_openapi
        register_openapi(self)

    @staticmethod
    def _alive_handler(ctx: Context) -> Any:
        return {"status": "UP"}

    def _health_handler(self, ctx: Context) -> Any:
        return self.container.health()

    def _add_route(self, method: str, pattern: str,
                   handler: Callable | None = None):
        if handler is None:  # decorator form
            def decorator(fn: Callable) -> Callable:
                self.router.add(method, pattern, fn)
                return fn
            return decorator
        self.router.add(method, pattern, handler)
        return handler

    def get(self, pattern: str, handler: Callable | None = None):
        return self._add_route("GET", pattern, handler)

    def post(self, pattern: str, handler: Callable | None = None):
        return self._add_route("POST", pattern, handler)

    def put(self, pattern: str, handler: Callable | None = None):
        return self._add_route("PUT", pattern, handler)

    def patch(self, pattern: str, handler: Callable | None = None):
        return self._add_route("PATCH", pattern, handler)

    def delete(self, pattern: str, handler: Callable | None = None):
        return self._add_route("DELETE", pattern, handler)

    def add_static_files(self, url_prefix: str, directory: str) -> None:
        self.router.add_static(url_prefix, directory)

    def add_rest_handlers(self, entity_cls: type, **kwargs):
        """Auto-CRUD for a dataclass entity (reference rest.go:53)."""
        from .crud import add_rest_handlers
        return add_rest_handlers(self, entity_cls, **kwargs)

    def use_middleware(self, middleware: Callable) -> None:
        """Append a user middleware (runs innermost, after the chain)."""
        self._user_middlewares.append(middleware)

    # ------------------------------------------------------------- auth
    def _install_auth(self, provider, scheme: str) -> None:
        from .http.auth import auth_middleware
        self._middlewares.append(auth_middleware(provider, scheme=scheme))
        self._auth_providers.append(provider)

    def enable_basic_auth(self, **users: str) -> None:
        """Install basic-auth middleware (reference auth.go:16)."""
        from .http.auth import BasicAuthProvider
        self._install_auth(BasicAuthProvider(users), "Basic")

    def enable_basic_auth_with_validator(self, validator: Callable) -> None:
        from .http.auth import BasicAuthProvider
        self._install_auth(BasicAuthProvider(validator=validator), "Basic")

    def enable_api_key_auth(self, *keys: str,
                            key_names: dict[str, str] | None = None) -> None:
        """Install API-key auth. ``key_names`` maps key -> tenant label
        (the accounting identity usage metering reports under); keys
        only ever surface downstream as short fingerprints."""
        from .http.auth import APIKeyAuthProvider
        self._install_auth(APIKeyAuthProvider(list(keys),
                                              key_names=key_names),
                           "ApiKey")

    def enable_api_key_auth_with_validator(self, validator: Callable) -> None:
        from .http.auth import APIKeyAuthProvider
        self._install_auth(APIKeyAuthProvider(validator=validator), "ApiKey")

    def enable_oauth(self, jwks_url: str | None = None, *,
                     refresh_interval: float = 300.0, **kwargs) -> None:
        """Install Bearer-JWT auth against a JWKS endpoint
        (reference auth.go:92)."""
        from .http.auth import OAuthProvider
        kwargs.setdefault("logger", self.logger)
        provider = OAuthProvider(jwks_url,
                                 refresh_interval=refresh_interval, **kwargs)
        self._install_auth(provider, "Bearer")

    # -------------------------------------------------------- websockets
    def websocket(self, pattern: str, handler: Callable | None = None):
        """Register a websocket endpoint: the handler runs once per
        inbound message, ``ctx.bind()`` reads the frame
        (reference websocket.go:30-49)."""
        if handler is None:
            def decorator(fn: Callable) -> Callable:
                self.websocket(pattern, fn)
                return fn
            return decorator

        if self._ws_router is None:
            from .websocket.manager import WSManager
            self._ws_router = Router()
            if self.container.ws_manager is None:
                self.container.ws_manager = WSManager()
        self._ws_router.add("WS", pattern, handler)

        async def reject_plain_http(ctx) -> Any:
            from .http.errors import HTTPError
            raise HTTPError("websocket endpoint: upgrade required",
                            status_code=426)
        self.router.add("GET", pattern, reject_plain_http)
        return handler

    # --------------------------------------------------------------- gRPC
    def register_grpc_service(self, service) -> None:
        """Queue a GRPCService; the gRPC server boots with the app
        (reference grpc.go:200 RegisterService)."""
        if self.grpc_server is None:
            from .grpc.server import DEFAULT_GRPC_PORT, GRPCServer
            port = self.config.get_int("GRPC_PORT", DEFAULT_GRPC_PORT) \
                if hasattr(self.config, "get_int") else DEFAULT_GRPC_PORT
            self.grpc_server = GRPCServer(self.container, port=port,
                                          logger=self.logger)
        self.grpc_server.register(service)
        # protogen modules carry their protoc-compiled descriptors —
        # register them so reflection answers symbol lookups for real.
        # The constant lives in the GENERATED module, which is usually
        # a base class's module (users subclass <Service>Base in their
        # own app module), so walk the MRO
        import sys as _sys
        for klass in type(service).__mro__:
            module = _sys.modules.get(klass.__module__)
            fds = getattr(module, "FILE_DESCRIPTOR_SET", None)
            if fds:
                self.grpc_server.register_descriptors(fds)
                break

    def add_ws_service(self, name: str, url: str, *,
                       headers: dict[str, str] | None = None,
                       retry_interval: float = 5.0,
                       on_message: Callable | None = None):
        """Maintain a named outbound WS connection with reconnection
        (reference websocket.go:52-98)."""
        from .websocket.service import WSService
        service = WSService(name, url, headers=headers,
                            retry_interval=retry_interval,
                            logger=self.logger, on_message=on_message)
        self._ws_services[name] = service
        self.container.register_ws_service(name, service)
        self.on_start(lambda c: service.start())
        self.on_shutdown(service.stop)
        return service

    # ------------------------------------------------------------ hooks
    def on_start(self, hook: Callable) -> Callable:
        self._on_start.append(hook)
        return hook

    def on_shutdown(self, hook: Callable) -> Callable:
        self._on_shutdown.append(hook)
        return hook

    def subscribe(self, topic: str, handler: Callable | None = None):
        if handler is None:
            def decorator(fn: Callable) -> Callable:
                self._subscriptions[topic] = fn
                return fn
            return decorator
        self._subscriptions[topic] = handler
        return handler

    def add_cron_job(self, schedule: str, name: str, job: Callable) -> None:
        from .cron import Cron
        if self._cron is None:
            self._cron = Cron(self.container)
        self._cron.add(schedule, name, job)

    def migrate(self, migrations: dict) -> list[int]:
        from .migrations.runner import run as run_migrations
        return run_migrations(self.container, migrations)

    def serve_model(self, name: str, engine, tokenizer=None, *,
                    chat_path: str | None = "/chat",
                    slo=None, scheduler=None) -> None:
        """Wire a serving engine into the app: metrics, health, lifecycle,
        and (optionally) a chat endpoint, in one call. ``slo`` is an
        optional :class:`~gofr_tpu.serving.observability.SLOConfig`;
        by default the engine gets a tracker with the stock objectives
        (burn-rate gauges + ``GET /debug/slo``); pass a config to tune
        thresholds, or construct/clear ``engine.slo`` yourself.
        ``scheduler`` is an optional
        :class:`~gofr_tpu.serving.scheduler.SchedulerConfig` swapped
        into the engine's admission queue (fair-share weights, lanes,
        rate limits, shedding — see docs/configs.md); the default
        fair-share policy is already on."""
        if hasattr(engine, "attach_metrics"):
            engine.attach_metrics(self.container.metrics)
        else:
            engine.metrics = self.container.metrics
        engine.logger = self.logger
        # request tracing: the engine assembles engine.* child spans of
        # the submitting request's HTTP/gRPC span through this tracer
        if getattr(engine, "tracer", None) is None:
            engine.tracer = self.container.tracer
        # usage metering + SLO tracking: host-side accounting fed at
        # retire (serving/observability.py) — series land on the
        # container manager the engine was just attached to
        ledger = getattr(engine, "usage_ledger", None)
        if ledger is not None and ledger.metrics is None:
            ledger.metrics = self.container.metrics
        if hasattr(engine, "slo") and engine.slo is None:
            from .serving.observability import SLOConfig, SLOTracker
            engine.slo = SLOTracker(slo or SLOConfig(),
                                    metrics=self.container.metrics,
                                    logger=self.logger)
        # flight-data-recorder wiring: SLO trips land on the engine's
        # event ledger, and a fast-burn trip snapshots an incident
        # bundle (serving/events.py) — both no-ops when the ledger is
        # disabled (GOFR_EVENTS=0 / EngineConfig.events=False)
        slo_tracker = getattr(engine, "slo", None)
        ev_ledger = getattr(engine, "events", None)
        incidents = getattr(engine, "incidents", None)
        if slo_tracker is not None and ev_ledger is not None \
                and hasattr(slo_tracker, "events"):
            slo_tracker.events = ev_ledger
        if slo_tracker is not None and incidents is not None \
                and getattr(slo_tracker, "on_fast_burn", True) is None:
            autoprof = getattr(engine, "autoprof", None)

            def _on_fast_burn(incidents=incidents, autoprof=autoprof):
                # arm BEFORE triggering so the bundle can point at the
                # capture directory (serving/costmodel.py AutoProfiler;
                # a no-op when disabled/debounced/killed)
                capture = autoprof.arm(
                    "fast_burn", "SLO error-budget fast burn") \
                    if autoprof is not None else None
                incidents.trigger(
                    "fast_burn", cause="SLO error-budget fast burn",
                    attrs={"autoprof_dir": (capture or {}).get("dir")})
            slo_tracker.on_fast_burn = _on_fast_burn
        # scheduler plumbing: the engine constructed its admission
        # queue already — swap in the app-level policy and wire the
        # shed-episode WARNs to the app logger
        sched = getattr(engine, "waiting", None)
        if sched is not None and hasattr(sched, "reconfigure"):
            if scheduler is not None:
                sched.reconfigure(scheduler)
            if getattr(sched, "logger", None) is None:
                sched.logger = self.logger
        self.container.add_model(name, engine)
        self._install_debug_routes()
        if self.container.tpu is None:
            from .device import DeviceRegistry
            self.container.tpu = DeviceRegistry(
                logger=self.logger, metrics=self.container.metrics)
        if hasattr(self.container.tpu, "register_engine"):
            self.container.tpu.register_engine(name, engine)
        if chat_path:
            from .serving.handlers import make_chat_handler
            from .serving.tokenizer import ByteTokenizer
            self.post(chat_path,
                      make_chat_handler(engine, tokenizer or ByteTokenizer()))
        self.on_start(lambda c: engine.start())
        # close, not stop: the shutdown hook runs ON the event loop, so
        # a wedged device call must only hold it for close()'s short
        # join budget, not stop()'s full 30s
        self.on_shutdown(engine.close)
        self._engines.append(engine)

    # ------------------------------------------------------------- fleet
    def serve_fleet_leader(self, *, coordinator: str = "",
                           host_id: str = "leader", router=None,
                           tokenizer=None, **kw):
        """Install a multi-host control-plane LEADER on this app:
        join/heartbeat/topology routes, the federated
        ``/control/fleet/metrics`` Prometheus surface and the
        consolidated ``/debug/fleet`` JSON view, wired to the
        container's logger and metrics manager. Returns the
        :class:`~gofr_tpu.serving.control_plane.ControlPlaneLeader`.

        ``router=RouterConfig(...)`` additionally turns the leader
        into the fleet's data-plane front door: ``POST /chat`` and the
        OpenAI surface proxy to the member whose prefix cache best
        covers the request, with session affinity, typed-reject
        failover and unbuffered stream passthrough
        (:class:`~gofr_tpu.serving.router.FleetRouter`, reachable
        afterwards as ``leader.router``). ``tokenizer`` overrides the
        routing tokenizer (default byte-level — correct whenever the
        workers serve byte-tokenized models)."""
        from .serving.control_plane import ControlPlaneLeader
        kw.setdefault("metrics", self.container.metrics)
        leader = ControlPlaneLeader(coordinator=coordinator,
                                    host_id=host_id,
                                    logger=self.logger, **kw)
        leader.install(self)
        leader.router = None
        if router is not None:
            from .serving.router import FleetRouter, RouterConfig
            if router is True:
                router = RouterConfig()
            fleet_router = FleetRouter(leader, router,
                                       tokenizer=tokenizer,
                                       logger=self.logger,
                                       tracer=self.container.tracer)
            fleet_router.install(self)
            leader.router = fleet_router
        return leader

    def join_fleet(self, leader_url: str, *, host_id: str,
                   engine=None, address: str = "", **kw):
        """Join this app to a serving-group leader as a WORKER: the
        agent heartbeats with the engine's health, flight-recorder
        digest and this container's metrics snapshot attached, carries
        ``traceparent`` on every control RPC, and sets the fleet
        context (host_id/rank/generation) that enriches every log
        record and span. ``engine=None`` picks the first served model.
        An empty ``address`` advertises this app's own HTTP endpoint
        (``ADVERTISE_HOST``, default 127.0.0.1, plus the bound port)
        once the server binds — ephemeral-port workers become routable
        by the leader's data-plane router without knowing their port
        up front. Starts with the app, stops with it."""
        from .serving.control_plane import (WorkerAgent,
                                            engine_fleet_sources)
        if engine is None and self.container.models:
            engine = next(iter(self.container.models.values()))
        addr_source: Any = address
        if not address:
            advertise_host = self.config.get("ADVERTISE_HOST") \
                or "127.0.0.1"

            def addr_source() -> str:
                server = getattr(self, "http_server", None)
                port = int(getattr(server, "bound_port", 0) or 0)
                return f"{advertise_host}:{port}" if port else ""
        sources: dict = {}
        if engine is not None:
            health, summary, _metrics = engine_fleet_sources(engine)
            sources = {"health_source": health,
                       "summary_source": summary}
        kw.setdefault("metrics_source", self.container.metrics.snapshot)
        kw.setdefault("metrics", self.container.metrics)
        # heartbeat event piggyback: the agent attaches the engine
        # ledger's digest so the leader can merge a fleet timeline
        if engine is not None \
                and getattr(engine, "events", None) is not None:
            kw.setdefault("events", engine.events)
        agent = WorkerAgent(leader_url, host_id=host_id,
                            address=addr_source,
                            tracer=self.container.tracer,
                            logger=self.logger, **{**sources, **kw})
        self.on_start(lambda c: agent.start())
        self.on_shutdown(agent.stop)
        self._agents.append(agent)
        return agent

    def _install_debug_routes(self) -> None:
        """Serving debug surface, registered once with the first
        ``serve_model``: ``GET /debug/engine`` (flight-recorder pass
        ring + request logs + stats for every served model),
        ``GET /debug/workload`` + ``POST /debug/workload/start|stop``
        (workload capture download/arm/disarm), ``GET /debug/events``
        (the flight-data-recorder event ring as gofr-events JSONL) +
        ``GET /debug/incidents`` (snapshot bundles) and, when
        ``PROFILER_ENABLED`` is set, ``POST /debug/profile/start|stop``
        wrapping ``jax.profiler`` for on-demand xprof captures. All
        ride the normal middleware chain, so auth providers installed
        on the app guard them like any other route."""
        if getattr(self, "_debug_routes_installed", False):
            return
        self._debug_routes_installed = True
        container = self.container

        def bounded_int_param(ctx, name: str, default: int,
                              lo: int, hi: int) -> int:
            """Query-param hygiene for the debug surface: absent ->
            default, out-of-range -> clamped into [lo, hi], anything
            that is not an integer -> 400 (a typo'd ?n= must say so,
            not silently dump a different amount of data)."""
            raw = ctx.param(name)
            if raw is None or raw == "":
                return default
            try:
                value = int(raw)
            except (TypeError, ValueError):
                from .http.errors import ErrorInvalidParam
                raise ErrorInvalidParam(name)
            return max(lo, min(hi, value))

        def trace_export_state(ctx=None):
            """Span-exporter backpressure state: a bounded exporter
            (InMemoryExporter ring) that evicted spans must say so —
            a silently truncated trace capture reads as 'no spans
            there', which is a lie."""
            exporter = getattr(container.tracer, "exporter", None)
            if exporter is None or not hasattr(exporter, "dropped"):
                return None
            out = {"dropped_spans": int(exporter.dropped)}
            spans = getattr(exporter, "spans", None)
            if spans is not None:
                out["buffered_spans"] = len(spans)
                out["max_spans"] = getattr(exporter, "max_spans", None)
            return out

        def engine_debug(ctx):
            n = bounded_int_param(ctx, "n", default=0, lo=0, hi=65536)
            out = {}
            for model_name, engine in container.models.items():
                recorder = getattr(engine, "recorder", None)
                out[model_name] = {
                    "health": engine.health_check()
                    if hasattr(engine, "health_check") else {},
                    "stats": dict(getattr(engine, "stats", {})),
                    "flight": recorder.snapshot(n or None)
                    if recorder is not None else None,
                }
            traces = trace_export_state()
            if traces is not None:
                out["traces"] = traces
            return out
        self.get("/debug/engine", engine_debug)

        def efficiency_debug(ctx):
            """Goodput rollup per served model: where the busy
            device-seconds went (useful vs. waste by cause, conserved),
            memory high-water marks with timestamps, and the recompile
            sentinel's state — the first stop of the 'where did my
            FLOPs go' runbook (docs/operations.md)."""
            out = {}
            for model_name, engine in container.models.items():
                if hasattr(engine, "efficiency_state"):
                    out[model_name] = engine.efficiency_state()
                else:
                    out[model_name] = None
            return out
        self.get("/debug/efficiency", efficiency_debug)

        def costs_debug(ctx):
            """Pass-cost observatory per served model: the online
            per-dispatch-signature cost table (EWMA + variance, µs/row
            and µs/token, sealed baselines, drift episodes) and the
            anomaly-triggered profiler's state — the 'p95 regressed,
            which kernel?' runbook (docs/operations.md) starts here."""
            out = {}
            for model_name, engine in container.models.items():
                out[model_name] = engine.cost_state() \
                    if hasattr(engine, "cost_state") else None
            return out
        self.get("/debug/costs", costs_debug)

        def integrity_debug(ctx):
            """Output-integrity observatory per served model: digest
            fold totals, the sealed golden corpus, golden canary probe
            results and the mismatch-episode latch — the 'a host is
            returning garbage' runbook (docs/operations.md) starts
            here. Fleet-wide divergence votes and quarantine live on
            the leader's ``/debug/fleet``."""
            out = {}
            for model_name, engine in container.models.items():
                out[model_name] = engine.integrity_state() \
                    if hasattr(engine, "integrity_state") else None
            return out
        self.get("/debug/integrity", integrity_debug)

        def usage_debug(ctx):
            """Per-tenant usage rollup: ``?tenant=`` filters,
            ``?window=5m`` sums over the recent-event ring instead of
            the cumulative totals."""
            from .serving.observability import parse_window
            tenant = ctx.param("tenant") or None
            try:
                window_s = parse_window(ctx.param("window") or None)
            except ValueError:
                from .http.errors import ErrorInvalidParam
                raise ErrorInvalidParam("window")
            out = {}
            for model_name, engine in container.models.items():
                ledger = getattr(engine, "usage_ledger", None)
                out[model_name] = ledger.rollup(
                    tenant=tenant, window_s=window_s) \
                    if ledger is not None else None
            return out
        self.get("/debug/usage", usage_debug)

        def slo_debug(ctx):
            out = {}
            for model_name, engine in container.models.items():
                slo = getattr(engine, "slo", None)
                out[model_name] = slo.state() if slo is not None else None
            return out
        self.get("/debug/slo", slo_debug)

        def scheduler_debug(ctx):
            """Admission-scheduler state per served model: policy,
            lane depths, per-tenant shares/weights/burn, token-bucket
            levels, shed-episode state and the rejection counters —
            the overload runbook's first stop (docs/operations.md).
            ``?fresh=1`` forces a ledger-share refresh so the view
            reflects retires that landed inside the 0.5s share-cache
            window (smokes and operators mid-incident want truth,
            not a cheap read)."""
            fresh = ctx.param("fresh") in ("1", "true")
            out = {}
            for model_name, engine in container.models.items():
                sched = getattr(engine, "waiting", None)
                out[model_name] = sched.state(fresh=fresh) \
                    if hasattr(sched, "state") else None
            return out
        self.get("/debug/scheduler", scheduler_debug)

        def pick_workload_recorder(ctx):
            """``?model=`` selects among served models (404 on an
            unknown name); default is the first served model — the
            single-model case every deployment here actually runs."""
            from .http.errors import ErrorEntityNotFound
            name = ctx.param("model") or None
            if not container.models:
                raise ErrorEntityNotFound("model")
            if name is None:
                name = next(iter(container.models))
            engine = container.models.get(name)
            if engine is None:
                raise ErrorEntityNotFound(f"model {name!r}")
            recorder = getattr(engine, "workload", None)
            if recorder is None:
                raise ErrorEntityNotFound(
                    f"model {name!r} has no workload recorder")
            return name, recorder

        def workload_download(ctx):
            """The capture ring as a versioned JSONL workload file —
            feed it to scripts/replay.py. ``?n=`` keeps only the last
            n records (clamped; garbage -> 400)."""
            from .http.response import File
            n = bounded_int_param(ctx, "n", default=0, lo=0, hi=1 << 20)
            _, recorder = pick_workload_recorder(ctx)
            body = recorder.to_jsonl(n or None)
            return File(content=body.encode(),
                        content_type="application/jsonl; charset=utf-8")
        self.get("/debug/workload", workload_download)

        def workload_start(ctx):
            """Arm capture (fresh ring). Body ``{"redact": true}``
            switches the capture to salted-hash redaction."""
            try:
                body = ctx.bind() or {}
            except Exception:
                body = {}
            redact = None
            if isinstance(body, dict) and "redact" in body:
                redact = bool(body.get("redact"))
            name, recorder = pick_workload_recorder(ctx)
            return {"model": name, "workload": recorder.start(redact)}
        self.post("/debug/workload/start", workload_start)

        def workload_stop(ctx):
            name, recorder = pick_workload_recorder(ctx)
            return {"model": name, "workload": recorder.stop()}
        self.post("/debug/workload/stop", workload_stop)

        def pick_event_ledger(ctx):
            """``?model=`` selects among served models (404 on an
            unknown name or a disabled ledger); default is the first
            served model."""
            from .http.errors import ErrorEntityNotFound
            name = ctx.param("model") or None
            if not container.models:
                raise ErrorEntityNotFound("model")
            if name is None:
                name = next(iter(container.models))
            engine = container.models.get(name)
            if engine is None:
                raise ErrorEntityNotFound(f"model {name!r}")
            ledger = getattr(engine, "events", None)
            if ledger is None or not ledger.enabled:
                raise ErrorEntityNotFound(
                    f"model {name!r} has no event ledger "
                    "(GOFR_EVENTS=0 or EngineConfig.events=False?)")
            return name, ledger

        def events_download(ctx):
            """The event ring as versioned JSONL (``gofr-events`` v1)
            — the flight data recorder's local timeline. ``?kind=``
            filters, ``?since=`` (unix seconds) trims, ``?n=`` keeps
            the newest n (clamped; garbage -> 400)."""
            from .http.response import File
            n = bounded_int_param(ctx, "n", default=0, lo=0, hi=1 << 20)
            kind = ctx.param("kind") or None
            raw_since = ctx.param("since")
            since = None
            if raw_since not in (None, ""):
                try:
                    since = float(raw_since)
                except (TypeError, ValueError):
                    from .http.errors import ErrorInvalidParam
                    raise ErrorInvalidParam("since")
            _, event_ledger = pick_event_ledger(ctx)
            body = event_ledger.to_jsonl(kind=kind, since=since,
                                         n=n or None)
            return File(content=body.encode(),
                        content_type="application/jsonl; charset=utf-8")
        self.get("/debug/events", events_download)

        def incidents_debug(ctx):
            """Incident-bundle spool per served model; ``?id=``
            fetches one full bundle (404 when unknown)."""
            from .http.errors import ErrorEntityNotFound
            incident_id = ctx.param("id") or None
            out = {}
            for model_name, engine in container.models.items():
                detector = getattr(engine, "incidents", None)
                if detector is None:
                    out[model_name] = None
                    continue
                if incident_id is not None:
                    bundle = detector.get(incident_id)
                    if bundle is not None:
                        return bundle
                    continue
                out[model_name] = {"incidents": detector.list(),
                                   "detector": detector.state()}
            if incident_id is not None:
                raise ErrorEntityNotFound(f"incident {incident_id!r}")
            return out
        self.get("/debug/incidents", incidents_debug)

        enabled = self.config.get_bool("PROFILER_ENABLED", False) \
            if hasattr(self.config, "get_bool") else False
        if not enabled:
            return
        from .serving.observability import ProfilerCapture
        capture = ProfilerCapture(
            base_dir=self.config.get_or_default(
                "PROFILER_DIR", "/tmp/gofr_tpu_profiles"),
            logger=self.logger)
        self.profiler = capture

        def profile_start(ctx):
            """Body ``{"dir": ..., "max_capture_s": N}`` — N > 0 arms
            a watchdog that stops the trace after N seconds even if
            nobody calls stop (counted in ``status()["auto_stops"]``)."""
            try:
                body = ctx.bind() or {}
            except Exception:
                body = {}
            if not isinstance(body, dict):
                body = {}
            target = body.get("dir")
            try:
                cap = float(body.get("max_capture_s") or 0.0)
            except (TypeError, ValueError):
                cap = 0.0
            return capture.start(target, max_capture_s=cap or None)

        def profile_stop(ctx):
            """Body ``{"force": true}`` recovers a leaked capture —
            e.g. a crashed client that started a trace and never came
            back — by stopping the profiler even when local state says
            idle."""
            try:
                body = ctx.bind() or {}
            except Exception:
                body = {}
            force = bool(body.get("force")) if isinstance(body, dict) \
                else False
            return capture.stop(force=force)

        def profile_status(ctx):
            return capture.status()

        self.post("/debug/profile/start", profile_start)
        self.post("/debug/profile/stop", profile_stop)
        self.get("/debug/profile", profile_status)

    # ---------------------------------------------------------- lifecycle
    def _build_http_handler(self):
        core = build_core_handler(self.router, self.container,
                                  self.request_timeout)
        middlewares = [
            tracer_middleware(self.container.tracer),
            logging_middleware(
                self.logger,
                tenant_resolver=self.container.tenant_resolver),
            cors_middleware(self.config),
            metrics_middleware(self.container.metrics),
        ]
        middlewares.extend(self._middlewares)
        middlewares.extend(self._user_middlewares)
        if self._ws_router is not None:
            # innermost, after auth + user middleware (reference
            # http_server.go:36-41 ordering)
            from .websocket.runtime import make_ws_middleware
            middlewares.append(make_ws_middleware(
                self._ws_router, self.container, self.logger))
        return chain(middlewares, core)

    def _build_metrics_handler(self):
        async def metrics_handler(request) -> ResponseData:
            if request.path == "/metrics":
                self.container.metrics.set_gauge(
                    "app_uptime_seconds",
                    round(time.time() - self.container._start_time, 1))
                # bounded-exporter backpressure: spans the ring evicted
                # (InMemoryExporter.dropped) — refreshed at scrape so a
                # truncated trace capture is visible, never silent
                exporter = getattr(self.container.tracer, "exporter",
                                   None)
                dropped = getattr(exporter, "dropped", None)
                if dropped is not None:
                    m = self.container.metrics
                    if m.get("app_traces_dropped_spans") is None:
                        m.new_gauge(
                            "app_traces_dropped_spans",
                            "spans evicted by the bounded in-memory "
                            "exporter ring (backpressure drops)")
                    m.set_gauge("app_traces_dropped_spans",
                                float(dropped))
                # content negotiation: a scraper asking for OpenMetrics
                # (Prometheus does when exemplar storage is on) gets
                # the exemplar-bearing exposition; everyone else gets
                # the classic text format, byte-identical to before
                accept = request.header("accept") \
                    if hasattr(request, "header") else ""
                if "application/openmetrics-text" in (accept or ""):
                    text = self.container.metrics.render_openmetrics()
                    ctype = ("application/openmetrics-text; "
                             "version=1.0.0; charset=utf-8")
                else:
                    text = self.container.metrics.render_prometheus()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                return ResponseData(
                    status=200, body=text.encode(), content_type=ctype)
            if request.path == "/.well-known/alive":
                return ResponseData(status=200, body=b'{"status": "UP"}')
            return ResponseData(status=404, body=b"not found",
                                content_type="text/plain")
        return metrics_handler

    async def _run_start_hooks(self) -> bool:
        """Sequential, abort on error (reference gofr.go:54-88)."""
        import inspect
        for hook in self._on_start:
            try:
                try:
                    takes_container = len(inspect.signature(hook).parameters) >= 1
                except (TypeError, ValueError):
                    takes_container = False
                result = hook(self.container) if takes_container else hook()
                if hasattr(result, "__await__"):
                    await result
            except Exception as exc:
                self.logger.error(f"on_start hook failed: {exc!r}")
                return False
        return True

    async def _start_server(self, server, env_key: str,
                            port: int) -> None:
        """Start a listener with the reference's port-availability
        guard (gofr.go:119-130): an occupied port fails boot with a
        message naming the port AND the env key that moves it, not a
        raw bind traceback."""
        import errno
        try:
            await server.start()
        except OSError as exc:
            if exc.errno == errno.EADDRINUSE:
                message = (f"port {port} is already in use; set "
                           f"{env_key} to a free port")
                self.logger.error(message)
                raise RuntimeError(message) from exc
            raise

    async def start(self) -> None:
        """Boot all servers without blocking (for tests / embedding).
        A failed boot unwinds whatever already started — callers catch
        one error against a clean slate, never a half-running app."""
        self._stop_event = asyncio.Event()
        await self.container.connect_async()
        if not await self._run_start_hooks():
            raise RuntimeError("on_start hook failed")
        try:
            await self._start_servers()
        except BaseException:
            try:
                await self.stop()
            except Exception as exc:
                self.logger.warn(f"cleanup after failed boot: {exc!r}")
            raise

    async def _start_servers(self) -> None:
        handler = self._build_http_handler()
        # CERT_FILE + KEY_FILE switch the main listener to TLS
        # (reference pkg/gofr/http_server.go:74-86); the metrics port
        # stays plaintext for scrapers, as in the reference.
        ssl_context = None
        cert_file = self.config.get("CERT_FILE")
        key_file = self.config.get("KEY_FILE")
        if cert_file and key_file:
            from .http.server import make_ssl_context
            try:
                ssl_context = make_ssl_context(cert_file, key_file)
            except (OSError, ValueError) as exc:
                # never degrade to cleartext on a port clients expect
                # to be HTTPS — fail startup, as ListenAndServeTLS does
                self.logger.error(f"TLS config invalid: {exc}")
                raise RuntimeError(
                    f"invalid CERT_FILE/KEY_FILE: {exc}") from exc
        self.http_server = HTTPServer(
            handler, host="0.0.0.0", port=self.http_port,
            logger=self.logger, ssl_context=ssl_context)
        await self._start_server(self.http_server, "HTTP_PORT",
                                 self.http_port)
        self._servers.append(self.http_server)

        self.metrics_server = HTTPServer(
            self._build_metrics_handler(), host="0.0.0.0",
            port=self.metrics_port, logger=self.logger)
        await self._start_server(self.metrics_server, "METRICS_PORT",
                                 self.metrics_port)
        self._servers.append(self.metrics_server)

        if self.grpc_server is not None:
            await self.grpc_server.start()

        if self._subscriptions:
            from .pubsub.subscriber import SubscriptionManager
            manager = SubscriptionManager(self.container)
            for topic, fn in self._subscriptions.items():
                self._tasks.append(asyncio.ensure_future(
                    manager.start_subscriber(topic, fn)))

        if self._cron is not None:
            self._tasks.append(asyncio.ensure_future(self._cron.run()))

        # periodic TPU gauge refresh (device count, HBM in use)
        if self.container.tpu is not None and \
                hasattr(self.container.tpu, "metrics_loop"):
            self._tasks.append(asyncio.ensure_future(
                self.container.tpu.metrics_loop()))

        # remote log-level polling (reference container.go:107)
        from .logging.remote import from_config as remote_level_from_config
        updater = remote_level_from_config(self.config, self.logger,
                                           self.container.metrics)
        if updater is not None:
            self._tasks.append(asyncio.ensure_future(updater.run()))

        # usage telemetry, opt-out (reference telemetry.go:13-38)
        from . import telemetry
        if telemetry.enabled(self.config):
            self._tasks.append(asyncio.ensure_future(
                telemetry.ping(self.container, "start")))

        self.logger.info(
            f"{self.container.app_name} up: http={self.http_server.bound_port} "
            f"metrics={self.metrics_server.bound_port}")

    async def stop(self) -> None:
        from . import telemetry
        ping_task: asyncio.Task | None = None
        if telemetry.enabled(self.config):
            # fire-and-forget: the ping gets the duration of the rest of
            # shutdown to complete, never delaying it (telemetry.py)
            ping_task = asyncio.ensure_future(
                telemetry.ping(self.container, "shutdown"))
        for hook in self._on_shutdown:
            try:
                result = hook()
                if hasattr(result, "__await__"):
                    await result
            except Exception as exc:
                self.logger.warn(f"shutdown hook: {exc!r}")
        for task in self._tasks:
            task.cancel()
        if self.container.ws_manager is not None:
            await self.container.ws_manager.close_all()
        if self.grpc_server is not None:
            await self.grpc_server.shutdown()
        for server in self._servers:
            await server.shutdown()
        self._servers.clear()
        await self.container.close()
        if ping_task is not None and not ping_task.done():
            ping_task.cancel()
        if self._stop_event is not None:
            self._stop_event.set()

    async def serve(self) -> None:
        """start() then block until a stop signal."""
        await self.start()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, self._signal_stop)
            except (NotImplementedError, RuntimeError):
                pass
        assert self._stop_event is not None
        await self._stop_event.wait()

    def _signal_stop(self) -> None:
        if getattr(self, "_shutdown_task", None) is not None:
            return  # second signal during grace period: already stopping
        self.logger.info("shutdown signal received")
        # strong reference (so GC can't drop it) kept OUTSIDE self._tasks —
        # stop() cancels everything in _tasks and must not cancel its caller
        self._shutdown_task = asyncio.ensure_future(self._graceful_stop())

    async def _graceful_stop(self) -> None:
        deadline = time.monotonic() + self.shutdown_grace
        try:
            await asyncio.wait_for(self._drain_serving(deadline),
                                   self.shutdown_grace)
        except asyncio.TimeoutError:
            self.logger.error("serving drain timed out; stopping hard")
        except Exception as exc:  # drain is best-effort by contract
            self.logger.warn(f"serving drain failed: {exc!r}")
        try:
            await asyncio.wait_for(
                self.stop(), max(1.0, deadline - time.monotonic()))
        except asyncio.TimeoutError:
            self.logger.error("graceful shutdown timed out; forcing exit")
            if self._stop_event is not None:
                self._stop_event.set()

    async def _drain_serving(self, deadline: float) -> None:
        """SIGTERM drain, in dependency order and inside the grace
        budget: (1) every served engine drains — admission closes (new
        submits get a typed 503 + Retry-After), queued and in-flight
        requests run to completion; (2) fleet agents deregister from
        their leader so survivors re-rank NOW instead of waiting out
        heartbeat silence. Engines drain concurrently on worker
        threads (``Engine.drain`` blocks); half the remaining grace is
        reserved for the hard-stop hooks that follow."""
        drainable = [e for e in self._engines if hasattr(e, "drain")]
        if drainable:
            budget = max(0.5, (deadline - time.monotonic()) * 0.5)
            self.logger.info(
                f"draining {len(drainable)} engine(s), budget "
                f"{budget:.1f}s")
            results = await asyncio.gather(
                *(asyncio.to_thread(e.drain, budget) for e in drainable),
                return_exceptions=True)
            for engine, ok in zip(drainable, results):
                if ok is not True:
                    self.logger.warn(
                        "engine did not drain cleanly",
                        detail=repr(ok) if isinstance(ok, Exception)
                        else "stragglers cut off at the deadline")
        for agent in self._agents:
            if hasattr(agent, "deregister"):
                await asyncio.to_thread(agent.deregister)

    def run(self) -> None:
        """Blocking entry point (reference run.go:15)."""
        try:
            asyncio.run(self.serve())
        except KeyboardInterrupt:
            pass


def new_app(config_dir: str = "configs", config=None) -> App:
    return App(config_dir=config_dir, config=config)


def new_cmd(config_dir: str = "configs", config=None):
    """CLI application factory (reference factory.go:81)."""
    from .cli.cmd import CMDApp
    return CMDApp(config_dir=config_dir, config=config)
