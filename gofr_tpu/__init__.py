"""gofr_tpu — a TPU-native service framework.

GoFr's developer surface (``app = gofr_tpu.App(); app.post("/chat", h);
app.run()`` — cf. reference pkg/gofr/factory.go:17, rest.go:9-31) with a
JAX/XLA/Pallas execution backend for ML routes: models, continuous
batching, paged KV caches, and mesh-sharded multi-chip serving.

Subpackages
-----------
- ``config``/``logging``/``metrics``/``tracing`` — the kernel layers.
- ``container``/``context`` — dependency-injection hub + handler context.
- ``http`` — asyncio HTTP server, router, middleware, responder.
- ``service`` — resilient inter-service HTTP clients.
- ``pubsub``/``cron``/``migrations``/``websocket``/``cli`` — app runtimes.
- ``ops``/``models``/``parallel``/``serving`` — the TPU compute stack.

Heavy imports (jax & friends) are deferred: importing :mod:`gofr_tpu`
alone pulls only the service-framework layers.
"""

from .version import FRAMEWORK as __version__  # noqa: F401

_LAZY: dict[str, tuple[str, str]] = {
    "App": ("gofr_tpu.app", "App"),
    "new_app": ("gofr_tpu.app", "new_app"),
    "new_cmd": ("gofr_tpu.app", "new_cmd"),
    "Context": ("gofr_tpu.context", "Context"),
    "Container": ("gofr_tpu.container.container", "Container"),
    "MockContainer": ("gofr_tpu.container.mock", "MockContainer"),
}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib
        module, attr = _LAZY[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f"module 'gofr_tpu' has no attribute {name!r}")
