"""Metrics manager with Prometheus text exposition.

Mirrors the reference's metrics surface (pkg/gofr/metrics/register.go:16-51):
``new_counter / new_up_down_counter / new_histogram / new_gauge`` to
register, and ``increment_counter / delta_up_down_counter /
record_histogram / set_gauge`` to write — all label-aware, all
thread-safe, all served in Prometheus text format on the dedicated
metrics port (reference metrics/handler.go:13, metrics_server.go:14-49).

The implementation is self-contained (no OTel SDK dependency): a typed
store keyed by metric name -> labelset -> value, like the reference's
``store.go:9-28``, rendered on scrape.

Fleet federation: ``Manager.snapshot()`` dumps every metric as a
JSON-safe structure a worker can attach to a control-plane heartbeat;
:func:`merge_snapshots` aggregates per-host snapshots (counters sum,
gauges keep per-host under a ``host`` label, histograms merge bucket
counts) and :func:`render_federated` renders per-host snapshots as one
Prometheus exposition with caller-chosen extra labels (``host``/
``rank``) on every sample — the leader's ``/control/fleet/metrics``.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Iterable, Mapping

from ..logging.logger import current_trace_ids

DEFAULT_BUCKETS = (0.001, 0.003, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 0.75, 1, 2, 3, 5, 10, 30)


class MetricsError(Exception):
    pass


def _labels_key(labels: Mapping[str, str]) -> tuple[tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


def _fmt_labels(key: tuple[tuple[str, str], ...]) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in key)
    return "{" + inner + "}"


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v)


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, description: str) -> None:
        self.name = name
        self.description = description
        self._values: dict[tuple[tuple[str, str], ...], float] = {}
        self._lock = threading.Lock()

    def _bump(self, delta: float, labels: Mapping[str, str]) -> None:
        key = _labels_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + delta

    def _set(self, value: float, labels: Mapping[str, str]) -> None:
        key = _labels_key(labels)
        with self._lock:
            self._values[key] = value

    def get(self, **labels: str) -> float:
        return self._values.get(_labels_key(labels), 0.0)

    def snapshot(self) -> dict:
        """JSON-safe dump: kind, help text, and every labeled series."""
        with self._lock:
            series = [{"labels": dict(k), "value": v}
                      for k, v in self._values.items()]
        return {"kind": self.kind, "help": self.description,
                "series": series}

    def render(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.description}"
        yield f"# TYPE {self.name} {self.kind}"
        with self._lock:
            items = list(self._values.items())
        for key, value in items:
            yield f"{self.name}{_fmt_labels(key)} {_fmt_value(value)}"


class Counter(_Metric):
    kind = "counter"


class UpDownCounter(_Metric):
    kind = "gauge"  # prometheus has no updown type; exposed as gauge

    def snapshot(self) -> dict:
        out = super().snapshot()
        # renders as a gauge, but deltas are additive across hosts —
        # merge_snapshots sums these instead of keeping per-host
        out["updown"] = True
        return out


class Gauge(_Metric):
    kind = "gauge"


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str,
                 buckets: Iterable[float] = DEFAULT_BUCKETS) -> None:
        super().__init__(name, description)
        self.buckets = tuple(sorted(buckets))
        # labelset -> (bucket_counts, sum, count)
        self._hist: dict[tuple[tuple[str, str], ...], tuple[list[int], float, int]] = {}
        # labelset -> per-bucket latest exemplar (trace_id, value, ts);
        # index len(buckets) is the +Inf bucket. Memory is bounded by
        # labelsets x (buckets + 1); rendered only on the OpenMetrics
        # content-negotiated path, so plain Prometheus output is
        # byte-identical with exemplars on or off.
        self._exemplars: dict[tuple[tuple[str, str], ...],
                              list[tuple[str, float, float] | None]] = {}

    def record(self, value: float, labels: Mapping[str, str],
               trace_id: str | None = None) -> None:
        """Record an observation; optionally capture an exemplar trace
        id. ``trace_id=None`` falls back to the active request's trace
        (the logging contextvar the tracer middleware sets) — a cheap
        host-side read; call sites off any request context (the engine
        thread) pass the retired request's own trace id explicitly."""
        if trace_id is None:
            ids = current_trace_ids()
            trace_id = ids[0] if ids else None
        key = _labels_key(labels)
        with self._lock:
            counts, total, n = self._hist.get(key, ([0] * len(self.buckets), 0.0, 0))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._hist[key] = (counts, total + value, n + 1)
            if trace_id:
                ex = self._exemplars.get(key)
                if ex is None:
                    ex = [None] * (len(self.buckets) + 1)
                    self._exemplars[key] = ex
                idx = next((i for i, b in enumerate(self.buckets)
                            if value <= b), len(self.buckets))
                ex[idx] = (trace_id, value, time.time())

    def get_count(self, **labels: str) -> int:
        # under _lock: a concurrent record() replaces the entry tuple
        # and mutates the bucket list in place — an unlocked read can
        # observe a half-updated (counts, sum, n) triple
        with self._lock:
            entry = self._hist.get(_labels_key(labels))
            return entry[2] if entry else 0

    def get_sum(self, **labels: str) -> float:
        with self._lock:
            entry = self._hist.get(_labels_key(labels))
            return entry[1] if entry else 0.0

    def snapshot(self) -> dict:
        with self._lock:
            series = [{"labels": dict(k), "counts": list(c),
                       "sum": s, "count": n}
                      for k, (c, s, n) in self._hist.items()]
        return {"kind": "histogram", "help": self.description,
                "buckets": list(self.buckets), "series": series}

    def render(self) -> Iterable[str]:
        yield from self._render(exemplars=False)

    def render_openmetrics(self) -> Iterable[str]:
        """Same exposition plus OpenMetrics exemplars on bucket lines:
        ``name_bucket{le="..."} 7 # {trace_id="..."} 0.093 <ts>`` —
        the hook a Grafana/Prometheus exemplar query follows from a
        bad latency bucket straight to the ``engine.request`` trace."""
        yield from self._render(exemplars=True)

    def _render(self, exemplars: bool) -> Iterable[str]:
        yield f"# HELP {self.name} {self.description}"
        yield f"# TYPE {self.name} histogram"
        with self._lock:
            items = [(k, ([*c], s, n)) for k, (c, s, n) in self._hist.items()]
            ex = {k: list(v) for k, v in self._exemplars.items()} \
                if exemplars else {}

        def tail(key: tuple, idx: int) -> str:
            e = ex.get(key)
            if not e or e[idx] is None:
                return ""
            trace_id, value, ts = e[idx]
            return (f' # {{trace_id="{_escape(trace_id)}"}} '
                    f"{_fmt_value(value)} {round(ts, 3)}")

        for key, (counts, total, n) in items:
            for i, (bucket, count) in enumerate(zip(self.buckets, counts)):
                bkey = key + (("le", _fmt_value(float(bucket))),)
                yield (f"{self.name}_bucket"
                       f"{_fmt_labels(tuple(sorted(bkey)))} {count}"
                       + tail(key, i))
            inf_key = key + (("le", "+Inf"),)
            yield (f"{self.name}_bucket"
                   f"{_fmt_labels(tuple(sorted(inf_key)))} {n}"
                   + tail(key, len(self.buckets)))
            yield f"{self.name}_sum{_fmt_labels(key)} {_fmt_value(total)}"
            yield f"{self.name}_count{_fmt_labels(key)} {n}"


class Manager:
    """Register-then-write metrics facade (reference register.go:16)."""

    def __init__(self, logger=None) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self._logger = logger

    def _register(self, metric: _Metric) -> None:
        with self._lock:
            if metric.name in self._metrics:
                raise MetricsError(f"metric {metric.name!r} already registered")
            self._metrics[metric.name] = metric

    # -- registration
    def new_counter(self, name: str, description: str) -> Counter:
        m = Counter(name, description)
        self._register(m)
        return m

    def new_up_down_counter(self, name: str, description: str) -> UpDownCounter:
        m = UpDownCounter(name, description)
        self._register(m)
        return m

    def new_histogram(self, name: str, description: str,
                      buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        m = Histogram(name, description, buckets)
        self._register(m)
        return m

    def new_gauge(self, name: str, description: str) -> Gauge:
        m = Gauge(name, description)
        self._register(m)
        return m

    # -- writes (no-op with a warning on unknown names, like the reference)
    def _lookup(self, name: str, kind: type) -> _Metric | None:
        m = self._metrics.get(name)
        if m is None or not isinstance(m, kind):
            if self._logger is not None:
                self._logger.error(f"metric {name!r} not registered as {kind.__name__}")
            return None
        return m

    def increment_counter(self, name: str, **labels: str) -> None:
        m = self._lookup(name, Counter)
        if m is not None:
            m._bump(1.0, labels)

    def add_counter(self, name: str, value: float, **labels: str) -> None:
        m = self._lookup(name, Counter)
        if m is not None:
            m._bump(value, labels)

    def delta_up_down_counter(self, name: str, delta: float, **labels: str) -> None:
        m = self._lookup(name, UpDownCounter)
        if m is not None:
            m._bump(delta, labels)

    def record_histogram(self, name: str, value: float, *,
                         exemplar_trace_id: str | None = None,
                         **labels: str) -> None:
        m = self._lookup(name, Histogram)
        if m is not None:
            m.record(value, labels, trace_id=exemplar_trace_id)

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        m = self._lookup(name, Gauge)
        if m is not None:
            m._set(value, labels)

    def get(self, name: str) -> _Metric | None:
        return self._metrics.get(name)

    def get_histogram_count(self, name: str, **labels: str) -> int:
        m = self._lookup(name, Histogram)
        return 0 if m is None else m.get_count(**labels)

    # -- scrape
    def render_prometheus(self, prefix: str | None = None) -> str:
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: list[str] = []
        for m in metrics:
            if prefix is not None and not m.name.startswith(prefix):
                continue
            lines.extend(m.render())
        return "\n".join(lines) + "\n" if lines else ""

    def render_openmetrics(self, prefix: str | None = None) -> str:
        """The ``application/openmetrics-text`` exposition: identical
        families and samples to :meth:`render_prometheus`, plus
        exemplars on histogram bucket lines and the ``# EOF``
        terminator OpenMetrics parsers require. Served when a scraper
        content-negotiates for it (the app's metrics handler)."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: list[str] = []
        for m in metrics:
            if prefix is not None and not m.name.startswith(prefix):
                continue
            if isinstance(m, Histogram):
                lines.extend(m.render_openmetrics())
            else:
                lines.extend(m.render())
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    # -- federation
    def snapshot(self) -> dict:
        """Structured dump of every registered metric — the payload a
        worker attaches to its control-plane heartbeat. Pure host-side
        reads under each metric's lock; JSON-serializable as-is."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        return {"v": 1, "metrics": {m.name: m.snapshot() for m in metrics}}


def merge_snapshots(per_host: Mapping[str, Mapping]) -> dict:
    """Aggregate per-host ``Manager.snapshot()`` payloads into one
    fleet view: counters (and up/down counters) SUM across hosts per
    identical labelset, gauges KEEP per-host (a ``host`` label is
    added), histograms MERGE bucket counts/sums per labelset when the
    bucket layouts agree (mismatched layouts fall back to per-host
    series under a ``host`` label, never silently mixed)."""
    families: dict[str, dict] = {}
    for host in sorted(per_host):
        snap = per_host[host] or {}
        for name, fam in (snap.get("metrics") or {}).items():
            kind = fam.get("kind", "untyped")
            tgt = families.setdefault(name, {
                "kind": kind, "help": fam.get("help", ""),
                "_sums": {}, "_hists": {}, "_per_host": [],
                "buckets": fam.get("buckets")})
            for s in fam.get("series", ()):
                labels = dict(s.get("labels") or {})
                key = _labels_key(labels)
                if kind == "counter" or (kind == "gauge"
                                         and "counts" not in s
                                         and fam.get("updown")):
                    tgt["_sums"][key] = (tgt["_sums"].get(key, 0.0)
                                         + float(s.get("value", 0.0)))
                elif kind == "histogram":
                    if fam.get("buckets") != tgt["buckets"]:
                        tgt["_per_host"].append(
                            {**s, "labels": {**labels, "host": host}})
                        continue
                    counts, total, n = tgt["_hists"].get(
                        key, ([0] * len(tgt["buckets"] or ()), 0.0, 0))
                    merged = [a + b for a, b in
                              zip(counts, s.get("counts", ()))]
                    tgt["_hists"][key] = (merged,
                                          total + float(s.get("sum", 0.0)),
                                          n + int(s.get("count", 0)))
                else:  # gauge / untyped: per-host identity matters
                    tgt["_per_host"].append(
                        {**s, "labels": {**labels, "host": host}})
    out: dict[str, dict] = {}
    for name, fam in families.items():
        series: list[dict] = []
        series.extend({"labels": dict(k), "value": v}
                      for k, v in fam["_sums"].items())
        series.extend({"labels": dict(k), "counts": c, "sum": s,
                       "count": n}
                      for k, (c, s, n) in fam["_hists"].items())
        series.extend(fam["_per_host"])
        entry = {"kind": fam["kind"], "help": fam["help"],
                 "series": series}
        if fam["kind"] == "histogram":
            entry["buckets"] = fam["buckets"]
        out[name] = entry
    return {"v": 1, "metrics": out}


def render_federated(per_host: Mapping[str, Mapping],
                     extra_labels: Mapping[str, Mapping[str, str]]
                     | None = None) -> str:
    """Render per-host snapshots as ONE Prometheus exposition: each
    family's HELP/TYPE appears once, every sample carries the caller's
    extra labels for its host (``{"host": ..., "rank": ...}``). Used by
    the leader's ``GET /control/fleet/metrics``; summing a counter
    over its ``host`` label reproduces the fleet total."""
    names: dict[str, dict] = {}
    for host in per_host:
        for name, fam in ((per_host[host] or {}).get("metrics")
                          or {}).items():
            names.setdefault(name, fam)
    lines: list[str] = []
    for name in sorted(names):
        first = names[name]
        kind = first.get("kind", "untyped")
        lines.append(f"# HELP {name} {first.get('help', '')}")
        lines.append(f"# TYPE {name} {kind}")
        for host in sorted(per_host):
            fam = ((per_host[host] or {}).get("metrics") or {}).get(name)
            if fam is None:
                continue
            extra = dict((extra_labels or {}).get(host)
                         or ({"host": host} if host else {}))
            buckets = fam.get("buckets") or ()
            for s in fam.get("series", ()):
                key = _labels_key({**(s.get("labels") or {}), **extra})
                if kind == "histogram":
                    counts = s.get("counts", ())
                    n = int(s.get("count", 0))
                    for bucket, count in zip(buckets, counts):
                        bkey = _labels_key(dict(
                            key + (("le", _fmt_value(float(bucket))),)))
                        lines.append(
                            f"{name}_bucket{_fmt_labels(bkey)} {count}")
                    ikey = _labels_key(dict(key + (("le", "+Inf"),)))
                    lines.append(f"{name}_bucket{_fmt_labels(ikey)} {n}")
                    lines.append(f"{name}_sum{_fmt_labels(key)} "
                                 f"{_fmt_value(float(s.get('sum', 0.0)))}")
                    lines.append(f"{name}_count{_fmt_labels(key)} {n}")
                else:
                    lines.append(f"{name}{_fmt_labels(key)} "
                                 f"{_fmt_value(float(s.get('value', 0.0)))}")
    return "\n".join(lines) + "\n" if lines else ""
