"""Metrics manager with Prometheus text exposition.

Mirrors the reference's metrics surface (pkg/gofr/metrics/register.go:16-51):
``new_counter / new_up_down_counter / new_histogram / new_gauge`` to
register, and ``increment_counter / delta_up_down_counter /
record_histogram / set_gauge`` to write — all label-aware, all
thread-safe, all served in Prometheus text format on the dedicated
metrics port (reference metrics/handler.go:13, metrics_server.go:14-49).

The implementation is self-contained (no OTel SDK dependency): a typed
store keyed by metric name -> labelset -> value, like the reference's
``store.go:9-28``, rendered on scrape.
"""

from __future__ import annotations

import math
import threading
from typing import Iterable, Mapping

DEFAULT_BUCKETS = (0.001, 0.003, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 0.75, 1, 2, 3, 5, 10, 30)


class MetricsError(Exception):
    pass


def _labels_key(labels: Mapping[str, str]) -> tuple[tuple[str, str], ...]:
    return tuple(sorted(labels.items()))


def _fmt_labels(key: tuple[tuple[str, str], ...]) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in key)
    return "{" + inner + "}"


def _escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v)


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, description: str) -> None:
        self.name = name
        self.description = description
        self._values: dict[tuple[tuple[str, str], ...], float] = {}
        self._lock = threading.Lock()

    def _bump(self, delta: float, labels: Mapping[str, str]) -> None:
        key = _labels_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + delta

    def _set(self, value: float, labels: Mapping[str, str]) -> None:
        key = _labels_key(labels)
        with self._lock:
            self._values[key] = value

    def get(self, **labels: str) -> float:
        return self._values.get(_labels_key(labels), 0.0)

    def render(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.description}"
        yield f"# TYPE {self.name} {self.kind}"
        with self._lock:
            items = list(self._values.items())
        for key, value in items:
            yield f"{self.name}{_fmt_labels(key)} {_fmt_value(value)}"


class Counter(_Metric):
    kind = "counter"


class UpDownCounter(_Metric):
    kind = "gauge"  # prometheus has no updown type; exposed as gauge


class Gauge(_Metric):
    kind = "gauge"


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, description: str,
                 buckets: Iterable[float] = DEFAULT_BUCKETS) -> None:
        super().__init__(name, description)
        self.buckets = tuple(sorted(buckets))
        # labelset -> (bucket_counts, sum, count)
        self._hist: dict[tuple[tuple[str, str], ...], tuple[list[int], float, int]] = {}

    def record(self, value: float, labels: Mapping[str, str]) -> None:
        key = _labels_key(labels)
        with self._lock:
            counts, total, n = self._hist.get(key, ([0] * len(self.buckets), 0.0, 0))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._hist[key] = (counts, total + value, n + 1)

    def get_count(self, **labels: str) -> int:
        # under _lock: a concurrent record() replaces the entry tuple
        # and mutates the bucket list in place — an unlocked read can
        # observe a half-updated (counts, sum, n) triple
        with self._lock:
            entry = self._hist.get(_labels_key(labels))
            return entry[2] if entry else 0

    def get_sum(self, **labels: str) -> float:
        with self._lock:
            entry = self._hist.get(_labels_key(labels))
            return entry[1] if entry else 0.0

    def render(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.description}"
        yield f"# TYPE {self.name} histogram"
        with self._lock:
            items = [(k, ([*c], s, n)) for k, (c, s, n) in self._hist.items()]
        for key, (counts, total, n) in items:
            for bucket, count in zip(self.buckets, counts):
                bkey = key + (("le", _fmt_value(float(bucket))),)
                yield f"{self.name}_bucket{_fmt_labels(tuple(sorted(bkey)))} {count}"
            inf_key = key + (("le", "+Inf"),)
            yield f"{self.name}_bucket{_fmt_labels(tuple(sorted(inf_key)))} {n}"
            yield f"{self.name}_sum{_fmt_labels(key)} {_fmt_value(total)}"
            yield f"{self.name}_count{_fmt_labels(key)} {n}"


class Manager:
    """Register-then-write metrics facade (reference register.go:16)."""

    def __init__(self, logger=None) -> None:
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()
        self._logger = logger

    def _register(self, metric: _Metric) -> None:
        with self._lock:
            if metric.name in self._metrics:
                raise MetricsError(f"metric {metric.name!r} already registered")
            self._metrics[metric.name] = metric

    # -- registration
    def new_counter(self, name: str, description: str) -> Counter:
        m = Counter(name, description)
        self._register(m)
        return m

    def new_up_down_counter(self, name: str, description: str) -> UpDownCounter:
        m = UpDownCounter(name, description)
        self._register(m)
        return m

    def new_histogram(self, name: str, description: str,
                      buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        m = Histogram(name, description, buckets)
        self._register(m)
        return m

    def new_gauge(self, name: str, description: str) -> Gauge:
        m = Gauge(name, description)
        self._register(m)
        return m

    # -- writes (no-op with a warning on unknown names, like the reference)
    def _lookup(self, name: str, kind: type) -> _Metric | None:
        m = self._metrics.get(name)
        if m is None or not isinstance(m, kind):
            if self._logger is not None:
                self._logger.error(f"metric {name!r} not registered as {kind.__name__}")
            return None
        return m

    def increment_counter(self, name: str, **labels: str) -> None:
        m = self._lookup(name, Counter)
        if m is not None:
            m._bump(1.0, labels)

    def add_counter(self, name: str, value: float, **labels: str) -> None:
        m = self._lookup(name, Counter)
        if m is not None:
            m._bump(value, labels)

    def delta_up_down_counter(self, name: str, delta: float, **labels: str) -> None:
        m = self._lookup(name, UpDownCounter)
        if m is not None:
            m._bump(delta, labels)

    def record_histogram(self, name: str, value: float, **labels: str) -> None:
        m = self._lookup(name, Histogram)
        if m is not None:
            m.record(value, labels)

    def set_gauge(self, name: str, value: float, **labels: str) -> None:
        m = self._lookup(name, Gauge)
        if m is not None:
            m._set(value, labels)

    def get(self, name: str) -> _Metric | None:
        return self._metrics.get(name)

    def get_histogram_count(self, name: str, **labels: str) -> int:
        m = self._lookup(name, Histogram)
        return 0 if m is None else m.get_count(**labels)

    # -- scrape
    def render_prometheus(self) -> str:
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: list[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"
