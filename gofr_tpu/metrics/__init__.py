from .registry import (
    Counter,
    Gauge,
    Histogram,
    Manager,
    MetricsError,
    UpDownCounter,
)

__all__ = ["Counter", "Gauge", "Histogram", "Manager", "MetricsError", "UpDownCounter"]
