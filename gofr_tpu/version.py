"""Framework version, mirroring the reference's version package.

Reference: /root/reference/pkg/gofr/version/version.go:1-3
"""

FRAMEWORK = "0.1.0-dev"
