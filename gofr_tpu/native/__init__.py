"""Native (C++) runtime components.

The reference framework is pure Go; this build's runtime-side hot
paths are C++ compiled on demand (build.py) with pure-Python fallbacks
so nothing ever *requires* a toolchain:

- :mod:`.bpe` — byte-pair tokenizer merge loop (serving admission).
- :mod:`.batch_queue` — waitable MPMC batch queue (continuous-batching
  admission; blocking pops release the GIL).

The TPU compute path stays JAX/XLA/Pallas — the native layer is the
host runtime around it, mirroring how the reference keeps its runtime
(routers, schedulers, IO) in its systems language.
"""

from .build import NativeBuildError, available, compiler  # noqa: F401
