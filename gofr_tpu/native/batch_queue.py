"""ctypes binding for the C++ waitable batch queue (src/batchq.cpp).

``BatchQueue`` stores opaque uint64 handles; :class:`RequestQueue`
wraps it into a put/pop_batch queue of Python objects for the serving
engine, with a pure-Python fallback (``PyRequestQueue``) when no
compiler is present. Blocking pops release the GIL, so producers
(HTTP handler threads) run while the engine thread waits.
"""

from __future__ import annotations

import ctypes
import itertools
import queue as queue_mod
import threading
import time
from typing import Any

from .build import NativeBuildError, load_library


class BatchQueue:
    """Thin uint64 queue over the C ABI."""

    def __init__(self, capacity: int = 0) -> None:
        self._lib = load_library("batchq")
        self._lib.bq_create.restype = ctypes.c_void_p
        self._lib.bq_create.argtypes = [ctypes.c_long]
        self._lib.bq_push.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        self._lib.bq_pop_batch.restype = ctypes.c_long
        self._lib.bq_pop_batch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_long, ctypes.c_long, ctypes.c_long]
        self._lib.bq_size.restype = ctypes.c_long
        self._lib.bq_size.argtypes = [ctypes.c_void_p]
        self._lib.bq_close.argtypes = [ctypes.c_void_p]
        self._lib.bq_destroy.argtypes = [ctypes.c_void_p]
        self._handle = ctypes.c_void_p(self._lib.bq_create(capacity))

    def push(self, item: int) -> bool:
        """False when full or closed."""
        return self._lib.bq_push(self._handle, item) == 0

    def pop_batch(self, max_n: int, first_wait_s: float = 0.1,
                  drain_wait_s: float = 0.0) -> list[int] | None:
        """Block up to ``first_wait_s`` for one item, drain up to
        ``max_n`` (waiting ``drain_wait_s`` for stragglers).
        ``None`` = closed and drained; ``[]`` = timed out."""
        out = (ctypes.c_uint64 * max_n)()
        n = self._lib.bq_pop_batch(self._handle, out, max_n,
                                   int(first_wait_s * 1e6),
                                   int(drain_wait_s * 1e6))
        if n == -2:
            return None
        return list(out[:max(n, 0)])

    def size(self) -> int:
        return int(self._lib.bq_size(self._handle))

    def close(self) -> None:
        self._lib.bq_close(self._handle)

    def __del__(self) -> None:
        lib = getattr(self, "_lib", None)
        handle = getattr(self, "_handle", None)
        if lib is not None and handle:
            lib.bq_destroy(handle)


class RequestQueue:
    """Object queue over :class:`BatchQueue`: ids go through the native
    queue, the objects stay in a Python-side table."""

    def __init__(self, capacity: int = 0) -> None:
        self._q = BatchQueue(capacity)
        self._items: dict[int, Any] = {}
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    def put(self, item: Any) -> bool:
        item_id = next(self._ids)
        with self._lock:
            self._items[item_id] = item
        if not self._q.push(item_id):
            with self._lock:
                self._items.pop(item_id, None)
            return False
        return True

    def pop_batch(self, max_n: int, first_wait_s: float = 0.1,
                  drain_wait_s: float = 0.0) -> list[Any] | None:
        ids = self._q.pop_batch(max_n, first_wait_s, drain_wait_s)
        if ids is None:
            return None
        with self._lock:
            return [self._items.pop(i) for i in ids if i in self._items]

    def get_nowait(self) -> Any:
        """queue.Queue-compatible accessor (raises queue.Empty)."""
        batch = self.pop_batch(1, first_wait_s=0.0)
        if not batch:
            raise queue_mod.Empty
        return batch[0]

    def qsize(self) -> int:
        return self._q.size()

    def close(self) -> None:
        self._q.close()


class PyRequestQueue:
    """Pure-Python fallback with identical semantics."""

    def __init__(self, capacity: int = 0) -> None:
        self._q: queue_mod.Queue = queue_mod.Queue(capacity or 0)
        self._closed = False

    def put(self, item: Any) -> bool:
        if self._closed:
            return False
        try:
            self._q.put_nowait(item)
            return True
        except queue_mod.Full:
            return False

    def pop_batch(self, max_n: int, first_wait_s: float = 0.1,
                  drain_wait_s: float = 0.0) -> list[Any] | None:
        out: list[Any] = []
        # grab already-queued work even at zero wait (the engine's busy
        # path polls with first_wait_s=0.0 between decode steps)
        try:
            out.append(self._q.get_nowait())
        except queue_mod.Empty:
            pass
        deadline = time.monotonic() + first_wait_s
        while not out:
            if self._closed and self._q.empty():
                return None
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return out
            try:
                out.append(self._q.get(timeout=min(remaining, 0.05)))
            except queue_mod.Empty:
                continue
        while len(out) < max_n:
            try:
                out.append(self._q.get(timeout=drain_wait_s or 0.0001))
            except queue_mod.Empty:
                break
        return out

    def get_nowait(self) -> Any:
        """queue.Queue-compatible accessor (raises queue.Empty)."""
        return self._q.get_nowait()

    def qsize(self) -> int:
        return self._q.qsize()

    def close(self) -> None:
        self._closed = True


def new_request_queue(capacity: int = 0):
    """Native when the C++ build works, Python otherwise. Any build or
    dlopen failure (no compiler, unwritable cache dir, corrupt cached
    .so, compile timeout) falls back — the queue must never be the
    reason an engine cannot construct."""
    try:
        return RequestQueue(capacity)
    except Exception:
        return PyRequestQueue(capacity)
