"""ctypes binding for the C++ BPE encoder (src/bpe.cpp).

``load(ranks)`` builds a native encoder from a ``bytes -> rank`` table
(tiktoken style, id == merge priority); ``load(ranks, merge_ranks)``
builds the HF tokenizer.json style where the merges list supplies
priorities and the vocab supplies ids. ``NativeBPE.encode`` releases
the GIL for the merge loop and takes optional pre-tokenizer piece
boundaries (byte offsets merges may not cross). Raises
``NativeBuildError`` when no compiler is available — the caller
(serving/tokenizer.py) falls back to pure Python.
"""

from __future__ import annotations

import ctypes

from .build import load_library


class NativeBPE:
    def __init__(self, ranks: dict[bytes, int],
                 merge_ranks: dict[bytes, int] | None = None) -> None:
        self._lib = load_library("bpe")
        self._lib.bpe_create.restype = ctypes.c_void_p
        self._lib.bpe_add_token.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int32]
        self._lib.bpe_add_merge.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int32]
        self._lib.bpe_encode_bounded.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
        self._lib.bpe_encode_bounded.restype = ctypes.c_int
        self._lib.bpe_destroy.argtypes = [ctypes.c_void_p]
        self._lib.bpe_finalize.argtypes = [ctypes.c_void_p]
        self._handle = ctypes.c_void_p(self._lib.bpe_create())
        for token, rank in ranks.items():
            self._lib.bpe_add_token(self._handle, token, len(token), rank)
        for piece, prio in (merge_ranks or {}).items():
            self._lib.bpe_add_merge(self._handle, piece, len(piece), prio)
        self._lib.bpe_finalize(self._handle)

    def encode(self, data: bytes,
               bounds: list[int] | None = None) -> list[int]:
        cap = max(len(data), 16)
        nb = len(bounds) if bounds else 0
        b_arr = (ctypes.c_int32 * max(nb, 1))(*(bounds or [0]))
        out = (ctypes.c_int32 * cap)()
        n = self._lib.bpe_encode_bounded(self._handle, data, len(data),
                                         b_arr, nb, out, cap)
        if n < 0:  # output overflow cannot happen with cap >= len, but be safe
            cap *= 4
            out = (ctypes.c_int32 * cap)()
            n = self._lib.bpe_encode_bounded(self._handle, data, len(data),
                                             b_arr, nb, out, cap)
        return list(out[:max(n, 0)])

    def __del__(self) -> None:
        lib = getattr(self, "_lib", None)
        handle = getattr(self, "_handle", None)
        if lib is not None and handle:
            lib.bpe_destroy(handle)


def load(ranks: dict[bytes, int],
         merge_ranks: dict[bytes, int] | None = None) -> NativeBPE:
    return NativeBPE(ranks, merge_ranks)
