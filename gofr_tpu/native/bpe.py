"""ctypes binding for the C++ BPE encoder (src/bpe.cpp).

``load(ranks)`` builds a native encoder from a ``bytes -> rank`` table;
``NativeBPE.encode`` releases the GIL for the merge loop. Raises
``NativeBuildError`` when no compiler is available — the caller
(serving/tokenizer.py) falls back to pure Python.
"""

from __future__ import annotations

import ctypes

from .build import load_library


class NativeBPE:
    def __init__(self, ranks: dict[bytes, int]) -> None:
        self._lib = load_library("bpe")
        self._lib.bpe_create.restype = ctypes.c_void_p
        self._lib.bpe_add_token.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int, ctypes.c_int32]
        self._lib.bpe_encode.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
        self._lib.bpe_encode.restype = ctypes.c_int
        self._lib.bpe_destroy.argtypes = [ctypes.c_void_p]
        self._lib.bpe_finalize.argtypes = [ctypes.c_void_p]
        self._handle = ctypes.c_void_p(self._lib.bpe_create())
        for token, rank in ranks.items():
            self._lib.bpe_add_token(self._handle, token, len(token), rank)
        self._lib.bpe_finalize(self._handle)

    def encode(self, data: bytes) -> list[int]:
        cap = max(len(data), 16)
        out = (ctypes.c_int32 * cap)()
        n = self._lib.bpe_encode(self._handle, data, len(data), out, cap)
        if n < 0:  # output overflow cannot happen with cap >= len, but be safe
            cap *= 4
            out = (ctypes.c_int32 * cap)()
            n = self._lib.bpe_encode(self._handle, data, len(data), out, cap)
        return list(out[:max(n, 0)])

    def __del__(self) -> None:
        lib = getattr(self, "_lib", None)
        handle = getattr(self, "_handle", None)
        if lib is not None and handle:
            lib.bpe_destroy(handle)


def load(ranks: dict[bytes, int]) -> NativeBPE:
    return NativeBPE(ranks)
