// Waitable MPMC batch queue — the admission primitive of the
// continuous-batching engine.
//
// The serving engine's loop needs "block until at least one request,
// then greedily drain up to max_n without oversleeping" semantics.
// Doing that over Python's queue.Queue costs a GIL round-trip per item
// per wake; this condition-variable queue is called once per batch via
// ctypes (GIL released while blocked, so producers run while the
// engine thread waits — and the bench/engine hot loop never sleeps in
// Python).
//
// Items are opaque uint64 handles (the Python side keeps id -> request).
//
// C ABI:
//   bq_create(capacity) -> handle
//   bq_push(handle, item) -> 0 | -1 full | -2 closed
//   bq_pop_batch(handle, out, max_n, first_wait_us, drain_wait_us)
//       -> n >= 0 (0 = timed out empty) | -2 closed-and-drained
//   bq_size(handle), bq_close(handle), bq_destroy(handle)

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <mutex>

namespace {

struct BatchQueue {
    std::mutex mu;
    std::condition_variable not_empty;
    std::condition_variable not_full;
    std::condition_variable drained;  // destroy handshake
    std::deque<uint64_t> items;
    size_t capacity;
    int waiters = 0;  // threads inside a blocking wait
    bool closed = false;

    explicit BatchQueue(size_t cap) : capacity(cap) {}
};

struct WaiterGuard {
    BatchQueue* q;  // mu must be held at construction and destruction
    explicit WaiterGuard(BatchQueue* queue) : q(queue) { q->waiters++; }
    ~WaiterGuard() {
        if (--q->waiters == 0) q->drained.notify_all();
    }
};

}  // namespace

extern "C" {

void* bq_create(long capacity) {
    return new BatchQueue(capacity > 0 ? static_cast<size_t>(capacity)
                                       : SIZE_MAX);
}

int bq_push(void* h, uint64_t item) {
    auto* q = static_cast<BatchQueue*>(h);
    std::unique_lock<std::mutex> lock(q->mu);
    if (q->closed) return -2;
    if (q->items.size() >= q->capacity) return -1;
    q->items.push_back(item);
    lock.unlock();
    q->not_empty.notify_one();
    return 0;
}

long bq_pop_batch(void* h, uint64_t* out, long max_n, long first_wait_us,
                  long drain_wait_us) {
    auto* q = static_cast<BatchQueue*>(h);
    std::unique_lock<std::mutex> lock(q->mu);
    WaiterGuard guard(q);
    if (q->items.empty() && !q->closed) {
        q->not_empty.wait_for(lock, std::chrono::microseconds(first_wait_us),
                              [q] { return !q->items.empty() || q->closed; });
    }
    if (q->items.empty()) return q->closed ? -2 : 0;

    long n = 0;
    auto grab = [&] {
        while (n < max_n && !q->items.empty()) {
            out[n++] = q->items.front();
            q->items.pop_front();
        }
    };
    grab();
    // opportunistic drain: brief extra window to coalesce stragglers
    // into this device batch (continuous-batching flush deadline)
    while (n < max_n && drain_wait_us > 0 && !q->closed) {
        if (!q->not_empty.wait_for(lock,
                                   std::chrono::microseconds(drain_wait_us),
                                   [q] { return !q->items.empty() ||
                                                q->closed; }))
            break;
        grab();
    }
    // notify under the lock and let WaiterGuard destruct while it is
    // still held — an early unlock would decrement waiters/notify
    // drained unsynchronized, racing bq_destroy into use-after-free
    q->not_full.notify_all();
    return n;
}

long bq_size(void* h) {
    auto* q = static_cast<BatchQueue*>(h);
    std::lock_guard<std::mutex> lock(q->mu);
    return static_cast<long>(q->items.size());
}

void bq_close(void* h) {
    auto* q = static_cast<BatchQueue*>(h);
    {
        std::lock_guard<std::mutex> lock(q->mu);
        q->closed = true;
    }
    q->not_empty.notify_all();
    q->not_full.notify_all();
}

void bq_destroy(void* h) {
    auto* q = static_cast<BatchQueue*>(h);
    {
        // close, wake everyone, and wait for blocked poppers to leave
        // before freeing the mutex/cvs they are waiting on
        std::unique_lock<std::mutex> lock(q->mu);
        q->closed = true;
        q->not_empty.notify_all();
        q->not_full.notify_all();
        q->drained.wait(lock, [q] { return q->waiters == 0; });
    }
    delete q;
}

}  // extern "C"
