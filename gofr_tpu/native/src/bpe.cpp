// Byte-pair encoder — the host-side tokenization hot path.
//
// The serving engine tokenizes every request on the CPU before the
// device sees it; in Python the greedy merge loop dominates request
// admission at high QPS. This implements the classic rank-based BPE
// merge with a doubly-linked part list + lazy min-heap: O(n log n)
// over the text instead of the O(n^2) scan of the Python fallback
// (gofr_tpu/serving/tokenizer.py:_bpe_merge), called through ctypes
// (which releases the GIL, so tokenization overlaps device steps).
//
// C ABI:
//   bpe_create() -> handle
//   bpe_add_token(handle, bytes, len, rank)   // build vocabulary
//   bpe_finalize(handle)                      // index pairs
//   bpe_encode(handle, text, len, out, cap) -> n tokens (or -1 overflow)
//   bpe_destroy(handle)

#include <cstdint>
#include <cstring>
#include <queue>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Encoder {
    std::unordered_map<std::string, int32_t> ranks;
};

struct Part {
    uint32_t start;   // byte offset into the text
    uint32_t len;     // current token length in bytes
    int32_t prev;     // index of previous live part, -1 at head
    int32_t next;     // index of next live part, -1 at tail
    uint64_t version; // bumped on every merge touching this part
    bool alive;
};

struct HeapEntry {
    int32_t rank;
    int32_t left;           // part index
    uint64_t left_version;  // staleness: left part grew since push
    uint32_t right_start;   // staleness: right partner replaced
    uint64_t right_version; // staleness: right partner grew since push
    bool operator>(const HeapEntry& o) const {
        if (rank != o.rank) return rank > o.rank;
        return left > o.left; // deterministic leftmost-first tie-break
    }
};

int32_t pair_rank(const Encoder* e, const uint8_t* text, const Part& a,
                  const Part& b) {
    std::string key(reinterpret_cast<const char*>(text + a.start),
                    a.len + b.len);
    auto it = e->ranks.find(key);
    return it == e->ranks.end() ? -1 : it->second;
}

}  // namespace

extern "C" {

void* bpe_create() { return new Encoder(); }

void bpe_add_token(void* h, const uint8_t* bytes, int len, int32_t rank) {
    auto* e = static_cast<Encoder*>(h);
    e->ranks.emplace(std::string(reinterpret_cast<const char*>(bytes), len),
                     rank);
}

void bpe_finalize(void*) {}  // reserved for a future pair index

int bpe_encode(void* h, const uint8_t* text, int len, int32_t* out,
               int out_cap) {
    auto* e = static_cast<Encoder*>(h);
    if (len <= 0) return 0;

    std::vector<Part> parts(len);
    for (int i = 0; i < len; ++i) {
        parts[i] = {static_cast<uint32_t>(i), 1, i - 1,
                    i + 1 < len ? i + 1 : -1, 0, true};
    }

    std::priority_queue<HeapEntry, std::vector<HeapEntry>,
                        std::greater<HeapEntry>> heap;
    for (int i = 0; i + 1 < len; ++i) {
        int32_t r = pair_rank(e, text, parts[i], parts[i + 1]);
        if (r >= 0) heap.push({r, i, 0, parts[i + 1].start, 0});
    }

    while (!heap.empty()) {
        HeapEntry top = heap.top();
        heap.pop();
        Part& a = parts[top.left];
        // exact identity: both sides unchanged since the entry was
        // pushed (either side growing through a merge bumps its version)
        if (!a.alive || a.version != top.left_version || a.next < 0)
            continue;
        Part& b = parts[a.next];
        if (b.start != top.right_start || b.version != top.right_version)
            continue;

        // merge b into a
        a.len += b.len;
        a.version++;
        b.alive = false;
        a.next = b.next;
        if (b.next >= 0) parts[b.next].prev = top.left;

        if (a.prev >= 0) {
            Part& p = parts[a.prev];
            int32_t pr = pair_rank(e, text, p, a);
            if (pr >= 0)
                heap.push({pr, a.prev, p.version, a.start, a.version});
        }
        if (a.next >= 0) {
            Part& n = parts[a.next];
            int32_t nr = pair_rank(e, text, a, n);
            if (nr >= 0)
                heap.push({nr, top.left, a.version, n.start, n.version});
        }
    }

    int n = 0;
    for (int i = 0; i >= 0; i = parts[i].next) {
        const Part& p = parts[i];
        std::string key(reinterpret_cast<const char*>(text + p.start), p.len);
        auto it = e->ranks.find(key);
        if (it != e->ranks.end()) {
            if (n >= out_cap) return -1;
            out[n++] = it->second;
        } else {
            // unmergeable span without a rank: emit known single bytes
            for (uint32_t j = 0; j < p.len; ++j) {
                std::string one(reinterpret_cast<const char*>(
                                    text + p.start + j), 1);
                auto bit = e->ranks.find(one);
                if (bit != e->ranks.end()) {
                    if (n >= out_cap) return -1;
                    out[n++] = bit->second;
                }
            }
        }
    }
    return n;
}

void bpe_destroy(void* h) { delete static_cast<Encoder*>(h); }

}  // extern "C"
