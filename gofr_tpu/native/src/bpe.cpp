// Byte-pair encoder — the host-side tokenization hot path.
//
// The serving engine tokenizes every request on the CPU before the
// device sees it; in Python the greedy merge loop dominates request
// admission at high QPS. This implements the classic rank-based BPE
// merge with a doubly-linked part list + lazy min-heap: O(n log n)
// over the text instead of the O(n^2) scan of the Python fallback
// (gofr_tpu/serving/tokenizer.py:_bpe_merge), called through ctypes
// (which releases the GIL, so tokenization overlaps device steps).
//
// Two vocabulary styles share the loop:
//   * tiktoken: the output id IS the merge priority (ranks only);
//   * HF tokenizer.json: merge priority comes from the merges list,
//     output ids from the vocab — bpe_add_merge switches the pair
//     lookup to the merge table while final emission keeps ranks.
// Pre-tokenizer boundaries (HF splits text with a regex before BPE)
// ride the same native call as byte offsets merges may not cross, so
// a whole request still tokenizes in ONE GIL-released call.
//
// C ABI:
//   bpe_create() -> handle
//   bpe_add_token(handle, bytes, len, rank)   // build vocabulary
//   bpe_add_merge(handle, bytes, len, prio)   // optional HF merge table
//   bpe_finalize(handle)                      // index pairs
//   bpe_encode(handle, text, len, out, cap) -> n tokens (or -1 overflow)
//   bpe_encode_bounded(handle, text, len, bounds, nbounds, out, cap)
//       // bounds: sorted byte offsets starting a new piece
//   bpe_destroy(handle)

#include <cstdint>
#include <cstring>
#include <queue>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct Encoder {
    std::unordered_map<std::string, int32_t> ranks;   // piece -> id
    std::unordered_map<std::string, int32_t> merges;  // piece -> priority
    bool use_merges = false;
};

struct Part {
    uint32_t start;   // byte offset into the text
    uint32_t len;     // current token length in bytes
    int32_t prev;     // index of previous live part, -1 at head
    int32_t next;     // index of next live part, -1 at tail
    uint64_t version; // bumped on every merge touching this part
    bool alive;
};

struct HeapEntry {
    int32_t rank;
    int32_t left;           // part index
    uint64_t left_version;  // staleness: left part grew since push
    uint32_t right_start;   // staleness: right partner replaced
    uint64_t right_version; // staleness: right partner grew since push
    bool operator>(const HeapEntry& o) const {
        if (rank != o.rank) return rank > o.rank;
        return left > o.left; // deterministic leftmost-first tie-break
    }
};

int32_t pair_rank(const Encoder* e, const uint8_t* text, const Part& a,
                  const Part& b) {
    std::string key(reinterpret_cast<const char*>(text + a.start),
                    a.len + b.len);
    const auto& table = e->use_merges ? e->merges : e->ranks;
    auto it = table.find(key);
    return it == table.end() ? -1 : it->second;
}

}  // namespace

extern "C" {

void* bpe_create() { return new Encoder(); }

void bpe_add_token(void* h, const uint8_t* bytes, int len, int32_t rank) {
    auto* e = static_cast<Encoder*>(h);
    e->ranks.emplace(std::string(reinterpret_cast<const char*>(bytes), len),
                     rank);
}

void bpe_add_merge(void* h, const uint8_t* bytes, int len, int32_t prio) {
    auto* e = static_cast<Encoder*>(h);
    e->merges.emplace(std::string(reinterpret_cast<const char*>(bytes), len),
                      prio);
    e->use_merges = true;
}

void bpe_finalize(void*) {}  // reserved for a future pair index

int bpe_encode_bounded(void* h, const uint8_t* text, int len,
                       const int32_t* bounds, int nbounds, int32_t* out,
                       int out_cap) {
    auto* e = static_cast<Encoder*>(h);
    if (len <= 0) return 0;

    // piece boundaries: a merge may never bridge two pre-tokenizer
    // pieces — any pair whose right side STARTS a piece is forbidden
    std::vector<uint8_t> boundary(len, 0);
    for (int i = 0; i < nbounds; ++i) {
        int32_t b = bounds[i];
        if (b > 0 && b < len) boundary[b] = 1;
    }

    std::vector<Part> parts(len);
    for (int i = 0; i < len; ++i) {
        parts[i] = {static_cast<uint32_t>(i), 1, i - 1,
                    i + 1 < len ? i + 1 : -1, 0, true};
    }

    std::priority_queue<HeapEntry, std::vector<HeapEntry>,
                        std::greater<HeapEntry>> heap;
    for (int i = 0; i + 1 < len; ++i) {
        if (boundary[i + 1]) continue;
        int32_t r = pair_rank(e, text, parts[i], parts[i + 1]);
        if (r >= 0) heap.push({r, i, 0, parts[i + 1].start, 0});
    }

    while (!heap.empty()) {
        HeapEntry top = heap.top();
        heap.pop();
        Part& a = parts[top.left];
        // exact identity: both sides unchanged since the entry was
        // pushed (either side growing through a merge bumps its version)
        if (!a.alive || a.version != top.left_version || a.next < 0)
            continue;
        Part& b = parts[a.next];
        if (b.start != top.right_start || b.version != top.right_version)
            continue;

        // merge b into a
        a.len += b.len;
        a.version++;
        b.alive = false;
        a.next = b.next;
        if (b.next >= 0) parts[b.next].prev = top.left;

        if (a.prev >= 0 && !boundary[a.start]) {
            Part& p = parts[a.prev];
            int32_t pr = pair_rank(e, text, p, a);
            if (pr >= 0)
                heap.push({pr, a.prev, p.version, a.start, a.version});
        }
        if (a.next >= 0 && !boundary[parts[a.next].start]) {
            Part& n = parts[a.next];
            int32_t nr = pair_rank(e, text, a, n);
            if (nr >= 0)
                heap.push({nr, top.left, a.version, n.start, n.version});
        }
    }

    int n = 0;
    for (int i = 0; i >= 0; i = parts[i].next) {
        const Part& p = parts[i];
        std::string key(reinterpret_cast<const char*>(text + p.start), p.len);
        auto it = e->ranks.find(key);
        if (it != e->ranks.end()) {
            if (n >= out_cap) return -1;
            out[n++] = it->second;
        } else {
            // unmergeable span without a rank: emit known single bytes
            for (uint32_t j = 0; j < p.len; ++j) {
                std::string one(reinterpret_cast<const char*>(
                                    text + p.start + j), 1);
                auto bit = e->ranks.find(one);
                if (bit != e->ranks.end()) {
                    if (n >= out_cap) return -1;
                    out[n++] = bit->second;
                }
            }
        }
    }
    return n;
}

int bpe_encode(void* h, const uint8_t* text, int len, int32_t* out,
               int out_cap) {
    return bpe_encode_bounded(h, text, len, nullptr, 0, out, out_cap);
}

void bpe_destroy(void* h) { delete static_cast<Encoder*>(h); }

}  // extern "C"
