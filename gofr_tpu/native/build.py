"""Native build: compile the C++ runtime sources with g++ on demand.

The reference is pure Go compiled ahead of time; our native runtime
pieces (BPE tokenizer, batch queue) compile once per machine into a
content-addressed cache (``~/.cache/gofr_tpu/``) the first time they
are imported, and every consumer falls back to pure Python when no
compiler is present — CI and tests never require a toolchain.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
import tempfile
from pathlib import Path

SRC_DIR = Path(__file__).parent / "src"

_loaded: dict[str, ctypes.CDLL] = {}


class NativeBuildError(Exception):
    pass


def _cache_dir() -> Path:
    root = os.environ.get("GOFR_NATIVE_CACHE",
                          os.path.join(os.path.expanduser("~"),
                                       ".cache", "gofr_tpu"))
    path = Path(root)
    path.mkdir(parents=True, exist_ok=True)
    return path


def compiler() -> str | None:
    for cc in (os.environ.get("CXX"), "g++", "clang++"):
        if cc and shutil.which(cc):
            return cc
    return None


def load_library(name: str) -> ctypes.CDLL:
    """Compile (if needed) and dlopen ``src/<name>.cpp``."""
    if name in _loaded:
        return _loaded[name]
    if os.environ.get("GOFR_NATIVE", "1").lower() in ("0", "false", "off"):
        raise NativeBuildError("native code disabled via GOFR_NATIVE")
    source = SRC_DIR / f"{name}.cpp"
    if not source.is_file():
        raise NativeBuildError(f"missing source {source}")
    cc = compiler()
    if cc is None:
        raise NativeBuildError("no C++ compiler on PATH")

    code = source.read_bytes()
    digest = hashlib.sha256(code).hexdigest()[:16]
    try:
        lib_path = _cache_dir() / f"{name}-{digest}.so"
        if not lib_path.is_file():
            # compile to a temp file then atomic-rename: concurrent
            # workers racing the first build must never dlopen a
            # half-written .so
            fd, tmp = tempfile.mkstemp(suffix=".so",
                                       dir=str(lib_path.parent))
            os.close(fd)
            try:
                cmd = [cc, "-O3", "-std=c++17", "-shared", "-fPIC",
                       str(source), "-o", tmp]
                proc = subprocess.run(cmd, capture_output=True, text=True,
                                      timeout=120)
                if proc.returncode != 0:
                    raise NativeBuildError(
                        f"{cc} failed for {name}: {proc.stderr[-2000:]}")
                os.replace(tmp, lib_path)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
        _loaded[name] = ctypes.CDLL(str(lib_path))
    except NativeBuildError:
        raise
    except Exception as exc:
        # unwritable cache dir, compile timeout, corrupt cached .so —
        # all must surface as NativeBuildError so callers can fall back
        raise NativeBuildError(f"native build of {name} failed: {exc!r}") \
            from exc
    return _loaded[name]


def available(name: str) -> bool:
    try:
        load_library(name)
        return True
    except NativeBuildError:
        return False
