"""Auto-CRUD: REST handlers generated from a dataclass entity.

Mirrors reference pkg/gofr/crud_handlers.go: ``scanEntity``
(crud_handlers.go:67-113) — the FIRST dataclass field is the primary
key; the entity name snake-cases into the table name and REST path;
``table_name()`` / ``rest_path()`` classmethods override both
(crud_handlers.go:40-46). ``add_rest_handlers`` registers
POST /entity, GET /entity, GET /entity/{id}, PUT /entity/{id},
DELETE /entity/{id} (crud_handlers.go:116 registerCRUDHandlers),
building dialect-aware statements through the SQL layer's quoted
identifiers and placeholders (datasource/sql/query_builder.go analog).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any

from .datasource.sql import placeholders, quote_ident
from .http.errors import ErrorEntityNotFound, ErrorInvalidParam
from .http.request import bind_dataclass


def _snake(name: str) -> str:
    return re.sub(r"(?<!^)(?=[A-Z])", "_", name).lower()


@dataclasses.dataclass
class EntitySpec:
    cls: type
    name: str
    table: str
    path: str
    fields: list[str]
    primary_key: str


def scan_entity(cls: type) -> EntitySpec:
    """Reflect a dataclass into an entity spec
    (reference crud_handlers.go:67-113)."""
    if not (dataclasses.is_dataclass(cls) and isinstance(cls, type)):
        raise TypeError("add_rest_handlers requires a dataclass type")
    entity_fields = [f.name for f in dataclasses.fields(cls)]
    if not entity_fields:
        raise TypeError(f"{cls.__name__} has no fields")
    name = _snake(cls.__name__)
    table = getattr(cls, "table_name", lambda: name)()
    path = getattr(cls, "rest_path", lambda: name)()
    return EntitySpec(cls=cls, name=name, table=quote_ident(table),
                      path=path.strip("/"),
                      fields=[quote_ident(f) for f in entity_fields],
                      primary_key=quote_ident(entity_fields[0]))


def _row_to_entity(spec: EntitySpec, row: Any) -> Any:
    # oracle-family stores report UPPERCASE column names; match the
    # dataclass fields case-insensitively like OracleWire.select does
    by_fold = {str(k).lower(): k for k in row.keys()}
    return spec.cls(**{f: row[by_fold[f.lower()]]
                       for f in spec.fields if f.lower() in by_fold})


def _entity_to_dict(entity: Any) -> dict[str, Any]:
    return dataclasses.asdict(entity)


def add_rest_handlers(app: Any, cls: type, *,
                      table_name: str | None = None,
                      rest_path: str | None = None) -> EntitySpec:
    """Generate + register the five CRUD handlers
    (reference rest.go:53 AddRESTHandlers)."""
    spec = scan_entity(cls)
    if table_name is not None:
        spec.table = quote_ident(table_name)
    if rest_path is not None:
        spec.path = rest_path.strip("/")
    base = f"/{spec.path}"
    by_id = f"{base}/{{{spec.primary_key}}}"
    columns = ", ".join(spec.fields)

    def sql_of(ctx):
        sql = ctx.sql
        if sql is None:
            raise RuntimeError("no SQL datasource configured")
        return sql

    def create(ctx):
        sql = sql_of(ctx)
        entity = bind_dataclass(ctx.bind() or {}, spec.cls)
        values = [getattr(entity, f) for f in spec.fields]
        marks = placeholders(sql.dialect, len(spec.fields))
        sql.exec(f"INSERT INTO {spec.table} ({columns}) VALUES ({marks})",
                 *values)
        return {f"{spec.name}": _entity_to_dict(entity)}

    def get_all(ctx):
        sql = sql_of(ctx)
        rows = sql.query(f"SELECT {columns} FROM {spec.table}")
        return [_entity_to_dict(_row_to_entity(spec, r)) for r in rows]

    def _pk(ctx):
        value = ctx.path_param(spec.primary_key)
        if value == "":
            raise ErrorInvalidParam(spec.primary_key)
        return value

    def get_one(ctx):
        sql = sql_of(ctx)
        row = sql.query_row(
            f"SELECT {columns} FROM {spec.table} "
            f"WHERE {spec.primary_key} = {sql.ph(1)}", _pk(ctx))
        if row is None:
            raise ErrorEntityNotFound(spec.primary_key, _pk(ctx))
        return _entity_to_dict(_row_to_entity(spec, row))

    def update(ctx):
        sql = sql_of(ctx)
        pk_value = _pk(ctx)
        # the pk comes from the path, not the body (reference
        # crud_handlers.go Update); the body may omit it
        data = dict(ctx.bind() or {})
        data.setdefault(spec.primary_key, pk_value)
        entity = bind_dataclass(data, spec.cls)
        non_pk = [f for f in spec.fields if f != spec.primary_key]
        if not non_pk:
            raise ErrorInvalidParam("nothing to update")
        sets = ", ".join(f"{f} = {sql.ph(i + 1)}"
                         for i, f in enumerate(non_pk))
        args = [getattr(entity, f) for f in non_pk] + [pk_value]
        cur = sql.exec(
            f"UPDATE {spec.table} SET {sets} "
            f"WHERE {spec.primary_key} = {sql.ph(len(non_pk) + 1)}", *args)
        if getattr(cur, "rowcount", 1) == 0:
            raise ErrorEntityNotFound(spec.primary_key, pk_value)
        return f"{spec.name} successfully updated with id: {pk_value}"

    def delete(ctx):
        sql = sql_of(ctx)
        pk_value = _pk(ctx)
        cur = sql.exec(f"DELETE FROM {spec.table} "
                       f"WHERE {spec.primary_key} = {sql.ph(1)}", pk_value)
        if getattr(cur, "rowcount", 1) == 0:
            raise ErrorEntityNotFound(spec.primary_key, pk_value)
        return f"{spec.name} successfully deleted with id: {pk_value}"

    app.post(base, create)
    app.get(base, get_all)
    app.get(by_id, get_one)
    app.put(by_id, update)
    app.delete(by_id, delete)
    return spec
