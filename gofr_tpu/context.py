"""The single handler-facing object: request + container + trace logger.

Mirrors reference pkg/gofr/context.go:18-38: a ``Context`` embeds the
transport-independent request, the full DI container (so ``ctx.sql``,
``ctx.kv``, ``ctx.get_http_service`` work), a trace-correlated logger,
``trace()`` for user spans (context.go:62), and ``bind`` (context.go:74).
The TPU additions: ``ctx.model(name)`` returns a serving engine and
``ctx.tpu`` the device registry.
"""

from __future__ import annotations

from typing import Any

from .container.container import Container
from .logging.logger import ContextLogger


class Context:
    def __init__(self, request: Any, container: Container,
                 responder: Any = None, terminal: Any = None) -> None:
        self.request = request
        self.container = container
        self.responder = responder
        self.terminal = terminal
        self.logger = ContextLogger(container.logger)
        self._auth_info: dict[str, Any] = {}
        self._ws_conn: Any = None  # set by the websocket runtime

    # -- request surface (reference context delegates to Request)
    def bind(self, target: Any = None) -> Any:
        return self.request.bind(target)

    def param(self, key: str) -> str:
        return self.request.param(key)

    def params(self, key: str) -> list[str]:
        return self.request.params(key)

    def path_param(self, key: str) -> str:
        return self.request.path_param(key)

    def header(self, key: str) -> str:
        getter = getattr(self.request, "header", None)
        return getter(key) if getter else ""

    def host_name(self) -> str:
        return self.request.host_name()

    # -- container surface
    @property
    def config(self):
        return self.container.config

    @property
    def metrics(self):
        return self.container.metrics

    @property
    def sql(self):
        return self.container.sql

    @property
    def redis(self):
        return self.container.redis

    @property
    def kv(self):
        return self.container.kv

    @property
    def file(self):
        return self.container.file

    @property
    def pubsub(self):
        return self.container.pubsub

    @property
    def tpu(self):
        return self.container.tpu

    def __getattr__(self, name: str):
        # breadth datasource slots (mongo, cassandra, dgraph, influxdb,
        # ...) resolve straight off the container, mirroring how the
        # reference Context embeds *Container (context.go:18-38)
        if name.startswith("_"):
            raise AttributeError(name)
        container = self.__dict__.get("container")
        if container is not None and hasattr(container, name):
            return getattr(container, name)
        raise AttributeError(name)

    def model(self, name: str) -> Any:
        return self.container.get_model(name)

    def get_http_service(self, name: str) -> Any:
        return self.container.get_http_service(name)

    # -- tracing (reference context.go:62)
    def trace(self, name: str):
        return self.container.tracer.start_span(name)

    def get_correlation_id(self) -> str:
        span = self.container.tracer.current_span()
        return span.trace_id if span else ""

    # -- auth info set by auth middleware (reference context.go:121)
    @property
    def auth_info(self) -> dict[str, Any]:
        return self._auth_info

    def set_auth_info(self, info: dict[str, Any]) -> None:
        self._auth_info = dict(info)

    # -- websocket (reference context.go:81 WriteMessageToSocket)
    async def write_message_to_socket(self, data: Any) -> None:
        if self._ws_conn is None:
            raise RuntimeError("not a websocket context")
        await self._ws_conn.send(data)

    @property
    def ws_manager(self):
        return self.container.ws_manager

    # -- publish convenience
    async def publish(self, topic: str, message: bytes | str | dict) -> None:
        if self.container.pubsub is None:
            raise RuntimeError("no pub/sub client configured")
        await self.container.pubsub.publish(topic, message)
