"""Leveled structured logger with JSON and pretty terminal modes.

Reimplements the reference logger's contract (pkg/gofr/logging/logger.go):
six levels DEBUG..FATAL, JSON lines on non-terminals and colored
one-liners on terminals (terminal detect logger.go:234-246), a
``PrettyPrint`` protocol so structured records (request logs, query
logs) render as single colored lines (logger.go:19-21), live
``change_level`` (remotelogger/dynamic_level_logger.go), a file logger
for CLI apps (logger.go:213-232), and a ``ContextLogger`` that
auto-injects the active trace/span ids (ctx_logger.go).

Correlation ids ride a ``contextvars.ContextVar`` set by the tracing
middleware, so any log emitted inside a request handler carries
``trace_id``/``span_id`` without plumbing.
"""

from __future__ import annotations

import io
import json
import os
import sys
import threading
import time
from contextvars import ContextVar
from typing import Any, Protocol, TextIO, runtime_checkable

# ---------------------------------------------------------------- levels

DEBUG, INFO, NOTICE, WARN, ERROR, FATAL = 1, 2, 3, 4, 5, 6

_LEVEL_NAMES = {DEBUG: "DEBUG", INFO: "INFO", NOTICE: "NOTICE",
                WARN: "WARN", ERROR: "ERROR", FATAL: "FATAL"}
_LEVEL_COLORS = {DEBUG: 36, INFO: 36, NOTICE: 36, WARN: 33, ERROR: 31, FATAL: 31}

Level = int


def level_from_string(name: str) -> Level:
    return {v: k for k, v in _LEVEL_NAMES.items()}.get((name or "").upper(), INFO)


# ------------------------------------------------- correlation contextvar

# (trace_id, span_id) for the active request; set by tracing middleware.
_trace_ctx: ContextVar[tuple[str, str] | None] = ContextVar("gofr_trace_ctx", default=None)


def set_trace_context(trace_id: str, span_id: str):
    return _trace_ctx.set((trace_id, span_id))


def reset_trace_context(token) -> None:
    _trace_ctx.reset(token)


def current_trace_ids() -> tuple[str, str] | None:
    return _trace_ctx.get()


# ------------------------------------------------- fleet (host) context
#
# Process-wide host identity for multi-host serving: set once at
# control-plane join (serving/control_plane.py) and merged into every
# log record next to trace_id/span_id, and into every span's
# attributes by the tracer — so one grep (and one trace) correlates
# leader and worker. A plain dict, not a contextvar: the whole process
# IS one host, there is nothing request-scoped about it.
_fleet_ctx: dict[str, Any] = {}


def set_fleet_context(**attrs: Any) -> None:
    """Merge host identity (``host_id``, ``rank``, ``generation``) into
    the process-wide fleet context; None values are dropped."""
    _fleet_ctx.update({k: v for k, v in attrs.items() if v is not None})


def clear_fleet_context() -> None:
    _fleet_ctx.clear()


def current_fleet_context() -> dict[str, Any]:
    return dict(_fleet_ctx)


@runtime_checkable
class PrettyPrint(Protocol):
    """Structured records that know how to render a colored one-liner.

    Mirrors reference logging/logger.go:19-21.
    """

    def pretty_print(self, out: TextIO) -> None: ...


class Logger:
    """Leveled logger. JSON lines by default; pretty colors on a tty."""

    def __init__(self, level: Level = INFO, out: TextIO | None = None,
                 err: TextIO | None = None, pretty: bool | None = None) -> None:
        self._level = level
        self._out = out if out is not None else sys.stdout
        self._err = err if err is not None else sys.stderr
        self._lock = threading.Lock()
        if pretty is None:
            pretty = self._is_terminal(self._out)
        self._pretty = pretty

    # -- level management (remote log level service calls change_level)
    @property
    def level(self) -> Level:
        return self._level

    def change_level(self, level: Level) -> None:
        self._level = level

    @staticmethod
    def _is_terminal(out: TextIO) -> bool:
        try:
            return os.isatty(out.fileno())
        except (AttributeError, OSError, ValueError, io.UnsupportedOperation):
            return False

    # -- emit
    def _log(self, level: Level, args: tuple, fields: dict[str, Any]) -> None:
        if level < self._level:
            return
        out = self._err if level >= ERROR else self._out
        # %-style formatting when called like logger.info("x=%s", x)
        if len(args) > 1 and isinstance(args[0], str) and "%" in args[0]:
            try:
                message: Any = args[0] % args[1:]
            except (TypeError, ValueError):
                message = " ".join(str(a) for a in args)
        elif len(args) == 1:
            message = args[0]
        else:
            message = " ".join(str(a) for a in args)

        trace = _trace_ctx.get()
        if self._pretty:
            self._emit_pretty(level, message, fields, trace, out)
        else:
            self._emit_json(level, message, fields, trace, out)

    def _emit_json(self, level: Level, message: Any, fields: dict[str, Any],
                   trace: tuple[str, str] | None, out: TextIO) -> None:
        now = time.time()
        record: dict[str, Any] = {
            "level": _LEVEL_NAMES[level],
            "time": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(now))
                    + f".{int((now % 1) * 1e6):06d}Z",
        }
        if trace:
            record["trace_id"], record["span_id"] = trace
        for k, v in _fleet_ctx.items():
            record.setdefault(k, v)
        if isinstance(message, PrettyPrint):
            record["message"] = getattr(message, "__dict__", str(message))
        elif isinstance(message, (dict, list, str, int, float, bool, type(None))):
            record["message"] = message
        else:
            record["message"] = str(message)
        if fields:
            record.update(fields)
        with self._lock:
            out.write(json.dumps(record, default=str) + "\n")
            out.flush()

    def _emit_pretty(self, level: Level, message: Any, fields: dict[str, Any],
                     trace: tuple[str, str] | None, out: TextIO) -> None:
        color = _LEVEL_COLORS[level]
        name = _LEVEL_NAMES[level]
        ts = time.strftime("%H:%M:%S")
        with self._lock:
            out.write(f"\x1b[{color}m{name:<6}\x1b[0m [{ts}] ")
            if trace:
                out.write(f"\x1b[38;5;8m{trace[0]}\x1b[0m ")
            if isinstance(message, PrettyPrint):
                message.pretty_print(out)
            else:
                out.write(str(message))
            if _fleet_ctx:
                out.write(" " + " ".join(f"{k}={v}"
                                         for k, v in _fleet_ctx.items()))
            if fields:
                out.write(" " + " ".join(f"{k}={v}" for k, v in fields.items()))
            out.write("\n")
            out.flush()

    # -- the public 6-level surface (reference logger.go:26-42)
    def debug(self, *args: Any, **fields: Any) -> None:
        self._log(DEBUG, args, fields)

    def info(self, *args: Any, **fields: Any) -> None:
        self._log(INFO, args, fields)

    def notice(self, *args: Any, **fields: Any) -> None:
        self._log(NOTICE, args, fields)

    def warn(self, *args: Any, **fields: Any) -> None:
        self._log(WARN, args, fields)

    def error(self, *args: Any, **fields: Any) -> None:
        self._log(ERROR, args, fields)

    def fatal(self, *args: Any, **fields: Any) -> None:
        """Log at FATAL and terminate, matching reference logger.go:152."""
        self._log(FATAL, args, fields)
        raise SystemExit(1)

    def log(self, *args: Any, **fields: Any) -> None:
        self._log(INFO, args, fields)

    def log_at(self, level: Level, *args: Any, **fields: Any) -> None:
        self._log(level, args, fields)


class ContextLogger(Logger):
    """Logger view bound to a request; shares the base logger's sinks.

    The base logger's level is read live so a remote level change
    affects in-flight request loggers too (reference ctx_logger.go).
    """

    def __init__(self, base: Logger) -> None:
        self._base = base
        super().__init__(level=base.level, out=base._out, err=base._err,
                         pretty=base._pretty)
        self._lock = base._lock

    @property
    def level(self) -> Level:
        return self._base.level

    def _log(self, level: Level, args: tuple, fields: dict[str, Any]) -> None:
        if level < self._base.level:
            return
        self._level = self._base.level
        Logger._log(self, level, args, fields)


def new_logger(level: Level = INFO, **kw: Any) -> Logger:
    return Logger(level=level, **kw)


def new_file_logger(path: str, level: Level = INFO) -> Logger:
    """File logger for CLI apps (reference logger.go:213-232)."""
    f = open(path, "a", buffering=1)
    return Logger(level=level, out=f, err=f, pretty=False)


class MockLogger(Logger):
    """Captures records in memory for test assertions."""

    def __init__(self, level: Level = DEBUG) -> None:
        self.buffer = io.StringIO()
        super().__init__(level=level, out=self.buffer, err=self.buffer, pretty=False)

    @property
    def lines(self) -> list[dict[str, Any]]:
        return [json.loads(line) for line in self.buffer.getvalue().splitlines() if line]
