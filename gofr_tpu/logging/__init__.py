from .logger import (
    DEBUG,
    ERROR,
    FATAL,
    INFO,
    NOTICE,
    WARN,
    ContextLogger,
    Level,
    Logger,
    MockLogger,
    level_from_string,
    new_file_logger,
    new_logger,
)

__all__ = [
    "DEBUG", "ERROR", "FATAL", "INFO", "NOTICE", "WARN",
    "ContextLogger", "Level", "Logger", "MockLogger",
    "level_from_string", "new_file_logger", "new_logger",
]
