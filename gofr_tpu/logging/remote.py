"""Remote log-level switching (reference
logging/remotelogger/dynamic_level_logger.go:141-214).

A background task polls ``REMOTE_LOG_URL`` every
``REMOTE_LOG_FETCH_INTERVAL`` seconds and applies the returned level to
the live logger via ``change_level`` — turn DEBUG on in production
without a restart. Accepts both the reference's response shape
(``{"data": [{"serviceName": ..., "logLevel": {"LOG_LEVEL": "DEBUG"}}]}``)
and a plain ``{"level": "DEBUG"}``.
"""

from __future__ import annotations

import asyncio
from typing import Any

from .logger import _LEVEL_NAMES, level_from_string

DEFAULT_INTERVAL_S = 15.0


def parse_level_response(payload: Any) -> str | None:
    """Extract a level name from either supported response shape."""
    if not isinstance(payload, dict):
        return None
    if isinstance(payload.get("level"), str):
        return payload["level"]
    data = payload.get("data")
    if isinstance(data, dict):
        data = [data]
    if isinstance(data, list):
        for entry in data:
            if not isinstance(entry, dict):
                continue
            log_level = entry.get("logLevel")
            if isinstance(log_level, dict) and \
                    isinstance(log_level.get("LOG_LEVEL"), str):
                return log_level["LOG_LEVEL"]
            if isinstance(entry.get("LOG_LEVEL"), str):
                return entry["LOG_LEVEL"]
    return None


class RemoteLevelUpdater:
    """Poll loop; ``service`` is anything with ``async get(path) ->
    Response`` (an HTTPService — circuit breaker/retry options apply)."""

    def __init__(self, logger: Any, service: Any, path: str = "",
                 interval_s: float = DEFAULT_INTERVAL_S) -> None:
        self.logger = logger
        self.service = service
        self.path = path
        self.interval_s = interval_s
        self.fetches = 0
        self.applied = 0

    async def poll_once(self) -> bool:
        """One fetch+apply; True iff the level changed."""
        self.fetches += 1
        try:
            resp = await self.service.get(self.path)
            if not getattr(resp, "ok", False):
                return False
            name = parse_level_response(resp.json())
        except Exception as exc:
            self.logger.debug(f"remote level fetch failed: {exc}")
            return False
        if name is None or (name or "").upper() not in _LEVEL_NAMES.values():
            # unknown names must not coerce to INFO — a garbage response
            # would silently change the production log level
            return False
        new_level = level_from_string(name)
        if new_level == self.logger.level:
            return False
        self.logger.info(
            f"LOG_LEVEL updated from "
            f"{_LEVEL_NAMES.get(self.logger.level, '?')} to "
            f"{_LEVEL_NAMES.get(new_level, '?')}")
        self.logger.change_level(new_level)
        self.applied += 1
        return True

    async def run(self) -> None:
        while True:
            await self.poll_once()
            await asyncio.sleep(self.interval_s)


def from_config(config: Any, logger: Any,
                metrics: Any = None) -> RemoteLevelUpdater | None:
    """Build the updater when REMOTE_LOG_URL is configured (reference
    container.go:107 wires remotelogger.New the same way)."""
    url = config.get_or_default("REMOTE_LOG_URL", "")
    if not url:
        return None
    try:
        interval = float(config.get_or_default("REMOTE_LOG_FETCH_INTERVAL",
                                               str(DEFAULT_INTERVAL_S)))
    except ValueError:
        logger.error("invalid REMOTE_LOG_FETCH_INTERVAL; using default")
        interval = DEFAULT_INTERVAL_S
    # a zero/negative interval would hot-loop against the endpoint
    interval = max(interval, 1.0)
    from ..service.client import HTTPService
    from urllib.parse import urlsplit
    parts = urlsplit(url)
    base = f"{parts.scheme}://{parts.netloc}"
    path = parts.path + (f"?{parts.query}" if parts.query else "")
    service = HTTPService(base, logger=logger, metrics=metrics,
                          timeout=10.0, service_name="remote-logger")
    return RemoteLevelUpdater(logger, service, path=path,
                              interval_s=interval)
