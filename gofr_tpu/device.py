"""TPU device registry — chip enumeration, HBM stats, health.

SURVEY §7 stage 4: the device registry lives in the container
(``ctx.tpu``) and feeds chip/HBM state into the same health and
metrics surfaces every other datasource uses (health aggregation
container/health.go:8-98; the reference has no device analog).

Design points for a tunneled/remote device backend:
- enumeration runs in a worker thread with a deadline — a dead tunnel
  makes health report DOWN instead of hanging the health endpoint;
- results are cached with a TTL so /health and the metrics poller
  don't hammer the backend;
- ``jax`` imports lazily, keeping ``import gofr_tpu`` light.
"""

from __future__ import annotations

import concurrent.futures
import threading
import time
from typing import Any

#: how long device enumeration may take before health reports DOWN
PROBE_TIMEOUT_S = 10.0
#: cached device info remains fresh this long
CACHE_TTL_S = 10.0


class DeviceRegistry:
    def __init__(self, logger: Any = None, metrics: Any = None,
                 probe_timeout_s: float = PROBE_TIMEOUT_S,
                 cache_ttl_s: float = CACHE_TTL_S) -> None:
        self.logger = logger
        self.metrics = metrics
        self.probe_timeout_s = probe_timeout_s
        self.cache_ttl_s = cache_ttl_s
        self.engines: dict[str, Any] = {}
        self._lock = threading.Lock()
        self._cache: list[dict] | None = None
        self._cache_at = 0.0
        self._last_error: str | None = None

    # ------------------------------------------------------- enumeration
    @staticmethod
    def _probe() -> list[dict]:
        """Runs on a worker thread: enumerate devices + memory stats."""
        import jax
        out = []
        for d in jax.devices():
            info: dict[str, Any] = {
                "id": d.id,
                "platform": d.platform,
                "kind": getattr(d, "device_kind", ""),
                "process_index": getattr(d, "process_index", 0),
            }
            coords = getattr(d, "coords", None)
            if coords is not None:
                info["coords"] = list(coords)
            stats_fn = getattr(d, "memory_stats", None)
            if stats_fn is not None:
                try:
                    stats = stats_fn() or {}
                    info["hbm_bytes_in_use"] = stats.get("bytes_in_use")
                    info["hbm_bytes_limit"] = stats.get(
                        "bytes_limit", stats.get("bytes_reservable_limit"))
                except Exception:
                    pass
            out.append(info)
        return out

    def devices(self, refresh: bool = False) -> list[dict]:
        """Cached device info; empty list when the backend is
        unreachable (``last_error`` says why)."""
        with self._lock:
            fresh = (self._cache is not None
                     and time.time() - self._cache_at < self.cache_ttl_s)
            if fresh and not refresh:
                return list(self._cache)
        # bounded probe off-thread; the pool is not reused because a
        # stuck probe thread must not block later probes
        pool = concurrent.futures.ThreadPoolExecutor(
            1, thread_name_prefix="tpu-probe")
        try:
            future = pool.submit(self._probe)
            devices = future.result(self.probe_timeout_s)
            error = None
        except concurrent.futures.TimeoutError:
            devices, error = None, \
                f"device probe exceeded {self.probe_timeout_s}s"
        except Exception as exc:
            devices, error = None, repr(exc)
        finally:
            pool.shutdown(wait=False)
        with self._lock:
            self._last_error = error
            if devices is not None:
                self._cache = devices
                self._cache_at = time.time()
            # on error keep serving the stale cache (if any): health
            # flags DOWN via last_error while details stay useful
            return list(self._cache or [])

    @property
    def last_error(self) -> str | None:
        return self._last_error

    def device_count(self) -> int:
        return len(self.devices())

    # ---------------------------------------------------------- engines
    def register_engine(self, name: str, engine: Any) -> None:
        self.engines[name] = engine

    # ----------------------------------------------------------- health
    def health_check(self) -> dict:
        devices = self.devices()
        status = "UP" if devices and self._last_error is None else "DOWN"
        details: dict[str, Any] = {
            "devices": devices,
            "device_count": len(devices),
        }
        if self._last_error:
            details["error"] = self._last_error
            if devices:
                status = "DEGRADED"  # stale cache still served
        if self.engines:
            engine_health = {
                name: (e.health_check() if hasattr(e, "health_check")
                       else {"status": "UP"})
                for name, e in self.engines.items()}
            details["engines"] = engine_health
            # a stalled or crashed engine must surface at the slot
            # level — the aggregate health endpoint only reads status
            ranks = {"UP": 0, "DEGRADED": 1, "DOWN": 2}
            worst = max((h.get("status", "UP") for h in
                         engine_health.values()),
                        key=lambda s: ranks.get(s, 1))
            if ranks.get(worst, 0) > ranks.get(status, 0):
                status = worst
        return {"status": status, "details": details}

    # ---------------------------------------------------------- metrics
    def publish_metrics(self) -> None:
        """Push device gauges (app_tpu_device_count /
        app_tpu_hbm_bytes_used, registered in container.py)."""
        if self.metrics is None:
            return
        devices = self.devices()
        self.metrics.set_gauge("app_tpu_device_count", len(devices))
        for d in devices:
            used = d.get("hbm_bytes_in_use")
            if used is not None:
                self.metrics.set_gauge("app_tpu_hbm_bytes_used", used,
                                       device=str(d["id"]))

    async def metrics_loop(self, interval_s: float = 15.0) -> None:
        """Background task App.start runs: periodic gauge refresh."""
        import asyncio
        while True:
            try:
                self.publish_metrics()
            except Exception as exc:
                if self.logger is not None:
                    self.logger.debug(f"tpu metrics refresh failed: {exc}")
            await asyncio.sleep(interval_s)

    def close(self) -> None:
        pass
