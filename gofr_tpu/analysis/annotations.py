"""Markers gofrlint keys on. Zero runtime behavior.

``@hot_path`` tags a function as steady-state hot: gofrlint walks it and
everything it statically calls within the package and rejects host
syncs, wall-clock reads, logging, and metric writes (rule
``hot-path-purity``). The decorator itself only sets an attribute — the
engine pays nothing for being annotated.

``@hot_path_boundary(reason)`` tags a function as a deliberate exit
from the hot path — the retire/collect/failure boundaries where the
engine is *supposed* to assemble observability host-side. The purity
walk stops at a boundary instead of descending into it. The reason is
mandatory and shows up in ``scripts/lint.py --explain``-style output so
a reviewer can audit why the boundary is legitimate.
"""

from __future__ import annotations

from typing import Callable, TypeVar

F = TypeVar("F", bound=Callable)

HOT_PATH_ATTR = "__gofr_hot_path__"
BOUNDARY_ATTR = "__gofr_hot_path_boundary__"


def hot_path(fn: F) -> F:
    """Mark ``fn`` as steady-state hot. gofrlint enforces purity over
    ``fn`` and its static callees (see rule ``hot-path-purity``)."""
    setattr(fn, HOT_PATH_ATTR, True)
    return fn


def hot_path_boundary(reason: str) -> Callable[[F], F]:
    """Mark a function as a deliberate hot-path exit (retire/collect/
    failure handling). ``reason`` is mandatory — an empty reason is a
    lint error (``bad-suppression``), same contract as inline allows."""
    if not isinstance(reason, str) or not reason.strip():
        raise ValueError("hot_path_boundary requires a non-empty reason")

    def mark(fn: F) -> F:
        setattr(fn, BOUNDARY_ATTR, reason)
        return fn

    return mark
