"""gofrlint — the repo-native AST invariant analyzer.

The engine's hardest-won properties (zero steady-state h2d transfers,
host-side-only observability assembly, registry-covered metrics, no
per-request recompiles) are enforced dynamically by the transfer-guard
/ bit-identity / registry-coverage tests — which only fire if a test
drives the exact regressed path. gofrlint moves those invariants left:
stdlib-``ast`` static rules that fail CI the moment a diff introduces
the violation, before any test runs.

Rules (each in ``analysis/rules/``):

- ``hot-path-purity``   — ``@hot_path`` closure must not sync/log/meter
- ``lock-discipline``   — lockset approximation over class bodies
- ``blocking-in-async`` — no sync sleep/IO/HTTP inside ``async def``
- ``metric-hygiene``    — writes <-> registrations, both directions
- ``recompile-hazard``  — per-request data into jit static args

Plus the built-in ``bad-suppression`` (an ``allow()`` without a reason,
or one that suppresses nothing) and ``parse-error``.

Usage: ``python scripts/lint.py gofr_tpu/ scripts/ bench.py`` or
programmatically via :func:`run_analysis`.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable

from .annotations import hot_path, hot_path_boundary
from .callgraph import CallGraph
from .core import (BAD_SUPPRESSION, PARSE_ERROR, Finding, Project,
                   apply_suppressions, load_project, unused_suppressions)
from .rules import ALL_RULES, RULE_IDS

__all__ = ["hot_path", "hot_path_boundary", "run_analysis", "Finding",
           "RULE_IDS", "BAD_SUPPRESSION", "PARSE_ERROR", "load_project"]


def run_analysis(paths: Iterable[str | Path], *,
                 rules: Iterable[str] | None = None,
                 root: Path | None = None) -> tuple[list[Finding], Project]:
    """Lint ``paths`` and return (findings, project).

    Findings covered by a same-line ``# gofrlint: allow(rule) -- reason``
    come back with ``suppressed=True`` (kept, so ``--format=json`` can
    audit the reason ledger); everything else is a violation. Parse
    errors, reason-less allows, and allows that cover nothing are
    violations under ``parse-error``/``bad-suppression``.
    """
    project = load_project(paths, root=root)
    graph = CallGraph(project)
    wanted = set(rules) if rules is not None else None
    findings: list[Finding] = list(project.errors)
    per_module: dict[str, list[Finding]] = {}
    for rule_mod in ALL_RULES:
        if wanted is not None and rule_mod.RULE_ID not in wanted:
            continue
        for f in rule_mod.run(project, graph):
            per_module.setdefault(f.path, []).append(f)
    for mod in project.modules:
        mod_findings = per_module.get(mod.rel, [])
        apply_suppressions(mod, mod_findings)
        findings.extend(mod_findings)
        if wanted is None:  # stale-allow audit only on full runs
            findings.extend(unused_suppressions(mod, mod_findings))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, project
