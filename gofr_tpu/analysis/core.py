"""gofrlint core: project loading, suppressions, findings, rule driver.

Stdlib-``ast`` only — the analyzer must run in CI before anything else
is importable, so it never imports the code it lints.

Suppression syntax (reason mandatory, same line as the finding)::

    self.metrics.add_counter("app_engine_h2d_transfers", 7.0)  \
        # gofrlint: allow(hot-path-purity) -- event-driven sync, not steady state

A suppression without a ``-- reason`` is itself an error finding
(rule ``bad-suppression``), so the escape hatch can't silently become
a blanket off-switch.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Iterable

SUPPRESS_RE = re.compile(
    r"#\s*gofrlint:\s*allow\(\s*([A-Za-z0-9_,\-\s*]+?)\s*\)"
    r"(?:\s*--\s*(.*\S))?")

BAD_SUPPRESSION = "bad-suppression"
PARSE_ERROR = "parse-error"


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    allow_reason: str | None = None

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "path": self.path, "line": self.line,
             "col": self.col, "message": self.message}
        if self.suppressed:
            d["suppressed"] = True
            d["allow_reason"] = self.allow_reason
        return d

    def render(self) -> str:
        tag = " (allowed: %s)" % self.allow_reason if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}{tag}"


@dataclass
class Suppression:
    line: int
    rules: tuple[str, ...]   # ("*",) allows every rule on the line
    reason: str | None

    def covers(self, rule: str) -> bool:
        return self.reason is not None and ("*" in self.rules
                                            or rule in self.rules)


@dataclass
class Module:
    path: Path          # real filesystem path
    rel: str            # display path (relative to lint root)
    source: str
    tree: ast.Module
    suppressions: dict[int, Suppression] = field(default_factory=dict)


def parse_suppressions(source: str) -> tuple[dict[int, Suppression], list[tuple[int, str]]]:
    """Scan raw lines for ``# gofrlint: allow(...)`` comments.

    Returns (line -> Suppression, [(line, problem), ...]); a missing or
    empty reason lands in the problems list and the suppression is
    recorded reason-less, so it covers nothing.
    """
    out: dict[int, Suppression] = {}
    problems: list[tuple[int, str]] = []
    comments: list[tuple[int, str]] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT and "gofrlint" in tok.string:
                comments.append((tok.start[0], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # load_project already reports unparseable files
    for i, text in comments:
        m = SUPPRESS_RE.search(text)
        if m is None:
            if re.search(r"#\s*gofrlint", text):
                problems.append((i, "unparseable gofrlint comment "
                                    "(expected: # gofrlint: allow(<rule>) -- <reason>)"))
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = m.group(2)
        if not reason:
            problems.append((i, "suppression missing its mandatory "
                                "'-- <reason>' clause"))
            reason = None
        out[i] = Suppression(line=i, rules=rules, reason=reason)
    return out, problems


def iter_py_files(paths: Iterable[str | Path]) -> list[Path]:
    files: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(f for f in p.rglob("*.py")
                                if "__pycache__" not in f.parts))
        elif p.suffix == ".py":
            files.append(p)
    # dedupe, stable order
    seen: set[Path] = set()
    out = []
    for f in files:
        r = f.resolve()
        if r not in seen:
            seen.add(r)
            out.append(f)
    return out


@dataclass
class Project:
    modules: list[Module]
    errors: list[Finding]   # parse errors + bad suppressions

    def module_by_dotted(self) -> dict[str, Module]:
        """Map best-effort dotted module names (``gofr_tpu.serving.engine``)
        to modules, for resolving intra-package ``from x import y``."""
        out: dict[str, Module] = {}
        for mod in self.modules:
            parts = list(Path(mod.rel).with_suffix("").parts)
            if parts and parts[-1] == "__init__":
                parts = parts[:-1]
            if parts:
                out[".".join(parts)] = mod
        return out


def load_project(paths: Iterable[str | Path],
                 root: Path | None = None) -> Project:
    root = (root or Path.cwd()).resolve()
    modules: list[Module] = []
    errors: list[Finding] = []
    for f in iter_py_files(paths):
        try:
            rel = str(f.resolve().relative_to(root))
        except ValueError:
            rel = str(f)
        try:
            source = f.read_text(encoding="utf-8")
            tree = ast.parse(source, filename=rel)
        except (SyntaxError, UnicodeDecodeError) as exc:
            line = getattr(exc, "lineno", 1) or 1
            errors.append(Finding(PARSE_ERROR, rel, line, 0,
                                  f"cannot parse: {exc}"))
            continue
        sup, problems = parse_suppressions(source)
        for line, problem in problems:
            errors.append(Finding(BAD_SUPPRESSION, rel, line, 0, problem))
        modules.append(Module(path=f, rel=rel, source=source, tree=tree,
                              suppressions=sup))
    return Project(modules=modules, errors=errors)


def apply_suppressions(mod: Module, findings: list[Finding]) -> None:
    """Mark findings covered by a same-line allow() as suppressed, and
    flag allows that cover nothing (stale suppressions rot the ledger
    of reasons — they must be deleted when the finding goes away)."""
    for f in findings:
        sup = mod.suppressions.get(f.line)
        if sup is not None and sup.covers(f.rule):
            f.suppressed = True
            f.allow_reason = sup.reason


def unused_suppressions(mod: Module, findings: list[Finding]) -> list[Finding]:
    used = {f.line for f in findings if f.suppressed}
    out = []
    for line, sup in sorted(mod.suppressions.items()):
        if sup.reason is not None and line not in used:
            out.append(Finding(
                BAD_SUPPRESSION, mod.rel, line, 0,
                f"allow({','.join(sup.rules)}) suppresses nothing on this "
                f"line — delete it or fix the rule name"))
    return out


# ----------------------------------------------------------- AST helpers

def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    return dotted_name(call.func)


def import_aliases(tree: ast.Module) -> dict[str, str]:
    """Local alias -> canonical dotted target, from top-level imports.

    ``import numpy as np`` -> {"np": "numpy"};
    ``from jax import numpy as jnp`` -> {"jnp": "jax.numpy"}.
    """
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    out[a.asname] = a.name
                else:
                    # ``import a.b`` binds ``a`` — a dotted use like
                    # ``a.b.c`` is already canonical
                    head = a.name.split(".")[0]
                    out[head] = head
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out


def canonical_call(call: ast.Call, aliases: dict[str, str]) -> str | None:
    """Resolve a call's dotted name through import aliases:
    ``np.asarray`` -> ``numpy.asarray`` when np aliases numpy."""
    name = call_name(call)
    if name is None:
        return None
    head, _, rest = name.partition(".")
    target = aliases.get(head)
    if target is None:
        return name
    return target + ("." + rest if rest else "")
