"""Rule ``recompile-hazard``.

The static sibling of the runtime RecompileSentinel: a ``jax.jit`` /
``pjit`` wrapper whose ``static_argnums``/``static_argnames`` position
is fed a value derived from per-request data recompiles once per
distinct value — the classic way a serving engine melts down under
real traffic (every novel prompt length or sampling param burns a
compile).

Detection (intra-function/intra-module approximation):

1. find wrappers: ``g = jax.jit(f, static_argnums=(1,))`` (also
   ``pjit``, also via ``functools.partial(jax.jit, ...)``) with
   statically-known static positions/names;
2. find calls of those wrappers visible in the same scope chain;
3. taint: an argument expression at a static position is per-request
   when it mentions a request-ish root (``req``, ``request``,
   ``prompt``, ``msg``, ``payload``, ``body``, ``sampling``/``params``
   attribute chains) or a direct ``len(...)`` of one.

Bucketing the value first (``self._bucket_for(len(prompt))``) breaks
the taint only when routed through a call — calls are opaque to the
taint walk by design, because bucketing IS the sanctioned fix.
"""

from __future__ import annotations

import ast

from ..core import Finding, Project, canonical_call, import_aliases

RULE_ID = "recompile-hazard"

JIT_FUNCS = {"jax.jit", "jax.pjit", "jit", "pjit",
             "jax.experimental.pjit.pjit"}
REQUEST_ROOTS = {"req", "request", "requests_in", "msg", "message",
                 "payload", "body", "prompt", "prompt_tokens", "params",
                 "sampling", "sampling_params"}


def _is_jit_call(node: ast.Call, aliases: dict[str, str]) -> bool:
    name = canonical_call(node, aliases)
    if name is None:
        return False
    if name in JIT_FUNCS:
        return True
    # functools.partial(jax.jit, ...)
    if name in ("functools.partial", "partial") and node.args:
        inner = node.args[0]
        if isinstance(inner, (ast.Name, ast.Attribute)):
            fake = ast.Call(func=inner, args=[], keywords=[])
            return (canonical_call(fake, aliases) or "") in JIT_FUNCS
    return False


def _static_spec(node: ast.Call) -> tuple[list[int], list[str]] | None:
    """(positions, names) when the call carries static_argnums/names
    with literal values; None when it has none (not a hazard source)."""
    nums: list[int] = []
    names: list[str] = []
    found = False
    for kw in node.keywords:
        if kw.arg == "static_argnums":
            found = True
            nums.extend(_int_list(kw.value))
        elif kw.arg == "static_argnames":
            found = True
            names.extend(_str_list(kw.value))
    return (nums, names) if found else None


def _int_list(node: ast.expr) -> list[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [el.value for el in node.elts
                if isinstance(el, ast.Constant)
                and isinstance(el.value, int)]
    return []


def _str_list(node: ast.expr) -> list[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [el.value for el in node.elts
                if isinstance(el, ast.Constant)
                and isinstance(el.value, str)]
    return []


def _tainted(node: ast.AST) -> str | None:
    """A per-request root mentioned in ``node``, or None. Calls are
    opaque (routing a value through a bucketing helper breaks the
    taint — that is the sanctioned fix) except builtin ``len()``,
    which is transparent (``len(prompt)`` is still per-request)."""
    if isinstance(node, ast.Name):
        return node.id if node.id in REQUEST_ROOTS else None
    if isinstance(node, ast.Attribute):
        if node.attr in REQUEST_ROOTS:
            return node.attr
        return _tainted(node.value)
    if isinstance(node, ast.Call):
        if isinstance(node.func, ast.Name) and node.func.id == "len":
            for a in node.args:
                hit = _tainted(a)
                if hit:
                    return hit
        return None
    for child in ast.iter_child_nodes(node):
        hit = _tainted(child)
        if hit:
            return hit
    return None


class _ScopeScanner(ast.NodeVisitor):
    """One pass per module: record jit wrappers by assigned name, then
    flag tainted call sites of those wrappers."""

    def __init__(self, mod, aliases: dict[str, str]) -> None:
        self.mod = mod
        self.aliases = aliases
        self.wrappers: dict[str, tuple[list[int], list[str]]] = {}
        self.findings: list[Finding] = []

    # wrapper discovery: name = jax.jit(f, static_argnums=...), also the
    # two-step form name = functools.partial(jax.jit, static...)(f)
    def visit_Assign(self, node: ast.Assign) -> None:
        src = None
        if isinstance(node.value, ast.Call):
            if _is_jit_call(node.value, self.aliases):
                src = node.value
            elif isinstance(node.value.func, ast.Call) \
                    and _is_jit_call(node.value.func, self.aliases):
                src = node.value.func
        if src is not None:
            spec = _static_spec(src)
            if spec is not None:
                for t in node.targets:
                    tgt = None
                    if isinstance(t, ast.Name):
                        tgt = t.id
                    elif isinstance(t, ast.Attribute):
                        tgt = t.attr  # self._decode = jax.jit(...)
                    if tgt:
                        self.wrappers[tgt] = spec
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        # direct immediate invocation: jax.jit(f, static_argnums=(1,))(a, b)
        if isinstance(node.func, ast.Call) \
                and _is_jit_call(node.func, self.aliases):
            spec = _static_spec(node.func)
            if spec is not None:
                self._check(node, spec, "jit-wrapped callable")
        # call of a recorded wrapper name
        name = None
        if isinstance(node.func, ast.Name):
            name = node.func.id
        elif isinstance(node.func, ast.Attribute):
            name = node.func.attr
        if name in self.wrappers:
            self._check(node, self.wrappers[name], f"'{name}'")
        self.generic_visit(node)

    def _check(self, call: ast.Call,
               spec: tuple[list[int], list[str]], label: str) -> None:
        nums, names = spec
        # static positions count the wrapped fn's first arg as 0; at a
        # wrapper call site positions map 1:1
        for pos in nums:
            if pos < len(call.args):
                root = _tainted(call.args[pos])
                if root:
                    self._flag(call, label, f"positional arg {pos}", root)
        for kw in call.keywords:
            if kw.arg in names:
                root = _tainted(kw.value)
                if root:
                    self._flag(call, label, f"keyword '{kw.arg}'", root)

    def _flag(self, call: ast.Call, label: str, where: str,
              root: str) -> None:
        self.findings.append(Finding(
            RULE_ID, self.mod.rel, call.lineno, call.col_offset,
            f"static arg ({where}) of {label} derives from per-request "
            f"data ('{root}') — every distinct value triggers a "
            f"recompile; bucket it first"))


def run(project: Project, graph=None) -> list[Finding]:
    findings: list[Finding] = []
    for mod in project.modules:
        aliases = import_aliases(mod.tree)
        scanner = _ScopeScanner(mod, aliases)
        scanner.visit(mod.tree)
        findings.extend(scanner.findings)
    return findings
