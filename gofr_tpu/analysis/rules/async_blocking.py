"""Rule ``blocking-in-async``.

``async def`` bodies (engine loop glue, control-plane agents, the
websocket path) must not stall the event loop: no ``time.sleep``, no
synchronous ``requests``/``urllib`` HTTP, no blocking socket setup, no
``subprocess`` waits, no bare builtin ``open()`` (use a thread
offload or the async file helpers). Nested *sync* ``def``s inside an
async function are skipped — they may legitimately run in an executor
— but nested async defs are scanned.
"""

from __future__ import annotations

import ast

from ..core import Finding, Project, canonical_call, import_aliases

RULE_ID = "blocking-in-async"

BLOCKING_CALLS = {
    "time.sleep": "time.sleep() stalls the event loop — await asyncio.sleep",
    "urllib.request.urlopen": "synchronous HTTP in async code",
    "socket.create_connection": "blocking socket connect in async code",
    "subprocess.run": "subprocess wait blocks the event loop",
    "subprocess.call": "subprocess wait blocks the event loop",
    "subprocess.check_call": "subprocess wait blocks the event loop",
    "subprocess.check_output": "subprocess wait blocks the event loop",
    "os.system": "os.system blocks the event loop",
}
BLOCKING_PREFIXES = {
    "requests.": "synchronous 'requests' HTTP in async code",
}
OPEN_MSG = ("builtin open() is synchronous file IO — offload to a "
            "thread (asyncio.to_thread) or do it before going async")


class _AsyncScanner(ast.NodeVisitor):
    """Walk one async function's body without descending into nested
    sync defs (executor-bound) or nested async defs (scanned on their
    own by the module walk)."""

    def __init__(self, mod, fn: ast.AsyncFunctionDef,
                 aliases: dict[str, str]) -> None:
        self.mod = mod
        self.fn = fn
        self.aliases = aliases
        self.findings: list[Finding] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        return

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        if node is not self.fn:
            return
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        name = canonical_call(node, self.aliases)
        msg = None
        if name is not None:
            msg = BLOCKING_CALLS.get(name)
            if msg is None:
                for prefix, pmsg in BLOCKING_PREFIXES.items():
                    if name.startswith(prefix):
                        msg = pmsg
                        break
        if msg is None and isinstance(node.func, ast.Name) \
                and node.func.id == "open":
            msg = OPEN_MSG
        if msg is not None:
            self.findings.append(Finding(
                RULE_ID, self.mod.rel, node.lineno, node.col_offset,
                f"{msg} (in 'async def {self.fn.name}')"))
        self.generic_visit(node)


def run(project: Project, graph=None) -> list[Finding]:
    findings: list[Finding] = []
    for mod in project.modules:
        aliases = import_aliases(mod.tree)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                scanner = _AsyncScanner(mod, node, aliases)
                scanner.visit(node)
                findings.extend(scanner.findings)
    return findings
