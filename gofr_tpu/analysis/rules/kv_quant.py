"""Rule ``kv-quant-boundary``.

The paged KV pool's representation — dtype, int8 codes, per-row
scales — is owned by the scatters/gathers in ``ops/paged_kv.py``,
which run INSIDE the jitted hot closures (quantize-on-write,
dequantize-in-kernel). Serving code violates that boundary when it:

1. casts a pool itself (``kc.astype(...)``, ``pool["q"].astype(...)``)
   — a dtype re-lay in the closure silently de-quantizes the pool or
   materialises a second full-size copy in HBM;
2. casts rows AT a scatter boundary
   (``scatter_chunk(kc, t, k.astype(kc.dtype), ...)``) — the cast
   belongs inside the scatter, where the quantized path replaces it
   with quantize-on-write; a caller-side cast bakes the plain-pool
   dtype into the closure and breaks the int8 layout;
3. reads a pool back to host (``np.asarray(pool)``,
   ``jax.device_get(kc)``, ``kc.block_until_ready()``) to dequantize
   or inspect it host-side — KV stays on device, always.

Detection is name-based (graph-free, same approximation as
``recompile-hazard``): an expression is pool-ish when its root name is
one of the pool spellings the serving/model layers use (``kc``/``vc``,
``kp``/``vp``, ``k_pool``/``v_pool``, ``k_cache``/``v_cache``,
``pool``), including ``self.``-attributes and the quantized pytree's
``["q"]``/``["s"]`` leaves.
"""

from __future__ import annotations

import ast

from ..core import Finding, Project, canonical_call, import_aliases

RULE_ID = "kv-quant-boundary"

#: pool spellings across engine/glue/model code
POOL_ROOTS = {"kc", "vc", "kp", "vp", "kp_all", "vp_all",
              "k_pool", "v_pool", "k_cache", "v_cache", "pool"}
#: the jitted pool writers that own quantize-on-write
WRITERS = {"scatter_prefill", "scatter_chunk", "scatter_decode",
           "pool_write"}
#: host-readback calls (canonical names after alias resolution)
HOST_READS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
              "jax.device_get"}


def _is_pool(node: ast.AST) -> bool:
    """True when ``node`` is a pool reference: a pool-root name, a
    ``self.<pool>`` attribute, or a subscript of one (``pool["q"]``,
    ``kc[li]``)."""
    if isinstance(node, ast.Name):
        return node.id in POOL_ROOTS
    if isinstance(node, ast.Attribute):
        return node.attr in POOL_ROOTS
    if isinstance(node, ast.Subscript):
        return _is_pool(node.value)
    return False


def _astype_calls(node: ast.AST):
    """Yield every ``<expr>.astype(...)`` call inside ``node``."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) \
                and isinstance(sub.func, ast.Attribute) \
                and sub.func.attr == "astype":
            yield sub


class _Scanner(ast.NodeVisitor):
    def __init__(self, mod, aliases: dict[str, str]) -> None:
        self.mod = mod
        self.aliases = aliases
        self.findings: list[Finding] = []

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        # (1) pool.astype(...) / pool["q"].astype(...)
        if isinstance(fn, ast.Attribute) and fn.attr == "astype" \
                and _is_pool(fn.value):
            self._flag(node, "pool dtype cast in the hot closure — the "
                             "scatters own the pool representation "
                             "(quantize-on-write); drop the .astype")
        # (3) kc.block_until_ready() — host sync on the pool
        if isinstance(fn, ast.Attribute) \
                and fn.attr in ("block_until_ready",) \
                and _is_pool(fn.value):
            self._flag(node, "host sync on the KV pool in serving "
                             "code — KV stays on device")
        # (3) np.asarray(pool) / jax.device_get(pool): the argument
        # must BE a pool reference — reading back kernel outputs that
        # merely close over a pool (np.asarray(fn(q, kp, vp))) is the
        # normal way offline profiling scripts check results
        name = canonical_call(node, self.aliases)
        if name in HOST_READS and any(_is_pool(a) for a in node.args):
            self._flag(node, "host-side readback of the KV pool — "
                             "dequantization happens inside the jitted "
                             "gather (gather_view), never on host")
        # (2) writer call with a cast argument
        wname = None
        if isinstance(fn, ast.Name):
            wname = fn.id
        elif isinstance(fn, ast.Attribute):
            wname = fn.attr
        if wname in WRITERS:
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                for cast in _astype_calls(arg):
                    self._flag(cast, f"dtype cast at the "
                               f"'{wname}' boundary — the scatter "
                               f"quantizes/casts on write; pass the "
                               f"raw rows")
        self.generic_visit(node)

    def _flag(self, node: ast.AST, msg: str) -> None:
        self.findings.append(Finding(
            RULE_ID, self.mod.rel, node.lineno, node.col_offset, msg))


def run(project: Project, graph=None) -> list[Finding]:
    findings: list[Finding] = []
    for mod in project.modules:
        aliases = import_aliases(mod.tree)
        scanner = _Scanner(mod, aliases)
        scanner.visit(mod.tree)
        findings.extend(scanner.findings)
    return findings
