"""Rule ``lock-discipline``.

Lockset approximation over each class body: any attribute that is ever
mutated inside ``with self.<...lock...>:`` anywhere in the class is
treated as lock-protected; a mutation of that attribute outside a lock
context is flagged as a candidate race.

"Mutation" means ``self.x = / += ...``, ``self.x[...] = ...``,
``del self.x[...]``, and calls of container mutators
(``self.x.append(...)``, ``.pop``, ``.update``, ...).

Lock contexts (where mutation is legal):

- lexically inside a ``with`` whose context expression mentions a name
  containing ``lock`` (``self._lock``, ``self._slo_lock``,
  ``cv``/``Condition`` objects named ``*lock*``);
- methods named ``*_locked`` — the repo's convention for helpers that
  document "caller holds the lock" in their name;
- ``__init__``/``__new__``/``__enter__``/``__exit__``/``__del__`` and
  module-level class bodies — construction and teardown predate
  sharing.

This is deliberately a one-lockset-per-class approximation (classes
with several locks are treated as one). It trades soundness for
signal: with ~200 lock sites in the tree it is the strongest race
catcher available without a runtime TSan.
"""

from __future__ import annotations

import ast

from ..core import Finding, Project

RULE_ID = "lock-discipline"

MUTATORS = {"append", "appendleft", "extend", "extendleft", "insert",
            "add", "remove", "discard", "pop", "popleft", "popitem",
            "clear", "update", "setdefault", "sort", "reverse"}
EXEMPT_METHODS = {"__init__", "__new__", "__enter__", "__exit__",
                  "__del__", "__post_init__"}


def _mentions_lock(node: ast.expr) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and "lock" in n.attr.lower():
            return True
        if isinstance(n, ast.Name) and "lock" in n.id.lower():
            return True
    return False


def _self_attr_target(node: ast.expr) -> str | None:
    """The attribute name when ``node`` mutates ``self.<attr>`` (plain,
    subscripted, or nested-subscript)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


class _Mutation:
    __slots__ = ("attr", "node", "locked", "establishes", "method", "kind")

    def __init__(self, attr: str, node: ast.AST, locked: bool,
                 establishes: bool, method: str, kind: str) -> None:
        self.attr = attr
        self.node = node
        self.locked = locked          # legal here (lock held or exempt)
        self.establishes = establishes  # proves the attr IS lock-protected
        self.method = method
        self.kind = kind


class _ClassScanner(ast.NodeVisitor):
    def __init__(self, class_name: str) -> None:
        self.class_name = class_name
        self.mutations: list[_Mutation] = []
        self._method: str | None = None
        self._lock_depth = 0
        self._method_exempt = False

    # -- context tracking
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_method(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_method(node)

    def _visit_method(self, node) -> None:
        if self._method is not None:
            # nested function: inherits the enclosing lock context
            self.generic_visit(node)
            return
        self._method = node.name
        self._method_exempt = (node.name in EXEMPT_METHODS
                               or node.name.endswith("_locked"))
        self.generic_visit(node)
        self._method = None
        self._method_exempt = False

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        return  # nested classes get their own scanner

    def visit_With(self, node: ast.With) -> None:
        self._visit_with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._visit_with(node)

    def _visit_with(self, node) -> None:
        locked = any(_mentions_lock(item.context_expr)
                     for item in node.items)
        if locked:
            self._lock_depth += 1
        self.generic_visit(node)
        if locked:
            self._lock_depth -= 1

    @property
    def _locked(self) -> bool:
        return self._lock_depth > 0 or self._method_exempt \
            or self._method is None

    # -- mutation collection
    def _note(self, attr: str | None, node: ast.AST, kind: str) -> None:
        if attr is None or self._method is None:
            return
        # an actual `with ...lock:` block, or a helper whose name signs
        # the "caller holds the lock" contract, proves the attribute is
        # lock-protected; __init__-style exemptions prove nothing
        establishes = (self._lock_depth > 0
                       or (self._method or "").endswith("_locked"))
        self.mutations.append(_Mutation(attr, node, self._locked,
                                        establishes, self._method, kind))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            for el in (t.elts if isinstance(t, (ast.Tuple, ast.List))
                       else [t]):
                self._note(_self_attr_target(el), node, "assignment")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._note(_self_attr_target(node.target), node, "assignment")
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._note(_self_attr_target(node.target), node, "assignment")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._note(_self_attr_target(t), node, "delete")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in MUTATORS:
            self._note(_self_attr_target(f.value), node,
                       f".{f.attr}() mutation")
        self.generic_visit(node)


def run(project: Project, graph=None) -> list[Finding]:
    findings: list[Finding] = []
    for mod in project.modules:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            scanner = _ClassScanner(node.name)
            for stmt in node.body:
                scanner.visit(stmt)
            lockset = {m.attr for m in scanner.mutations if m.establishes}
            for m in scanner.mutations:
                if m.locked or m.attr not in lockset:
                    continue
                findings.append(Finding(
                    RULE_ID, mod.rel, m.node.lineno, m.node.col_offset,
                    f"'{node.name}.{m.attr}' is written under "
                    f"'with ...lock:' elsewhere in this class but this "
                    f"{m.kind} in '{m.method}' is unlocked — a candidate "
                    f"race (hold the lock, or rename the helper "
                    f"'*_locked' if the caller holds it)"))
    return findings
