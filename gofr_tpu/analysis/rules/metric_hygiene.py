"""Rule ``metric-hygiene``.

Statically extract every metric-name string literal and cross-check
write sites against registration sites, both ways:

- a **write** (``increment_counter``/``add_counter``/
  ``delta_up_down_counter``/``record_histogram``/``set_gauge``) whose
  name is registered nowhere in the linted tree is a silent
  log-and-drop — flagged at the write;
- a **registration** (``new_counter``/``new_up_down_counter``/
  ``new_histogram``/``new_gauge``) whose name is written nowhere is an
  orphan — dead exposition surface — flagged at the registration;
- a write or registration whose name is **not a string literal** is
  invisible to static checking — flagged so it either becomes a
  literal or carries an allow() explaining the dynamism.

This supersedes the breadth half of the dynamic registry-coverage test
(tests/test_observability.py) and catches what that test cannot:
metrics only written on error paths a test never drives.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass

from ..core import Finding, Project

RULE_ID = "metric-hygiene"

WRITE_METHODS = {"increment_counter", "add_counter",
                 "delta_up_down_counter", "record_histogram", "set_gauge"}
REG_METHODS = {"new_counter", "new_up_down_counter", "new_histogram",
               "new_gauge"}


@dataclass
class _Site:
    names: tuple[str, ...] | None   # None: dynamic (non-literal) name
    method: str
    rel: str
    line: int
    col: int


def _loop_bindings(tree: ast.Module) -> dict[str, tuple[str, ...]]:
    """Unroll the repo's registration idiom statically:

        for name, desc in (("app_x", "..."), ("app_y", "...")):
            metrics.new_gauge(name, desc)

    (also via a module-level constant: ``for name, desc in _GAUGES:``).
    Maps loop-variable name -> every constant string it binds. A
    module-wide map is an approximation (loop vars could collide across
    functions), biased toward fewer false "dynamic name" findings.
    """
    consts: dict[str, ast.expr] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            consts[node.targets[0].id] = node.value
    out: dict[str, set[str]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.For):
            continue
        it = node.iter
        if isinstance(it, ast.Name):
            it = consts.get(it.id)
        if not isinstance(it, (ast.Tuple, ast.List)):
            continue
        if isinstance(node.target, ast.Tuple):
            targets = [(i, t.id) for i, t in enumerate(node.target.elts)
                       if isinstance(t, ast.Name)]
        elif isinstance(node.target, ast.Name):
            targets = [(None, node.target.id)]
        else:
            continue
        for el in it.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                for pos, tname in targets:
                    if pos is None:
                        out.setdefault(tname, set()).add(el.value)
            elif isinstance(el, (ast.Tuple, ast.List)):
                for pos, tname in targets:
                    if pos is not None and pos < len(el.elts):
                        v = el.elts[pos]
                        if isinstance(v, ast.Constant) \
                                and isinstance(v.value, str):
                            out.setdefault(tname, set()).add(v.value)
    return {k: tuple(sorted(v)) for k, v in out.items()}


def _name_arg(call: ast.Call,
              loops: dict[str, tuple[str, ...]]) -> tuple[str, ...] | None:
    arg: ast.expr | None = None
    if call.args:
        arg = call.args[0]
    else:
        for kw in call.keywords:
            if kw.arg == "name":
                arg = kw.value
                break
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return (arg.value,)
    if isinstance(arg, ast.Name) and arg.id in loops:
        return loops[arg.id]
    return None


def collect_sites(project: Project) -> tuple[list[_Site], list[_Site]]:
    """All (writes, registrations) in the linted tree."""
    writes: list[_Site] = []
    regs: list[_Site] = []
    for mod in project.modules:
        loops = _loop_bindings(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Attribute):
                continue
            meth = node.func.attr
            if meth not in WRITE_METHODS and meth not in REG_METHODS:
                continue
            site = _Site(_name_arg(node, loops), meth, mod.rel,
                         node.lineno, node.col_offset)
            (writes if meth in WRITE_METHODS else regs).append(site)
    return writes, regs


def written_names(project: Project) -> set[str]:
    """The statically-extracted metric write surface — what the
    meta-test cross-checks against the dynamic registry-coverage scan."""
    writes, _ = collect_sites(project)
    return {n for w in writes if w.names for n in w.names}


def registered_names(project: Project) -> set[str]:
    _, regs = collect_sites(project)
    return {n for r in regs if r.names for n in r.names}


def run(project: Project, graph=None) -> list[Finding]:
    writes, regs = collect_sites(project)
    if not writes and not regs:
        return []
    reg_names = {n for r in regs if r.names for n in r.names}
    write_names = {n for w in writes if w.names for n in w.names}
    findings: list[Finding] = []
    for w in writes:
        if w.names is None:
            findings.append(Finding(
                RULE_ID, w.rel, w.line, w.col,
                f"metric name passed to {w.method}() is not a string "
                f"literal — static hygiene cannot verify it"))
            continue
        for n in w.names:
            if n not in reg_names:
                findings.append(Finding(
                    RULE_ID, w.rel, w.line, w.col,
                    f"metric '{n}' is written ({w.method}) but "
                    f"registered nowhere in the linted tree — a silent "
                    f"log-and-drop at runtime"))
    for r in regs:
        if r.names is None:
            findings.append(Finding(
                RULE_ID, r.rel, r.line, r.col,
                f"metric name passed to {r.method}() is not a string "
                f"literal — static hygiene cannot verify it"))
            continue
        for n in r.names:
            if n not in write_names:
                findings.append(Finding(
                    RULE_ID, r.rel, r.line, r.col,
                    f"metric '{n}' is registered ({r.method}) but "
                    f"written nowhere in the linted tree — orphaned "
                    f"exposition surface"))
    return findings
