"""Rule ``hot-path-purity``.

Functions marked ``@hot_path`` — and everything they statically call
within the package (see ``callgraph.py`` for the resolution
approximation) — must stay dispatch-bound: no device syncs, no wall
clock, no logging, no metric writes. The sanctioned exits are
``@hot_path_boundary`` functions (retire/collect/failure handling),
where the walk stops.

Forbidden constructs:

- ``<expr>.item()`` — a device sync, full stop.
- ``numpy.asarray(...)`` / ``numpy.array(...)`` — device->host copy
  when handed a jax array (``jnp.asarray`` stays on device and is
  allowed).
- ``jax.device_get`` / ``block_until_ready`` (function or method).
- ``int()/float()/bool()`` applied *directly to a jax/jnp call* — the
  statically-visible slice of "coercion of a traced value". Coercing a
  host value (``int(self.lengths[i])`` over a numpy mirror) is not
  flagged; the dynamic transfer-guard test still owns that blind spot.
- ``time.time()`` / ``datetime.now()/utcnow()`` — wall-clock reads;
  ``time.perf_counter`` / ``monotonic`` are the sanctioned timers and
  stay legal.
- logging calls (``logger.info`` etc., any receiver whose name says
  logger/logging/log).
- metric writes through the Manager API (``increment_counter``,
  ``add_counter``, ``delta_up_down_counter``, ``record_histogram``,
  ``set_gauge``).
"""

from __future__ import annotations

import ast

from ..callgraph import CallGraph, FuncKey
from ..core import Finding, Project, canonical_call, import_aliases

RULE_ID = "hot-path-purity"

METRIC_WRITES = {"increment_counter", "add_counter",
                 "delta_up_down_counter", "record_histogram", "set_gauge"}
LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
               "critical", "log", "fatal"}
LOG_RECEIVERS = {"logger", "logging", "log", "_logger", "_log"}
WALL_CLOCK = {"time.time", "datetime.now", "datetime.utcnow",
              "datetime.datetime.now", "datetime.datetime.utcnow"}
SYNC_FUNCS = {"numpy.asarray", "numpy.array", "jax.device_get",
              "jax.block_until_ready"}
JAX_ROOTS = {"jax", "jax.numpy"}


def _is_jax_expr(node: ast.expr, aliases: dict[str, str]) -> bool:
    """True when ``node`` is itself a call into jax/jnp — the static
    stand-in for "this expression is a traced value"."""
    if not isinstance(node, ast.Call):
        return False
    name = canonical_call(node, aliases)
    if name is None:
        return False
    head = name.rsplit(".", 1)[0] if "." in name else name
    return head in JAX_ROOTS or name.startswith("jax.")


def _receiver_is_logger(func: ast.Attribute) -> bool:
    base = func.value
    if isinstance(base, ast.Name):
        return base.id in LOG_RECEIVERS
    if isinstance(base, ast.Attribute):  # self.logger / app._logger / ctx.log
        return base.attr in LOG_RECEIVERS
    return False


def _scan_function(info, chain: list[str],
                   aliases: dict[str, str]) -> list[Finding]:
    out: list[Finding] = []
    mod = info.module
    via = "" if len(chain) == 1 else \
        " (on the hot path via %s)" % " -> ".join(
            c.split("::")[-1] for c in chain)

    def flag(node: ast.AST, what: str) -> None:
        out.append(Finding(
            RULE_ID, mod.rel, node.lineno, node.col_offset,
            f"{what} in hot-path function "
            f"'{info.key.qualname}'{via}"))

    for node in ast.walk(info.node):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        # <expr>.item()
        if isinstance(func, ast.Attribute) and func.attr == "item" \
                and not node.args and not node.keywords:
            flag(node, "device sync '.item()'")
            continue
        if isinstance(func, ast.Attribute) \
                and func.attr == "block_until_ready":
            flag(node, "device sync 'block_until_ready'")
            continue
        name = canonical_call(node, aliases)
        if name in SYNC_FUNCS:
            flag(node, f"device sync '{name}'")
            continue
        if name in WALL_CLOCK:
            flag(node, f"wall-clock read '{name}' (use time.perf_counter "
                       "outside the hot path)")
            continue
        if isinstance(func, ast.Name) and func.id in ("int", "float", "bool") \
                and node.args and _is_jax_expr(node.args[0], aliases):
            flag(node, f"'{func.id}()' coerces a traced jax value "
                       "(implicit device sync)")
            continue
        if isinstance(func, ast.Attribute) and func.attr in METRIC_WRITES:
            flag(node, f"metric write '.{func.attr}(...)'")
            continue
        if isinstance(func, ast.Attribute) and func.attr in LOG_METHODS \
                and _receiver_is_logger(func):
            flag(node, f"logging call '.{func.attr}(...)'")
            continue
    return out


def run(project: Project, graph: CallGraph) -> list[Finding]:
    findings: list[Finding] = []
    closure = graph.hot_closure()
    alias_cache: dict[str, dict[str, str]] = {}
    seen: set[tuple[str, int, int]] = set()  # nested defs walk twice
    for key, chain in sorted(closure.items(),
                             key=lambda kv: (kv[0].module, kv[0].qualname)):
        info = graph.funcs[key]
        aliases = alias_cache.setdefault(
            info.module.rel, import_aliases(info.module.tree))
        for f in _scan_function(info, chain, aliases):
            spot = (f.path, f.line, f.col)
            if spot not in seen:
                seen.add(spot)
                findings.append(f)
    return findings
