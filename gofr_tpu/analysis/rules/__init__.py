"""gofrlint ruleset. Each rule module exposes RULE_ID and
``run(project, graph) -> list[Finding]``; the registry here is what the
CLI and the analyzer driver iterate."""

from __future__ import annotations

from . import (async_blocking, hot_path, kv_quant, locks,
               metric_hygiene, recompile)

ALL_RULES = (hot_path, locks, async_blocking, metric_hygiene, recompile,
             kv_quant)

RULE_IDS = tuple(r.RULE_ID for r in ALL_RULES)
