"""Static call graph over the linted project — the approximation the
``hot-path-purity`` closure walks.

Resolution is deliberately conservative and syntactic:

- ``self.X(...)``      -> method ``X`` of the lexically enclosing class
- ``X(...)``           -> nested function in an enclosing scope, else a
                          module-level function in the same module, else
                          a same-project function imported via
                          ``from gofr_tpu.mod import X``
- anything else (``obj.method()``, calls through containers, dynamic
  dispatch) is NOT followed — the forbidden-construct scanner still
  sees the call expression itself, so ``self.metrics.add_counter(...)``
  is caught as a metric write even though we never descend into the
  metrics manager.

Functions marked ``@hot_path_boundary(...)`` terminate traversal: they
are the engine's sanctioned retire/collect exits where host-side
assembly is the point.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from .annotations import BOUNDARY_ATTR, HOT_PATH_ATTR  # noqa: F401  (re-export for docs)
from .core import Module, Project, dotted_name

FuncDef = ast.FunctionDef | ast.AsyncFunctionDef


@dataclass(frozen=True)
class FuncKey:
    module: str          # Module.rel
    qualname: str        # "Engine._decode_step" / "helper" / "outer.<locals>.inner"

    def __str__(self) -> str:
        return f"{self.module}::{self.qualname}"


@dataclass
class FuncInfo:
    key: FuncKey
    node: FuncDef
    module: Module
    class_name: str | None
    hot_root: bool = False
    boundary: bool = False
    boundary_reason: str | None = None
    calls: list[tuple[FuncKey, ast.Call]] = field(default_factory=list)


def _decorator_name(dec: ast.expr) -> str | None:
    if isinstance(dec, ast.Call):
        dec = dec.func
    return dotted_name(dec)


def _is_hot_decorator(dec: ast.expr) -> bool:
    name = _decorator_name(dec)
    return name is not None and name.split(".")[-1] == "hot_path"


def _boundary_reason(dec: ast.expr) -> str | None:
    if not isinstance(dec, ast.Call):
        return None
    name = _decorator_name(dec)
    if name is None or name.split(".")[-1] != "hot_path_boundary":
        return None
    if dec.args and isinstance(dec.args[0], ast.Constant) \
            and isinstance(dec.args[0].value, str):
        return dec.args[0].value
    return ""  # boundary with a non-literal reason: treated as present


class _Collector(ast.NodeVisitor):
    """Index every function definition with its lexical context."""

    def __init__(self, mod: Module, graph: "CallGraph") -> None:
        self.mod = mod
        self.graph = graph
        self.class_stack: list[str] = []
        self.func_stack: list[str] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def _visit_func(self, node: FuncDef) -> None:
        if self.func_stack:
            qual = ".".join(self.func_stack) + f".<locals>.{node.name}"
        elif self.class_stack:
            qual = ".".join(self.class_stack) + f".{node.name}"
        else:
            qual = node.name
        key = FuncKey(self.mod.rel, qual)
        info = FuncInfo(
            key=key, node=node, module=self.mod,
            class_name=self.class_stack[-1] if self.class_stack else None)
        for dec in node.decorator_list:
            if _is_hot_decorator(dec):
                info.hot_root = True
            reason = _boundary_reason(dec)
            if reason is not None:
                info.boundary = True
                info.boundary_reason = reason
        self.graph.add(info)
        self.func_stack.append(qual)
        self.generic_visit(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


class CallGraph:
    def __init__(self, project: Project) -> None:
        self.project = project
        self.funcs: dict[FuncKey, FuncInfo] = {}
        # (module_rel, class_name, method) -> key;  (module_rel, name) -> key
        self._methods: dict[tuple[str, str, str], FuncKey] = {}
        self._module_funcs: dict[tuple[str, str], FuncKey] = {}
        self._dotted = project.module_by_dotted()
        for mod in project.modules:
            _Collector(mod, self).visit(mod.tree)
        self._link()

    def add(self, info: FuncInfo) -> None:
        self.funcs[info.key] = info
        if info.class_name and "." not in info.key.qualname.replace(
                info.class_name + ".", "", 1):
            self._methods[(info.key.module, info.class_name,
                           info.node.name)] = info.key
        if info.class_name is None and "<locals>" not in info.key.qualname:
            self._module_funcs[(info.key.module, info.node.name)] = info.key

    # -- resolution ---------------------------------------------------

    def _resolve(self, info: FuncInfo, call: ast.Call) -> FuncKey | None:
        func = call.func
        # self.X(...)
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id in ("self", "cls")
                and info.class_name is not None):
            return self._methods.get(
                (info.key.module, info.class_name, func.attr))
        # bare X(...)
        if isinstance(func, ast.Name):
            # nested function within this function's scope chain
            qual = info.key.qualname
            while qual:
                cand = FuncKey(info.key.module,
                               f"{qual}.<locals>.{func.id}")
                if cand in self.funcs:
                    return cand
                if "." not in qual:
                    break
                qual = qual.rsplit(".", 1)[0]
                if qual.endswith("<locals>"):
                    qual = qual.rsplit(".", 1)[0]
            got = self._module_funcs.get((info.key.module, func.id))
            if got is not None:
                return got
            # from gofr_tpu.x import y — follow into a sibling module
            target = self._import_target(info.module, func.id)
            if target is not None:
                return target
        return None

    def _import_target(self, mod: Module, name: str) -> FuncKey | None:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ImportFrom):
                continue
            for a in node.names:
                if (a.asname or a.name) != name:
                    continue
                target_mod = self._find_from_module(mod, node)
                if target_mod is not None:
                    return self._module_funcs.get((target_mod.rel, a.name))
        return None

    def _find_from_module(self, mod: Module,
                          node: ast.ImportFrom) -> Module | None:
        parts = list(Path(mod.rel).with_suffix("").parts)
        if parts and parts[-1] == "__init__":
            parts = parts[:-1]
        if node.level:
            base = parts[:-(node.level)] if node.level <= len(parts) else []
            dotted = ".".join(base + (node.module.split(".") if node.module else []))
        else:
            dotted = node.module or ""
        return self._dotted.get(dotted)

    def _link(self) -> None:
        for info in self.funcs.values():
            for call in (n for n in ast.walk(info.node)
                         if isinstance(n, ast.Call)):
                target = self._resolve(info, call)
                if target is not None and target != info.key:
                    info.calls.append((target, call))

    # -- closure ------------------------------------------------------

    def hot_closure(self) -> dict[FuncKey, list[str]]:
        """Every function reachable from a ``@hot_path`` root without
        crossing a ``@hot_path_boundary``. Maps key -> a sample call
        chain (root-first qualnames) for diagnostics."""
        out: dict[FuncKey, list[str]] = {}
        stack: list[tuple[FuncKey, list[str]]] = [
            (k, [str(k)]) for k, f in self.funcs.items() if f.hot_root]
        while stack:
            key, chain = stack.pop()
            info = self.funcs.get(key)
            if info is None or key in out:
                continue
            if info.boundary and len(chain) > 1:
                continue  # sanctioned exit — do not descend
            out[key] = chain
            for callee, _ in info.calls:
                if callee not in out:
                    stack.append((callee, chain + [str(callee)]))
        return out
