"""OpenAPI spec serving + Swagger UI (reference pkg/gofr/swagger.go).

Two modes, auto-registered at ``/.well-known/*`` like the reference
(swagger.go:59-70):

- **file mode** (reference parity): if ``./static/openapi.json``
  exists, it is served verbatim at ``/.well-known/openapi.json``
  (swagger.go:24-35 reads the file from disk per request, so edits
  show up without a restart).
- **generated mode** (no reference counterpart): otherwise the spec is
  generated from the app's live route table — every registered route
  becomes a path item, ``{param}`` segments become path parameters,
  and model-serving routes get typed request/response schemas.

The UI at ``/.well-known/swagger`` is a self-contained offline HTML
page (no CDN assets — the deployment may have zero egress) that
fetches the JSON spec and renders an interactive route explorer with
try-it-out requests.
"""

from __future__ import annotations

import json
import os
from typing import Any

from .http.response import File, Raw

OPENAPI_JSON = "openapi.json"
WELL_KNOWN_SPEC = f"/.well-known/{OPENAPI_JSON}"
WELL_KNOWN_UI = "/.well-known/swagger"

_SKIP_PATHS = {"/.well-known/health", "/.well-known/alive",
               WELL_KNOWN_SPEC, WELL_KNOWN_UI, "/favicon.ico"}

_STATUS_BY_METHOD = {"POST": "201", "DELETE": "204"}


def generate_spec(app: Any) -> dict:
    """Build an OpenAPI 3.0 document from the live route table."""
    paths: dict[str, dict] = {}
    for route in app.router.routes:
        if route.pattern in _SKIP_PATHS:
            continue
        item = paths.setdefault(route.pattern, {})
        op: dict[str, Any] = {
            "summary": (getattr(route.handler, "__doc__", None) or
                        f"{route.method} {route.pattern}").strip()
                       .split("\n")[0],
            "operationId": f"{route.method.lower()}_"
                           + route.pattern.strip("/").replace("/", "_")
                             .replace("{", "").replace("}", "") ,
            "responses": {
                _STATUS_BY_METHOD.get(route.method, "200"): {
                    "description": "success",
                    "content": {"application/json": {"schema": {
                        "$ref": "#/components/schemas/Envelope"}}},
                }
            },
        }
        params = [{"name": seg[1:-1], "in": "path", "required": True,
                   "schema": {"type": "string"}}
                  for seg in route.segments
                  if seg.startswith("{") and seg.endswith("}")]
        if params:
            op["parameters"] = params
        if route.method in ("POST", "PUT", "PATCH"):
            op["requestBody"] = {"content": {"application/json": {
                "schema": {"type": "object"}}}}
        item[route.method.lower()] = op

    # health endpoints documented explicitly
    paths["/.well-known/health"] = {"get": {
        "summary": "Aggregate health of every datasource, service and "
                   "TPU runtime",
        "responses": {"200": {"description": "UP or DEGRADED"}}}}
    paths["/.well-known/alive"] = {"get": {
        "summary": "Liveness probe",
        "responses": {"200": {"description": "alive"}}}}

    container = getattr(app, "container", None)
    return {
        "openapi": "3.0.3",
        "info": {
            "title": getattr(container, "app_name", "gofr-tpu app"),
            "version": getattr(container, "app_version", "dev"),
        },
        "paths": dict(sorted(paths.items())),
        "components": {"schemas": {
            "Envelope": {
                "type": "object",
                "properties": {
                    "data": {},
                    "error": {"type": "object", "properties": {
                        "message": {"type": "string"}}},
                    "metadata": {"type": "object"},
                },
            },
        }},
    }


def make_openapi_handler(app: Any, static_dir: str = "static"):
    """File mode when ./static/openapi.json exists, else generated."""

    def openapi_handler(ctx: Any) -> Any:
        path = os.path.join(static_dir, OPENAPI_JSON)
        if os.path.isfile(path):
            with open(path, "rb") as f:  # re-read per request, like the ref
                return File(content=f.read(),
                            content_type="application/json")
        return Raw(generate_spec(app))
    return openapi_handler


_UI_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>{title} — API</title><style>
body{{font-family:system-ui,sans-serif;margin:0;background:#fafafa;color:#1a1a1a}}
header{{background:#1a237e;color:#fff;padding:14px 24px;font-size:18px}}
main{{max-width:920px;margin:24px auto;padding:0 16px}}
.op{{background:#fff;border:1px solid #ddd;border-radius:6px;margin:10px 0}}
.op summary{{padding:10px 14px;cursor:pointer;display:flex;gap:12px;align-items:center}}
.m{{font-weight:700;min-width:60px;text-align:center;border-radius:4px;padding:3px 0;color:#fff;font-size:13px}}
.GET{{background:#1976d2}}.POST{{background:#388e3c}}.PUT{{background:#f57c00}}
.PATCH{{background:#7b1fa2}}.DELETE{{background:#d32f2f}}
.body{{padding:10px 14px;border-top:1px solid #eee}}
textarea,input{{width:100%;box-sizing:border-box;font-family:monospace;margin:4px 0}}
pre{{background:#263238;color:#c3e88d;padding:10px;border-radius:4px;overflow:auto;max-height:320px}}
button{{background:#1a237e;color:#fff;border:0;border-radius:4px;padding:6px 14px;cursor:pointer}}
small{{color:#777}}</style></head><body>
<header>{title} <small style="color:#9fa8da">v{version} — OpenAPI explorer</small></header>
<main id="ops">loading spec…</main>
<script>
fetch("{spec_url}").then(r=>r.json()).then(spec=>{{
  const main=document.getElementById("ops");main.innerHTML="";
  for(const [path,item] of Object.entries(spec.paths||{{}})){{
    for(const [method,op] of Object.entries(item)){{
      const d=document.createElement("details");d.className="op";
      const M=method.toUpperCase();
      d.innerHTML=`<summary><span class="m ${{M}}">${{M}}</span>`+
        `<code>${{path}}</code> <small>${{op.summary||""}}</small></summary>`+
        `<div class="body"><div class="params"></div>`+
        (op.requestBody?`<textarea rows=4 class="reqbody">{{}}</textarea>`:"")+
        `<button>Try it</button><pre hidden></pre></div>`;
      const params=op.parameters||[];
      const pdiv=d.querySelector(".params");
      for(const p of params){{
        pdiv.insertAdjacentHTML("beforeend",
          `<label>${{p.name}} <input data-name="${{p.name}}"></label>`);
      }}
      d.querySelector("button").onclick=async()=>{{
        let url=path;
        for(const inp of d.querySelectorAll("input[data-name]"))
          url=url.replace("{{"+inp.dataset.name+"}}",encodeURIComponent(inp.value));
        const init={{method:M}};
        const ta=d.querySelector(".reqbody");
        if(ta){{init.body=ta.value;init.headers={{"Content-Type":"application/json"}}}}
        const pre=d.querySelector("pre");pre.hidden=false;
        try{{const r=await fetch(url,init);
          const text=await r.text();
          let shown=text;try{{shown=JSON.stringify(JSON.parse(text),null,2)}}catch(e){{}}
          pre.textContent=r.status+" "+r.statusText+"\\n"+shown;
        }}catch(e){{pre.textContent="request failed: "+e}}
      }};
      main.appendChild(d);
    }}
  }}
}}).catch(e=>{{document.getElementById("ops").textContent="failed to load spec: "+e}});
</script></body></html>"""


def make_swagger_ui_handler(app: Any):
    def swagger_ui_handler(ctx: Any) -> Any:
        container = getattr(app, "container", None)
        html = _UI_HTML.format(
            title=getattr(container, "app_name", "gofr-tpu app"),
            version=getattr(container, "app_version", "dev"),
            spec_url=WELL_KNOWN_SPEC)
        return File(content=html.encode(), content_type="text/html")
    return swagger_ui_handler


def register(app: Any, static_dir: str = "static") -> None:
    """Install the spec + UI routes (reference swagger.go:59-70 gates on
    the file existing; generated mode means we always have a spec)."""
    app.router.add("GET", WELL_KNOWN_SPEC, make_openapi_handler(app, static_dir))
    app.router.add("GET", WELL_KNOWN_UI, make_swagger_ui_handler(app))
