"""Serving-path observability: flight recorder, engine trace assembly,
on-demand profiler capture, MFU derivation.

Everything in this module is HOST-side bookkeeping over timestamps and
counters the engine already collects. The hard invariant is **zero
perturbation of the hot path**: no device syncs, no host->device
transfers, no blocking work on the decode dispatch/collect path. The
pass ring is an append-only ``deque`` (CPython appends are atomic under
the GIL — no lock on the writer side), spans are assembled *after* a
request retires from timestamps recorded along the way, and the MFU
gauge is derived once at compile time from the decode graph's
``cost_analysis()`` FLOPs — serve-time updates are pure host
arithmetic. The transfer-guard test (zero steady-state h2d) and the
greedy bit-identity tests run with all of this enabled.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any


class FlightRecorder:
    """Fixed-size ring of per-pass records plus a short log of retired
    requests' event trails — the engine's black box. Served as JSON at
    ``/debug/engine``, summarized in ``Engine.health_check()``, dumped
    through the logger when the hot loop crashes.

    Writer side (the engine thread) only ever appends plain dicts to
    bounded deques; reader side (``snapshot``) copies under the GIL.
    ``size <= 0`` disables recording entirely.
    """

    def __init__(self, size: int = 256, request_logs: int = 32) -> None:
        self.enabled = size > 0
        self.size = max(0, int(size))
        self._passes: deque = deque(maxlen=max(1, self.size))
        self._requests: deque = deque(maxlen=max(1, int(request_logs)))
        self._seq = 0
        self._by_kind: dict[str, int] = {}

    # ------------------------------------------------------------ writers
    def record_pass(self, kind: str, **fields: Any) -> None:
        if not self.enabled:
            return
        self._seq += 1
        rec = {"seq": self._seq, "kind": kind, "t": time.time()}
        rec.update(fields)
        self._passes.append(rec)
        self._by_kind[kind] = self._by_kind.get(kind, 0) + 1

    def record_request(self, summary: dict) -> None:
        if self.enabled:
            self._requests.append(summary)

    # ------------------------------------------------------------ readers
    def snapshot(self, n: int | None = None) -> dict:
        passes = list(self._passes)
        if n is not None and n > 0:
            passes = passes[-n:]
        return {"enabled": self.enabled, "ring_size": self.size,
                "passes_recorded": self._seq, "passes": passes,
                "requests": list(self._requests)}

    def summary(self) -> dict:
        last = self._passes[-1] if self._passes else None
        out = {"passes_recorded": self._seq, "by_kind": dict(self._by_kind)}
        if last is not None:
            out["last_pass_kind"] = last["kind"]
            out["last_pass_age_s"] = round(time.time() - last["t"], 3)
        return out

    def fleet_summary(self) -> dict:
        """Compact per-host digest attached to control-plane heartbeats
        (serving/control_plane.py): p50/p95 pass duration, mean
        occupancy, last queue depth, tokens/s — computed over the pass
        ring, on the heartbeat thread, from fields already recorded.
        The leader derives fleet skew and straggler gauges from these."""
        passes = list(self._passes)
        out: dict = {"passes_recorded": self._seq,
                     "by_kind": dict(self._by_kind)}
        durs = sorted(p["dur"] for p in passes
                      if isinstance(p.get("dur"), (int, float)))
        if durs:
            out["pass_p50_s"] = round(durs[int(0.5 * (len(durs) - 1))], 6)
            out["pass_p95_s"] = round(durs[int(0.95 * (len(durs) - 1))], 6)
        occ = [p["occupancy"] for p in passes
               if isinstance(p.get("occupancy"), (int, float))]
        if occ:
            out["occupancy_mean"] = round(sum(occ) / len(occ), 3)
        depths = [p["queue_depth"] for p in passes
                  if isinstance(p.get("queue_depth"), (int, float))]
        if depths:
            out["queue_depth"] = depths[-1]
        timed = [p for p in passes if "tokens" in p]
        if len(timed) >= 2:
            span = timed[-1]["t"] - timed[0]["t"]
            if span > 0:
                out["tokens_per_s"] = round(
                    sum(p["tokens"] for p in timed[1:]) / span, 2)
        return out

    def dump(self, logger: Any, reason: str = "") -> None:
        """Post-mortem: the ring is exactly what you want to see after
        a crash — the last N passes before the loop died."""
        if logger is None or not self.enabled:
            return
        try:
            text = json.dumps(self.snapshot(), default=str)
            logger.error(f"engine flight recorder ({reason or 'dump'}): "
                         f"{text[:16384]}")
        except Exception:
            pass


def request_summary(req: Any) -> dict:
    """Flight-recorder entry for a retired request — plain host fields."""
    return {
        "prompt_tokens": len(req.prompt_tokens),
        "generated": len(req.generated),
        "slot": req.slot,
        "submitted_at": req.submitted_at,
        "admitted_at": req.admitted_at,
        "first_token_at": req.first_token_at,
        "finished_at": req.finished_at,
        "ttft_ms": round(req.ttft_ms, 3) if req.ttft_ms is not None else None,
        "error": req.error,
        "cancelled": req.cancelled,
        "events": [{"name": name, "t0": t0, "t1": t1, **(attrs or {})}
                   for name, t0, t1, attrs in req.events],
    }


def emit_engine_spans(tracer: Any, req: Any) -> None:
    """Assemble the ``engine.*`` child spans for a retired request and
    export them through the tracer. Called once at retire, entirely from
    host timestamps recorded along the lifecycle — the hot loop never
    creates spans. ``req.trace`` carries (trace_id, parent_span_id)
    captured at submit from the caller's active span (the HTTP/gRPC
    middleware span) or the inbound ``traceparent``, so one distributed
    trace runs HTTP -> engine -> retire."""
    trace = getattr(req, "trace", None)
    if tracer is None or trace is None:
        return
    trace_id, parent_id = trace
    end = req.finished_at or time.time()
    status = "OK" if req.error is None else f"ERROR: {req.error}"
    root = tracer.emit_span(
        "engine.request", trace_id=trace_id, parent_id=parent_id,
        start_time=req.submitted_at, end_time=end, status=status,
        attributes={"prompt_tokens": len(req.prompt_tokens),
                    "generated_tokens": len(req.generated),
                    "slot": req.slot, "cancelled": req.cancelled})
    admit = req.admitted_at or req.first_token_at or end
    tracer.emit_span("engine.queue", trace_id=trace_id,
                     parent_id=root.span_id, start_time=req.submitted_at,
                     end_time=admit)
    for name, t0, t1, attrs in req.events:
        tracer.emit_span(f"engine.{name}", trace_id=trace_id,
                         parent_id=root.span_id, start_time=t0,
                         end_time=t1, attributes=attrs)
    if req.first_token_at is not None:
        n = len(req.generated)
        tpot = ((end - req.first_token_at) / (n - 1)) if n > 1 else None
        tracer.emit_span(
            "engine.decode", trace_id=trace_id, parent_id=root.span_id,
            start_time=req.first_token_at, end_time=end,
            attributes={"tokens": n,
                        "tpot_s": round(tpot, 6) if tpot else None})
    tracer.emit_span("engine.retire", trace_id=trace_id,
                     parent_id=root.span_id, start_time=end, end_time=end,
                     attributes={"error": req.error or ""})


# ----------------------------------------------------------- watchdog
class StallWatchdog:
    """Promotes the engine's PASSIVE stall flag into action.

    ``Engine.health_check()`` flips to DEGRADED when work is in flight
    but no pass has completed for ``stall_threshold_s`` — but nothing
    reads that unless an orchestrator happens to poll. This thread
    polls it on the worker itself and, once per stall episode:

    - dumps the flight recorder through the logger (the last N passes
      before the hang are the post-mortem),
    - emits an ``engine.stall`` span and bumps the
      ``app_engine_stalls`` counter + ``stats["stalls"]``,

    after which the next control-plane heartbeat (whose health source
    is this same ``health_check``) reports DEGRADED and the leader can
    evict + re-rank survivors instead of waiting for heartbeat silence.

    Everything runs on this thread against host-side state — the hot
    loop is never touched (zero-perturbation invariant). Re-arms when
    the engine recovers, so a flapping device reports each episode.
    """

    def __init__(self, engine: Any, interval_s: float = 5.0) -> None:
        self.engine = engine
        self.interval_s = max(0.05, float(interval_s))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._escalated = False

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="engine-watchdog")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(self.interval_s + 1.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check_once()
            except Exception:  # a broken check must not kill the thread
                pass

    def check_once(self) -> bool:
        """One poll; returns True when a stall was escalated."""
        engine = self.engine
        health = engine.health_check()
        stalled = (health.get("status") == "DEGRADED"
                   and "stalled_for_s" in health)
        if not stalled:
            self._escalated = False
            return False
        if self._escalated:
            return False  # already reported this episode
        self._escalated = True
        stalled_for = health.get("stalled_for_s")
        engine.stats["stalls"] = engine.stats.get("stalls", 0) + 1
        if engine.logger is not None:
            engine.logger.error(
                "engine stalled: work in flight but no pass for "
                f"{stalled_for}s", active=health.get("active_slots"),
                waiting=health.get("waiting"))
        engine.recorder.dump(engine.logger,
                             reason=f"stall: no pass for {stalled_for}s")
        if engine.metrics is not None:
            engine.metrics.increment_counter("app_engine_stalls")
        tracer = getattr(engine, "tracer", None)
        if tracer is not None:
            tracer.start_span("engine.stall", attributes={
                "stalled_for_s": stalled_for,
                "active_slots": health.get("active_slots"),
                "waiting": health.get("waiting")}).end()
        return True


# ------------------------------------------------------------------- MFU
#
# Peak dense bf16 FLOPs per chip by device kind (same table the bench
# uses). Unknown kinds (CPU, future TPUs) -> None and the MFU gauge
# simply stays 0 — never a guess.
TPU_PEAK_FLOPS = {"TPU v5 lite": 197e12, "TPU v5p": 459e12,
                  "TPU v5": 459e12, "TPU v4": 275e12,
                  "TPU v6 lite": 918e12}


def device_peak_flops() -> float | None:
    try:
        import jax
        kind = jax.devices()[0].device_kind
    except Exception:
        return None
    for name, peak in sorted(TPU_PEAK_FLOPS.items(),
                             key=lambda kv: -len(kv[0])):
        if kind.startswith(name):
            return peak
    return None


def jit_cost_flops(jitted: Any, *args: Any) -> float | None:
    """FLOPs of one call of a jitted function, from XLA's own cost
    analysis of the lowered/compiled graph. Runs at compile time (the
    engine calls it from ``warmup``), never on the serving path; every
    failure mode degrades to None."""
    try:
        lowered = jitted.lower(*args)
    except Exception:
        return None
    cost = None
    for source in (lambda: lowered.cost_analysis(),
                   lambda: lowered.compile().cost_analysis()):
        try:
            cost = source()
        except Exception:
            cost = None
        if cost is not None:
            break
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if isinstance(cost, dict) and cost.get("flops"):
        return float(cost["flops"])
    return None


# -------------------------------------------------------------- profiler
class ProfilerCapture:
    """On-demand TPU profiler capture wrapping
    ``jax.profiler.start_trace/stop_trace`` with single-flight
    semantics — the state machine behind ``POST /debug/profile/start``
    and ``/debug/profile/stop``. A second start while a capture runs is
    refused (JAX would raise); stop without a start reports cleanly."""

    def __init__(self, base_dir: str = "/tmp/gofr_tpu_profiles",
                 logger: Any = None) -> None:
        self.base_dir = base_dir
        self.logger = logger
        self._lock = threading.Lock()
        self._active_dir: str | None = None
        self._started_at: float | None = None

    def start(self, trace_dir: str | None = None) -> dict:
        with self._lock:
            if self._active_dir is not None:
                return {"ok": False, "error": "capture already running",
                        "dir": self._active_dir}
            path = trace_dir or os.path.join(
                self.base_dir, time.strftime("%Y%m%d-%H%M%S"))
            try:
                os.makedirs(path, exist_ok=True)
                import jax
                jax.profiler.start_trace(path)
            except Exception as exc:
                return {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
            self._active_dir = path
            self._started_at = time.time()
            if self.logger:
                self.logger.info(f"profiler capture started: {path}")
            return {"ok": True, "dir": path}

    def stop(self) -> dict:
        with self._lock:
            if self._active_dir is None:
                return {"ok": False, "error": "no capture running"}
            path, self._active_dir = self._active_dir, None
            started, self._started_at = self._started_at, None
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception as exc:
                return {"ok": False, "dir": path,
                        "error": f"{type(exc).__name__}: {exc}"}
            if self.logger:
                self.logger.info(f"profiler capture stopped: {path}")
            return {"ok": True, "dir": path,
                    "duration_s": round(time.time() - started, 3)
                    if started else None}

    def status(self) -> dict:
        return {"running": self._active_dir is not None,
                "dir": self._active_dir}
