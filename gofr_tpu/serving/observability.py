"""Serving-path observability: flight recorder, workload capture,
engine trace assembly, tenant usage metering, SLO burn-rate tracking,
on-demand profiler capture, MFU derivation.

Everything in this module is HOST-side bookkeeping over timestamps and
counters the engine already collects. The hard invariant is **zero
perturbation of the hot path**: no device syncs, no host->device
transfers, no blocking work on the decode dispatch/collect path. The
pass ring is an append-only ``deque`` (CPython appends are atomic under
the GIL — no lock on the writer side), spans are assembled *after* a
request retires from timestamps recorded along the way, and the MFU
gauge is derived once at compile time from the decode graph's
``cost_analysis()`` FLOPs — serve-time updates are pure host
arithmetic. The transfer-guard test (zero steady-state h2d) and the
greedy bit-identity tests run with all of this enabled.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any

from .events import NO_EVENTS


class FlightRecorder:
    """Fixed-size ring of per-pass records plus a short log of retired
    requests' event trails — the engine's black box. Served as JSON at
    ``/debug/engine``, summarized in ``Engine.health_check()``, dumped
    through the logger when the hot loop crashes.

    Writer side (the engine thread) only ever appends plain dicts to
    bounded deques; reader side (``snapshot``) copies under the GIL.
    ``size <= 0`` disables recording entirely.
    """

    def __init__(self, size: int = 256, request_logs: int = 32) -> None:
        self.enabled = size > 0
        self.size = max(0, int(size))
        self._passes: deque = deque(maxlen=max(1, self.size))
        self._requests: deque = deque(maxlen=max(1, int(request_logs)))
        self._seq = 0
        self._by_kind: dict[str, int] = {}
        #: optional () -> goodput summary (GoodputMeter.summary); the
        #: engine wires its meter here so fleet_summary carries the
        #: waste breakdown and the leader can say WHY a host is slow
        self.goodput_source: Any = None
        #: optional () -> prefix-cache digest (Engine.prefix_digest);
        #: rides fleet_summary so the leader's router can score hosts
        #: by longest resident prefix without any new protocol
        self.prefix_digest_source: Any = None
        #: optional () -> per-signature cost table (CostModel.table);
        #: rides fleet_summary so the leader can compare hosts on the
        #: SAME compiled graph (signature-normalized straggler math)
        #: instead of the workload-mix-confounded p95
        self.cost_source: Any = None
        #: optional () -> integrity digest block
        #: (IntegrityPlane.summary); rides fleet_summary so the leader
        #: can majority-vote golden-probe digests across hosts and
        #: quarantine the outlier (serving/integrity.py)
        self.integrity_source: Any = None

    # ------------------------------------------------------------ writers
    def record_pass(self, kind: str, **fields: Any) -> None:
        if not self.enabled:
            return
        self._seq += 1
        rec = {"seq": self._seq, "kind": kind, "t": time.time()}
        rec.update(fields)
        self._passes.append(rec)
        self._by_kind[kind] = self._by_kind.get(kind, 0) + 1

    def record_request(self, summary: dict) -> None:
        if self.enabled:
            self._requests.append(summary)

    # ------------------------------------------------------------ readers
    def snapshot(self, n: int | None = None) -> dict:
        passes = list(self._passes)
        if n is not None and n > 0:
            passes = passes[-n:]
        return {"enabled": self.enabled, "ring_size": self.size,
                "passes_recorded": self._seq, "passes": passes,
                "requests": list(self._requests)}

    def summary(self) -> dict:
        last = self._passes[-1] if self._passes else None
        out = {"passes_recorded": self._seq, "by_kind": dict(self._by_kind)}
        if last is not None:
            out["last_pass_kind"] = last["kind"]
            out["last_pass_age_s"] = round(time.time() - last["t"], 3)
        return out

    def fleet_summary(self) -> dict:
        """Compact per-host digest attached to control-plane heartbeats
        (serving/control_plane.py): p50/p95 pass duration, mean
        occupancy, last queue depth, tokens/s — computed over the pass
        ring, on the heartbeat thread, from fields already recorded.
        The leader derives fleet skew and straggler gauges from these."""
        passes = list(self._passes)
        out: dict = {"passes_recorded": self._seq,
                     "by_kind": dict(self._by_kind)}
        durs = sorted(p["dur"] for p in passes
                      if isinstance(p.get("dur"), (int, float)))
        if durs:
            out["pass_p50_s"] = round(durs[int(0.5 * (len(durs) - 1))], 6)
            out["pass_p95_s"] = round(durs[int(0.95 * (len(durs) - 1))], 6)
        occ = [p["occupancy"] for p in passes
               if isinstance(p.get("occupancy"), (int, float))]
        if occ:
            out["occupancy_mean"] = round(sum(occ) / len(occ), 3)
        depths = [p["queue_depth"] for p in passes
                  if isinstance(p.get("queue_depth"), (int, float))]
        if depths:
            out["queue_depth"] = depths[-1]
        timed = [p for p in passes if "tokens" in p]
        if len(timed) >= 2:
            span = timed[-1]["t"] - timed[0]["t"]
            if span > 0:
                out["tokens_per_s"] = round(
                    sum(p["tokens"] for p in timed[1:]) / span, 2)
        if self.goodput_source is not None:
            try:
                g = self.goodput_source() or {}
            except Exception:
                g = {}
            for key in ("goodput_ratio", "busy_s", "useful_s",
                        "waste_s"):
                if g.get(key) is not None:
                    out[key] = g[key]
        if self.prefix_digest_source is not None:
            try:
                digest = self.prefix_digest_source()
            except Exception:
                digest = None
            if digest:
                out["prefix_digest"] = digest
        if self.cost_source is not None:
            try:
                costs = self.cost_source()
            except Exception:
                costs = None
            if costs:
                out["costs"] = costs
        if self.integrity_source is not None:
            try:
                integ = self.integrity_source()
            except Exception:
                integ = None
            if integ:
                out["integrity"] = integ
        return out

    def dump(self, logger: Any, reason: str = "") -> None:
        """Post-mortem: the ring is exactly what you want to see after
        a crash — the last N passes before the loop died."""
        if logger is None or not self.enabled:
            return
        try:
            text = json.dumps(self.snapshot(), default=str)
            logger.error(f"engine flight recorder ({reason or 'dump'}): "
                         f"{text[:16384]}")
        except Exception:
            pass


def request_summary(req: Any) -> dict:
    """Flight-recorder entry for a retired request — plain host fields."""
    return {
        "prompt_tokens": len(req.prompt_tokens),
        "generated": len(req.generated),
        "slot": req.slot,
        "tenant": getattr(req, "tenant", None),
        "device_s": round(getattr(req, "device_s", 0.0), 6),
        "submitted_at": req.submitted_at,
        "admitted_at": req.admitted_at,
        "first_token_at": req.first_token_at,
        "finished_at": req.finished_at,
        "ttft_ms": round(req.ttft_ms, 3) if req.ttft_ms is not None else None,
        "error": req.error,
        "cancelled": req.cancelled,
        "digest": getattr(req, "digest", None),
        "events": [{"name": name, "t0": t0, "t1": t1, **(attrs or {})}
                   for name, t0, t1, attrs in req.events],
    }


# ------------------------------------------------- goodput accounting
class GoodputMeter:
    """Device-time waste attribution with a hard conservation
    invariant: every accounted device-second is classified as
    ``useful`` or one of the waste causes, and

        ``useful_s + sum(waste_s.values()) == busy_s``

    holds at all times (useful is computed as the residual of each
    pass's classification, so the identity is structural, not
    statistical — tests pin it across every pass kind).

    Causes (the taxonomy ``/debug/efficiency`` and
    ``app_engine_waste_seconds{cause}`` expose):

    - ``padding`` — inactive/pad rows in a dispatched fixed-shape
      batch: empty decode slots, dummy prefill-group rows, verify rows
      discarded before collect. The kernels tolerate them by design;
      the meter prices them.
    - ``preempt_recompute`` — prefill time spent re-computing KV a
      preempted request already produced once (vLLM-style
      preemption-by-recompute), plus batch-prefill rows orphaned by a
      preemption mid-flight.
    - ``spec_rejected`` — the drafted-minus-accepted fraction of each
      speculative verify row: positions computed and thrown away.
    - ``bubble`` — wall-clock gaps between a collect completing with
      NOTHING left in flight and the next dispatch, while work was
      waiting (queued, requeued or active). Host scheduling overhead
      the device spends idle — the dispatch-bound regime BENCH_r05
      measured, now a named number.
    - ``integrity_probe`` — device time spent serving golden canary
      probes (serving/integrity.py): correct-by-design synthetic
      traffic, re-priced out of ``useful`` at the probe's retire
      (:meth:`reprice_probe`) so correctness verification is never
      mistaken for serving goodput.

    Everything is engine-thread float arithmetic at dispatch/collect —
    the same single-writer discipline as the FlightRecorder; no locks,
    no device syncs, zero hot-path perturbation (the transfer-guard
    and greedy bit-identity tests run with the meter ON). ``busy_s``
    sums per-pass durations, so with pipelining it may exceed wall
    time — it is an attribution base, not a wall clock.
    """

    CAUSES = ("padding", "preempt_recompute", "spec_rejected", "bubble",
              "integrity_probe")

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = bool(enabled)
        self.reset()

    def reset(self) -> None:
        self.busy_s = 0.0
        self.useful_s = 0.0
        self.waste_s = {c: 0.0 for c in self.CAUSES}
        self.passes = 0
        #: per-pass-kind sub-ledger for the /debug/efficiency rollup
        self.by_kind: dict[str, dict] = {}
        self._free_at: float | None = None
        self._backlog = False

    # ------------------------------------------------------------ feeds
    def _account(self, kind: str, busy: float, useful: float,
                 **wastes: float) -> None:
        self.busy_s += busy
        self.useful_s += useful
        sub = self.by_kind.setdefault(
            kind, {"busy_s": 0.0, "useful_s": 0.0,
                   **{c: 0.0 for c in self.CAUSES}})
        sub["busy_s"] += busy
        sub["useful_s"] += useful
        for cause, amount in wastes.items():
            if amount:
                self.waste_s[cause] += amount
                sub[cause] += amount
        self.passes += 1

    def add_decode(self, busy: float, served_rows: int,
                   batch: int) -> None:
        """A decode pass: the graph always runs the full ``batch``
        shape; rows that emitted no kept tokens (empty slots,
        pending-prefill sentinels, retired requests riding out a
        pipelined pass) are padding."""
        if not self.enabled or busy <= 0 or batch <= 0:
            return
        served = max(0, min(int(served_rows), batch))
        useful = busy * served / batch
        self._account("decode", busy, useful, padding=busy - useful)

    def add_prefill(self, kind: str, busy: float, group: int,
                    fresh_rows: int, recompute_rows: int) -> None:
        """A (batch or chunk) prefill dispatch of ``group`` padded
        rows: ``fresh_rows`` computed new KV, ``recompute_rows``
        re-prefilled a preempted request's history (or were orphaned
        by one), the rest were dummy pad rows."""
        if not self.enabled or busy <= 0 or group <= 0:
            return
        share = busy / group
        fresh = max(0, min(int(fresh_rows), group))
        recomp = max(0, min(int(recompute_rows), group - fresh))
        self._account(kind, busy, fresh * share,
                      preempt_recompute=recomp * share,
                      padding=(group - fresh - recomp) * share)

    def add_spec(self, busy: float, batch: int,
                 rows: list[tuple[int, int]]) -> None:
        """A speculative verify pass over a full-``batch`` graph.
        ``rows`` carries one ``(drafted, accepted)`` pair per row that
        survived to collect; each row's useful fraction is the emitted
        tokens (accepted + bonus) over its fed positions
        (1 + drafted), the rejected remainder is ``spec_rejected``,
        and rows not fed (or discarded by a mid-pass preemption) are
        padding."""
        if not self.enabled or busy <= 0 or batch <= 0:
            return
        share = busy / batch
        useful = rejected = 0.0
        for drafted, accepted in rows:
            drafted = max(0, int(drafted))
            accepted = max(0, min(int(accepted), drafted))
            useful += share * (1 + accepted) / (1 + drafted)
            rejected += share * (drafted - accepted) / (1 + drafted)
        self._account("spec_verify", busy, useful,
                      spec_rejected=rejected,
                      padding=max(0, batch - len(rows)) * share)

    def reprice_probe(self, device_s: float) -> None:
        """Re-price a retired golden probe's attributed device time
        from ``useful`` to the ``integrity_probe`` waste cause —
        ``busy_s`` unchanged, so the conservation identity stays
        structural. The transfer lands in ``by_kind`` as a dedicated
        ``integrity_probe`` journal row (zero busy, negative useful)
        so per-kind sums still reconcile against the totals."""
        if not self.enabled or device_s <= 0:
            return
        moved = min(float(device_s), self.useful_s)
        if moved <= 0:
            return
        self.useful_s -= moved
        self.waste_s["integrity_probe"] += moved
        sub = self.by_kind.setdefault(
            "integrity_probe", {"busy_s": 0.0, "useful_s": 0.0,
                                **{c: 0.0 for c in self.CAUSES}})
        sub["useful_s"] -= moved
        sub["integrity_probe"] += moved

    def note_pass_end(self, t: float, backlog: bool) -> None:
        """The device went idle at host time ``t`` (a collect finished
        with nothing left in flight). ``backlog`` records whether work
        was waiting — only then does the gap to the next dispatch
        count as a bubble."""
        if self.enabled:
            self._free_at = t
            self._backlog = bool(backlog)

    def note_dispatch(self, t: float) -> None:
        """A device dispatch at host time ``t`` closes any open idle
        gap; with backlog pending, the gap was a bubble: device-time
        lost to host-side scheduling while requests waited."""
        if not self.enabled or self._free_at is None:
            return
        gap = t - self._free_at
        self._free_at = None
        if self._backlog and gap > 0:
            self.busy_s += gap
            self.waste_s["bubble"] += gap

    # ---------------------------------------------------------- readers
    def summary(self) -> dict:
        """The compact digest: heartbeat summaries, workload headers,
        the bench payload."""
        busy = self.busy_s
        out = {"busy_s": round(busy, 6),
               "useful_s": round(self.useful_s, 6),
               "waste_s": {c: round(v, 6)
                           for c, v in self.waste_s.items()}}
        if busy > 0:
            out["goodput_ratio"] = round(self.useful_s / busy, 6)
        return out

    def dominant_waste(self) -> str | None:
        worst = max(self.waste_s, key=self.waste_s.get, default=None)
        return worst if worst and self.waste_s[worst] > 0 else None

    def state(self) -> dict:
        """The full ``/debug/efficiency`` payload: totals, per-kind
        breakdown, dominant cause, and the conservation residual (a
        float-epsilon health check on the invariant itself)."""
        out = self.summary()
        out["enabled"] = self.enabled
        out["passes"] = self.passes
        out["dominant_waste"] = self.dominant_waste()
        out["by_kind"] = {k: {kk: round(vv, 6) for kk, vv in sub.items()}
                          for k, sub in self.by_kind.items()}
        out["conservation_error_s"] = round(
            self.busy_s - self.useful_s - sum(self.waste_s.values()), 9)
        return out


class WatermarkTracker:
    """Memory high-water marks with timestamps: KV-pool pages (or rows
    for the slot layout), prefix-cache pages, and host RSS. Updated on
    the engine's throttled gauge cadence — pure host compares, monotone
    non-decreasing within a run by construction. Served in
    ``/debug/efficiency`` and as ``app_engine_*_watermark`` gauges."""

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = bool(enabled)
        self._marks: dict[str, dict] = {}
        #: EventLedger high-water crossings are recorded on (engine
        #: wiring); crossings within 5% of the last recorded one are
        #: not re-recorded, so a slowly creeping mark can't flood the
        #: ring while the ratchet still lands in the timeline
        self.events = NO_EVENTS
        self._event_marks: dict[str, float] = {}

    def update(self, name: str, value: float,
               t: float | None = None) -> bool:
        """Record ``value`` if it is a new high-water mark; returns
        True when the mark advanced."""
        if not self.enabled:
            return False
        mark = self._marks.get(name)
        if mark is not None and value <= mark["value"]:
            return False
        self._marks[name] = {"value": value,
                             "t": time.time() if t is None else t}
        last = self._event_marks.get(name)
        if last is None or value >= last * 1.05:
            self._event_marks[name] = value
            self.events.emit("obs.watermark", cause=name, value=value)
        return True

    def update_rss(self) -> None:
        """Host RSS high-water mark from the kernel's own accounting
        (``ru_maxrss`` is already a max — one cheap syscall)."""
        if not self.enabled:
            return
        try:
            import resource
            kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            self.update("host_rss_bytes", float(kb) * 1024.0)
        except Exception:
            pass

    def get(self, name: str) -> float | None:
        mark = self._marks.get(name)
        return mark["value"] if mark is not None else None

    def state(self) -> dict:
        return {name: dict(mark) for name, mark in self._marks.items()}


class RecompileSentinel:
    """Detects unexpected post-warmup XLA recompiles from dispatch
    shape signatures.

    The engine's graphs are keyed by static shape tuples — prefill
    (bucket, group), chunk (width, group, window), decode (window),
    verify (draft width). ``warmup()`` observes every signature it
    compiles, then ``seal()``s the sentinel; after that, the first
    dispatch of a NOVEL signature is, by construction, a lowering the
    warmup did not cover — a serving-path recompile. The engine bumps
    ``app_engine_recompiles`` and WARNs once per signature with the
    offending shape, so a shape-induced recompile storm names itself
    instead of surfacing as an unexplained p99 explosion.

    Host-side set lookups at dispatch time — O(1), no device work.
    Engines that never warm up never seal, so the sentinel stays
    silent (everything is an expected cold compile then)."""

    MAX_SIGNATURES = 32

    def __init__(self, enabled: bool = True) -> None:
        self.enabled = bool(enabled)
        self.sealed = False
        self.recompiles = 0
        self.signatures: list[str] = []
        self._seen: set = set()

    def observe(self, sig: tuple) -> None:
        """Seed an expected signature (warmup-time compiles)."""
        if self.enabled:
            self._seen.add(sig)

    def seal(self) -> None:
        """Warmup is done: novel signatures are recompiles from now."""
        self.sealed = True

    def dispatch(self, sig: tuple) -> bool:
        """Note a dispatch; True when it is a novel POST-warmup shape
        (fires exactly once per signature — the repeat dispatch hits a
        warm graph and stays silent)."""
        if not self.enabled or sig in self._seen:
            return False
        self._seen.add(sig)
        if not self.sealed:
            return False
        self.recompiles += 1
        if len(self.signatures) < self.MAX_SIGNATURES:
            self.signatures.append("/".join(str(p) for p in sig))
        return True

    def state(self) -> dict:
        return {"enabled": self.enabled, "sealed": self.sealed,
                "recompiles": self.recompiles,
                "signatures": list(self.signatures),
                "known_shapes": len(self._seen)}


# ------------------------------------------------- workload capture
#
# Versioned workload-file format (JSONL): the first line is a header
# object, every following line one retired request. The replay driver
# (serving/replay.py) refuses unknown formats/versions, so the header
# is the compatibility contract — bump WORKLOAD_VERSION on any
# incompatible record change.
WORKLOAD_FORMAT = "gofr-workload"
WORKLOAD_VERSION = 1


def salted_token_hash(tokens: Any, salt: str) -> str:
    """Stable redaction digest of a token-id sequence. The salt is
    drawn per recorder (never serialized), so captured hashes cannot
    be dictionary-attacked against a known tokenizer — but two
    requests with the same prompt in one capture still collide, which
    is exactly what replay-divergence comparison needs."""
    body = ",".join(str(int(t)) for t in tokens)
    return hashlib.sha256(f"{salt}:{body}".encode()).hexdigest()[:24]


class WorkloadRecorder:
    """Bounded ring of per-request workload records — the capturable,
    replayable twin of the :class:`FlightRecorder` (which keeps pass
    telemetry; this keeps the *traffic*). Served as a versioned JSONL
    file at ``GET /debug/workload``, armed/disarmed by
    ``POST /debug/workload/start|stop`` or ``EngineConfig.workload_capture``.

    Records are host-assembled ONCE per request at retire
    (``Engine._finalize_obs``), from fields the engine already carries:
    arrival timestamp, prompt token ids, sampling params, the engine's
    resolved sampling seed, tenant label, and the outcome
    (completion ids, TTFT/TPOT/e2e, finish reason). The hot loop never
    touches this — the zero-perturbation invariant of the module holds
    with capture ON (tested).

    ``redact=True`` swaps prompt/completion token ids for salted
    hashes (lengths preserved): safe to ship off-box, still good for
    load-shape replay and hash-level divergence checks, but NOT for
    bit-identity replay (the prompts are gone — ``replay_workload``
    refuses).
    """

    def __init__(self, size: int = 4096, *, redact: bool = False,
                 engine_seed: int | None = None) -> None:
        self.enabled = size > 0
        self.size = max(0, int(size))
        self.redact = bool(redact)
        self.engine_seed = engine_seed
        self.capturing = False
        self.started_at: float | None = None
        self._salt = os.urandom(8).hex()
        self._records: deque = deque(maxlen=max(1, self.size))
        self._seq = 0
        self._dropped = 0
        #: optional () -> GoodputMeter.summary, wired by the engine:
        #: the header then carries the capture-side efficiency digest
        #: so a replay can compare waste breakdowns, not just tokens
        self.goodput_source: Any = None
        #: optional () -> CostModel.table, wired by the engine: the
        #: header then carries the capture-side per-signature cost
        #: table so a replay can report per-kernel-class divergence
        self.cost_source: Any = None

    # ------------------------------------------------------------ control
    def start(self, redact: bool | None = None) -> dict:
        """Arm capture with a FRESH ring (a capture is one workload —
        stale records from an earlier session never bleed in)."""
        if not self.enabled:
            return self.status()
        if redact is not None:
            self.redact = bool(redact)
        self._records.clear()
        self._seq = 0
        self._dropped = 0
        self.started_at = time.time()
        self.capturing = True
        return self.status()

    def stop(self) -> dict:
        self.capturing = False
        return self.status()

    def status(self) -> dict:
        return {"enabled": self.enabled, "capturing": self.capturing,
                "redact": self.redact, "size": self.size,
                "records": len(self._records), "recorded": self._seq,
                "dropped": self._dropped, "started_at": self.started_at}

    # ------------------------------------------------------------ writer
    def record(self, req: Any) -> None:
        """One retired request -> one record. Engine-thread append of a
        plain dict onto a bounded deque — same writer discipline as the
        flight recorder."""
        if not (self.enabled and self.capturing):
            return
        self._seq += 1
        if len(self._records) == self._records.maxlen:
            self._dropped += 1
        p = req.params
        status = ("cancelled" if req.cancelled
                  else "error" if req.error is not None else "ok")
        end = req.finished_at
        n = len(req.generated)
        tpot_ms = None
        if req.first_token_at is not None and end is not None and n > 1:
            tpot_ms = (end - req.first_token_at) * 1000.0 / (n - 1)
        rec: dict = {
            "t": req.submitted_at,
            "tenant": getattr(req, "tenant", None),
            # per-request seed: today every request shares the engine's
            # resolved sampling seed (rng keys ride the graphs as
            # arguments, folded by a global step) — recorded per request
            # so the format survives a future per-request rng
            "seed": self.engine_seed,
            "params": {"temperature": p.temperature, "top_p": p.top_p,
                       "top_k": p.top_k,
                       "max_new_tokens": p.max_new_tokens},
            "status": status,
        }
        if self.redact:
            rec["prompt_hash"] = salted_token_hash(req.prompt_tokens,
                                                   self._salt)
            rec["prompt_len"] = len(req.prompt_tokens)
            rec["completion_hash"] = salted_token_hash(req.generated,
                                                       self._salt)
            rec["completion_len"] = n
        else:
            rec["prompt_tokens"] = list(req.prompt_tokens)
            rec["completion_tokens"] = list(req.generated)
        if getattr(req, "digest", None):
            # the output fingerprint (serving/integrity.py): additive
            # record field so replay can diff recorded vs replayed
            # digests (the digest_divergence report key)
            rec["digest"] = req.digest
        if req.error is not None:
            rec["error"] = str(req.error)[:200]
        if req.ttft_ms is not None:
            rec["ttft_ms"] = round(req.ttft_ms, 3)
        if tpot_ms is not None:
            rec["tpot_ms"] = round(tpot_ms, 3)
        if end is not None:
            rec["e2e_ms"] = round((end - req.submitted_at) * 1000.0, 3)
        self._records.append(rec)

    # ------------------------------------------------------------ readers
    def header(self) -> dict:
        out = {"format": WORKLOAD_FORMAT, "version": WORKLOAD_VERSION,
               "redacted": self.redact, "engine_seed": self.engine_seed,
               "started_at": self.started_at, "recorded": self._seq,
               "dropped": self._dropped}
        if self.goodput_source is not None:
            # additive field (same WORKLOAD_VERSION): readers that
            # predate it simply ignore the key
            try:
                g = self.goodput_source()
                if g and g.get("busy_s"):
                    out["goodput"] = g
            except Exception:
                pass
        if self.cost_source is not None:
            # additive field, same contract as the goodput block
            try:
                costs = self.cost_source()
                if costs:
                    out["costs"] = costs
            except Exception:
                pass
        return out

    def snapshot(self, n: int | None = None) -> dict:
        records = list(self._records)
        if n is not None and n > 0:
            records = records[-n:]
        return {"header": self.header(), "records": records}

    def to_jsonl(self, n: int | None = None) -> str:
        """The ``GET /debug/workload`` body: header line, then one
        line per record in arrival order (the ring holds retire order;
        replay sorts by ``t`` anyway)."""
        snap = self.snapshot(n)
        lines = [json.dumps(snap["header"])]
        lines.extend(json.dumps(rec) for rec in snap["records"])
        return "\n".join(lines) + "\n"


def emit_engine_spans(tracer: Any, req: Any) -> None:
    """Assemble the ``engine.*`` child spans for a retired request and
    export them through the tracer. Called once at retire, entirely from
    host timestamps recorded along the lifecycle — the hot loop never
    creates spans. ``req.trace`` carries (trace_id, parent_span_id)
    captured at submit from the caller's active span (the HTTP/gRPC
    middleware span) or the inbound ``traceparent``, so one distributed
    trace runs HTTP -> engine -> retire."""
    trace = getattr(req, "trace", None)
    if tracer is None or trace is None:
        return
    trace_id, parent_id = trace
    end = req.finished_at or time.time()
    status = "OK" if req.error is None else f"ERROR: {req.error}"
    attrs = {"prompt_tokens": len(req.prompt_tokens),
             "generated_tokens": len(req.generated),
             "slot": req.slot, "cancelled": req.cancelled}
    if getattr(req, "tenant", None):
        # the accounting identity: a trace found through an exemplar
        # names who it was served for without a ledger lookup
        attrs["tenant"] = req.tenant
    root = tracer.emit_span(
        "engine.request", trace_id=trace_id, parent_id=parent_id,
        start_time=req.submitted_at, end_time=end, status=status,
        attributes=attrs)
    admit = req.admitted_at or req.first_token_at or end
    tracer.emit_span("engine.queue", trace_id=trace_id,
                     parent_id=root.span_id, start_time=req.submitted_at,
                     end_time=admit)
    for name, t0, t1, attrs in req.events:
        tracer.emit_span(f"engine.{name}", trace_id=trace_id,
                         parent_id=root.span_id, start_time=t0,
                         end_time=t1, attributes=attrs)
    if req.first_token_at is not None:
        n = len(req.generated)
        tpot = ((end - req.first_token_at) / (n - 1)) if n > 1 else None
        tracer.emit_span(
            "engine.decode", trace_id=trace_id, parent_id=root.span_id,
            start_time=req.first_token_at, end_time=end,
            attributes={"tokens": n,
                        "tpot_s": round(tpot, 6) if tpot else None})
    tracer.emit_span("engine.retire", trace_id=trace_id,
                     parent_id=root.span_id, start_time=end, end_time=end,
                     attributes={"error": req.error or ""})


# ----------------------------------------------------- usage metering
def parse_window(spec: str | None) -> float | None:
    """``"5m"``/``"1h"``/``"30s"``/``"300"`` -> seconds; None/'' -> None
    (cumulative totals). Raises ValueError on garbage."""
    if not spec:
        return None
    spec = spec.strip().lower()
    mult = {"s": 1.0, "m": 60.0, "h": 3600.0, "d": 86400.0}.get(spec[-1])
    if mult is not None:
        return float(spec[:-1]) * mult
    return float(spec)


def _fmt_window(seconds: float) -> str:
    if seconds % 3600 == 0:
        return f"{int(seconds // 3600)}h"
    if seconds % 60 == 0:
        return f"{int(seconds // 60)}m"
    return f"{int(seconds)}s"


class UsageLedger:
    """Per-tenant usage accounting, fed once per retired request from
    the engine's ``_finalize_obs`` — the metering plane behind
    ``app_tenant_*`` metrics, ``GET /debug/usage`` and the federated
    fleet rollup.

    Everything is host arithmetic over numbers the engine already
    collected (token counts, lifecycle timestamps, the per-pass
    device-time shares accumulated during collects), recorded at
    retire on the engine thread — the hot loop never touches this.
    Cumulative totals live per tenant; a bounded event ring
    (``window_records``) answers windowed queries, so
    ``?window=5m`` rollups degrade gracefully (oldest events drop)
    instead of growing without bound.
    """

    def __init__(self, metrics: Any = None,
                 window_records: int = 4096) -> None:
        self.metrics = metrics
        self._lock = threading.Lock()
        self._totals: dict[str, dict] = {}
        self._events: deque = deque(maxlen=max(1, int(window_records)))

    @staticmethod
    def _blank() -> dict:
        return {"requests": {}, "prompt_tokens": 0,
                "completion_tokens": 0, "device_s": 0.0,
                "queue_s": 0.0, "e2e_s": 0.0,
                # who pays for inefficiency: the slice of this
                # tenant's device_s that was preemption recompute or
                # rejected speculation (padding/bubbles are systemic,
                # not attributable to one principal)
                "waste_recompute_s": 0.0, "waste_spec_s": 0.0}

    def record(self, *, tenant: str, status: str, prompt_tokens: int,
               completion_tokens: int, queue_s: float = 0.0,
               e2e_s: float = 0.0, device_s: float = 0.0,
               waste_recompute_s: float = 0.0,
               waste_spec_s: float = 0.0,
               t: float | None = None) -> None:
        t = time.time() if t is None else t
        with self._lock:
            tot = self._totals.setdefault(tenant, self._blank())
            tot["requests"][status] = tot["requests"].get(status, 0) + 1
            tot["prompt_tokens"] += int(prompt_tokens)
            tot["completion_tokens"] += int(completion_tokens)
            tot["device_s"] += float(device_s)
            tot["queue_s"] += float(queue_s)
            tot["e2e_s"] += float(e2e_s)
            tot["waste_recompute_s"] += float(waste_recompute_s)
            tot["waste_spec_s"] += float(waste_spec_s)
            self._events.append(
                {"t": t, "tenant": tenant, "status": status,
                 "prompt_tokens": int(prompt_tokens),
                 "completion_tokens": int(completion_tokens),
                 "device_s": float(device_s), "queue_s": float(queue_s),
                 "e2e_s": float(e2e_s),
                 "waste_recompute_s": float(waste_recompute_s),
                 "waste_spec_s": float(waste_spec_s)})
        m = self.metrics
        if m is None:
            return
        m.increment_counter("app_tenant_requests", tenant=tenant,
                            status=status)
        if prompt_tokens:
            m.add_counter("app_tenant_prompt_tokens",
                          float(prompt_tokens), tenant=tenant)
        if completion_tokens:
            m.add_counter("app_tenant_completion_tokens",
                          float(completion_tokens), tenant=tenant)
        if device_s > 0:
            m.add_counter("app_tenant_device_seconds", float(device_s),
                          tenant=tenant)
        if waste_recompute_s > 0:
            m.add_counter("app_tenant_waste_seconds",
                          float(waste_recompute_s), tenant=tenant,
                          cause="preempt_recompute")
        if waste_spec_s > 0:
            m.add_counter("app_tenant_waste_seconds",
                          float(waste_spec_s), tenant=tenant,
                          cause="spec_rejected")
        m.record_histogram("app_tenant_queue_seconds", float(queue_s),
                           tenant=tenant)
        m.record_histogram("app_tenant_e2e_seconds", float(e2e_s),
                           tenant=tenant)

    def rollup(self, tenant: str | None = None,
               window_s: float | None = None) -> dict:
        """The ``GET /debug/usage`` JSON: cumulative totals per tenant,
        or windowed sums over the event ring when ``window_s`` is
        given (flagged ``partial`` when the ring has rotated past the
        window start — the caller knows the sum is a floor)."""
        with self._lock:
            if window_s is None:
                per_tenant = {name: {**tot,
                                     "requests": dict(tot["requests"])}
                              for name, tot in self._totals.items()
                              if tenant is None or name == tenant}
                out = {"window": None, "tenants": per_tenant}
            else:
                cutoff = time.time() - window_s
                per_tenant = {}
                for ev in self._events:
                    if ev["t"] < cutoff:
                        continue
                    if tenant is not None and ev["tenant"] != tenant:
                        continue
                    tot = per_tenant.setdefault(ev["tenant"],
                                                self._blank())
                    tot["requests"][ev["status"]] = \
                        tot["requests"].get(ev["status"], 0) + 1
                    for key in ("prompt_tokens", "completion_tokens",
                                "device_s", "queue_s", "e2e_s",
                                "waste_recompute_s", "waste_spec_s"):
                        tot[key] += ev.get(key, 0)
                partial = bool(self._events) and \
                    self._events[0]["t"] > cutoff and \
                    len(self._events) == self._events.maxlen
                out = {"window": _fmt_window(window_s),
                       "tenants": per_tenant, "partial": partial}
        for tot in out["tenants"].values():
            for key in ("device_s", "queue_s", "e2e_s",
                        "waste_recompute_s", "waste_spec_s"):
                tot[key] = round(tot.get(key, 0.0), 6)
        return out


# -------------------------------------------------------------- SLO
@dataclass
class SLOConfig:
    """Service-level objectives for the chat path (docs/configs.md).

    A retired request is GOOD when it finished without error and met
    every configured latency threshold (``None`` disables that
    dimension); cancelled requests are excluded (the client left —
    nothing was violated). The tracker turns good/bad streams into
    multi-window burn rates against the availability target, the
    standard SRE alerting shape: burn rate 1.0 = spending the error
    budget exactly at the sustainable pace.
    """

    #: time-to-first-token threshold (seconds); None = not judged
    ttft_s: float | None = 2.0
    #: mean inter-token latency threshold (seconds); None = not judged
    tpot_s: float | None = 0.5
    #: end-to-end latency threshold (seconds); None = not judged
    e2e_s: float | None = 30.0
    #: availability objective: the target fraction of good requests
    availability: float = 0.999
    #: burn-rate windows (seconds); the SHORTEST is the fast-burn
    #: window the WARN escalation watches
    windows: tuple = (300.0, 3600.0)
    #: WARN once per episode when the fast-window burn rate crosses
    #: this (14.4 = the classic "2% of a 30-day budget in one hour"
    #: page threshold). 0 disables the escalation.
    fast_burn: float = 14.4
    #: horizon the error-budget-remaining gauge is computed over
    budget_window_s: float = 86400.0
    #: per-window event ring bound; beyond it the oldest events drop
    #: (rates stay correct over what is retained)
    max_events: int = 65536


class SLOTracker:
    """Multi-window burn-rate tracking over the retired-request
    stream: ``app_slo_burn_rate{window=...}`` and
    ``app_slo_error_budget_remaining`` gauges, the ``GET /debug/slo``
    state, and a WARN once per fast-burn episode.

    Fed from ``Engine._finalize_obs`` (host arithmetic at retire,
    zero hot-path work). Each window keeps a rolling (deque, total,
    bad) triple — O(1) amortized per request."""

    def __init__(self, config: SLOConfig | None = None,
                 metrics: Any = None, logger: Any = None) -> None:
        self.config = config if config is not None else SLOConfig()
        self.metrics = metrics
        self.logger = logger
        #: EventLedger fast-burn episodes are recorded on (app wiring)
        self.events = NO_EVENTS
        #: optional zero-arg hook fired once per fast-burn episode —
        #: the IncidentDetector's trigger rides here
        self.on_fast_burn = None
        self._lock = threading.Lock()
        horizons = tuple(sorted(set(
            tuple(self.config.windows) + (self.config.budget_window_s,))))
        self._wins = {w: {"events": deque(maxlen=self.config.max_events),
                          "total": 0, "bad": 0} for w in horizons}
        self._total = 0
        self._bad = 0
        self._escalated = False
        #: monotonic high-water mark over fed timestamps: record()
        #: clamps each t up to it so the per-window deques stay sorted
        #: — _evict_locked pops from the head while events age out,
        #: which silently under- or over-counts if a late-arriving
        #: older timestamp lands behind a newer one (replay feeds and
        #: multi-source clocks do this)
        self._last_t = float("-inf")

    # ------------------------------------------------------------ feed
    def judge(self, *, error: str | None, ttft_s: float | None,
              tpot_s: float | None, e2e_s: float | None) -> bool:
        """Good iff no error and every configured threshold held."""
        if error is not None:
            return False
        cfg = self.config
        for value, limit in ((ttft_s, cfg.ttft_s),
                             (tpot_s, cfg.tpot_s),
                             (e2e_s, cfg.e2e_s)):
            if limit is not None and value is not None and value > limit:
                return False
        return True

    def record(self, good: bool, t: float | None = None) -> None:
        t = time.time() if t is None else t
        with self._lock:
            # modest reordering tolerated: clamp to the newest seen
            # timestamp so windows stay sorted and eviction stays exact
            t = max(t, self._last_t)
            self._last_t = t
            self._total += 1
            self._bad += 0 if good else 1
            for w, win in self._wins.items():
                if win["events"].maxlen == len(win["events"]):
                    _, old_bad = win["events"][0]  # about to rotate out
                    win["total"] -= 1
                    win["bad"] -= old_bad
                win["events"].append((t, 0 if good else 1))
                win["total"] += 1
                win["bad"] += 0 if good else 1
                self._evict_locked(w, t)
            state = self._state_locked(t)
        self._publish(state)

    def _evict_locked(self, w: float, now: float) -> None:
        win = self._wins[w]
        events = win["events"]
        cutoff = now - w
        while events and events[0][0] < cutoff:
            _, bad = events.popleft()
            win["total"] -= 1
            win["bad"] -= bad

    # ----------------------------------------------------------- state
    def _burn_locked(self, w: float) -> dict:
        win = self._wins[w]
        total, bad = win["total"], win["bad"]
        err_rate = (bad / total) if total else 0.0
        budget = max(1e-9, 1.0 - self.config.availability)
        return {"total": total, "bad": bad,
                "error_rate": round(err_rate, 6),
                "burn_rate": round(err_rate / budget, 4)}

    def _state_locked(self, now: float) -> dict:
        for w in self._wins:
            self._evict_locked(w, now)
        windows = {_fmt_window(w): self._burn_locked(w)
                   for w in self.config.windows}
        bw = self.config.budget_window_s
        budget_win = self._burn_locked(bw)
        allowed = budget_win["total"] * (1.0 - self.config.availability)
        remaining = 1.0 - (budget_win["bad"] / allowed) if allowed > 0 \
            else (0.0 if budget_win["bad"] else 1.0)
        fast_w = min(self.config.windows)
        fast = windows[_fmt_window(fast_w)]["burn_rate"]
        return {
            "objectives": {"ttft_s": self.config.ttft_s,
                           "tpot_s": self.config.tpot_s,
                           "e2e_s": self.config.e2e_s,
                           "availability": self.config.availability},
            "windows": windows,
            "budget": {"window": _fmt_window(bw),
                       "total": budget_win["total"],
                       "bad": budget_win["bad"],
                       "remaining": round(max(-1.0, min(1.0, remaining)),
                                          6)},
            "fast_burn": {"window": _fmt_window(fast_w),
                          "burn_rate": fast,
                          "threshold": self.config.fast_burn,
                          "tripped": bool(self.config.fast_burn
                                          and fast >= self.config.fast_burn)},
            "lifetime": {"total": self._total, "bad": self._bad},
        }

    def state(self) -> dict:
        """The ``GET /debug/slo`` payload."""
        with self._lock:
            return self._state_locked(time.time())

    def _publish(self, state: dict) -> None:
        m = self.metrics
        if m is not None:
            for label, win in state["windows"].items():
                m.set_gauge("app_slo_burn_rate", win["burn_rate"],
                            window=label)
            m.set_gauge("app_slo_error_budget_remaining",
                        state["budget"]["remaining"])
        tripped = state["fast_burn"]["tripped"]
        if tripped and not self._escalated:
            self._escalated = True
            if self.logger is not None:
                self.logger.warn(
                    "SLO fast burn: error budget burning at "
                    f"{state['fast_burn']['burn_rate']}x over the "
                    f"{state['fast_burn']['window']} window",
                    threshold=state["fast_burn"]["threshold"],
                    budget_remaining=state["budget"]["remaining"])
            self.events.emit(
                "obs.fast_burn", severity="error",
                burn_rate=state["fast_burn"]["burn_rate"],
                window=state["fast_burn"]["window"],
                budget_remaining=state["budget"]["remaining"])
            hook = self.on_fast_burn
            if hook is not None:
                try:
                    hook()
                except Exception:
                    pass  # an incident capture must never fail a retire
        elif not tripped:
            self._escalated = False  # episode over; re-arm


# ----------------------------------------------------------- watchdog
class StallWatchdog:
    """Promotes the engine's PASSIVE stall flag into action.

    ``Engine.health_check()`` flips to DEGRADED when work is in flight
    but no pass has completed for ``stall_threshold_s`` — but nothing
    reads that unless an orchestrator happens to poll. This thread
    polls it on the worker itself and, once per stall episode:

    - dumps the flight recorder through the logger (the last N passes
      before the hang are the post-mortem),
    - emits an ``engine.stall`` span and bumps the
      ``app_engine_stalls`` counter + ``stats["stalls"]``,

    after which the next control-plane heartbeat (whose health source
    is this same ``health_check``) reports DEGRADED and the leader can
    evict + re-rank survivors instead of waiting for heartbeat silence.

    Everything runs on this thread against host-side state — the hot
    loop is never touched (zero-perturbation invariant). Re-arms when
    the engine recovers, so a flapping device reports each episode.
    """

    def __init__(self, engine: Any, interval_s: float = 5.0) -> None:
        self.engine = engine
        self.interval_s = max(0.05, float(interval_s))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._escalated = False

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="engine-watchdog")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(self.interval_s + 1.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check_once()
            except Exception:  # a broken check must not kill the thread
                pass

    def check_once(self) -> bool:
        """One poll; returns True when a stall was escalated."""
        engine = self.engine
        health = engine.health_check()
        stalled = (health.get("status") == "DEGRADED"
                   and "stalled_for_s" in health)
        if not stalled:
            self._escalated = False
            return False
        if self._escalated:
            return False  # already reported this episode
        self._escalated = True
        stalled_for = health.get("stalled_for_s")
        engine.stats["stalls"] = engine.stats.get("stalls", 0) + 1
        if engine.logger is not None:
            engine.logger.error(
                "engine stalled: work in flight but no pass for "
                f"{stalled_for}s", active=health.get("active_slots"),
                waiting=health.get("waiting"))
        engine.recorder.dump(engine.logger,
                             reason=f"stall: no pass for {stalled_for}s")
        if engine.metrics is not None:
            engine.metrics.increment_counter("app_engine_stalls")
        tracer = getattr(engine, "tracer", None)
        if tracer is not None:
            tracer.start_span("engine.stall", attributes={
                "stalled_for_s": stalled_for,
                "active_slots": health.get("active_slots"),
                "waiting": health.get("waiting")}).end()
        getattr(engine, "events", NO_EVENTS).emit(
            "fleet.stall", severity="error",
            cause="no pass completed",
            stalled_for_s=stalled_for,
            active_slots=health.get("active_slots"),
            waiting=health.get("waiting"))
        return True


# ------------------------------------------------------------------- MFU
#
# Peak dense bf16 FLOPs per chip by device kind (same table the bench
# uses). Unknown kinds (CPU, future TPUs) -> None and the MFU gauge
# simply stays 0 — never a guess.
TPU_PEAK_FLOPS = {"TPU v5 lite": 197e12, "TPU v5p": 459e12,
                  "TPU v5": 459e12, "TPU v4": 275e12,
                  "TPU v6 lite": 918e12}


def device_peak_flops() -> float | None:
    try:
        import jax
        kind = jax.devices()[0].device_kind
    except Exception:
        return None
    for name, peak in sorted(TPU_PEAK_FLOPS.items(),
                             key=lambda kv: -len(kv[0])):
        if kind.startswith(name):
            return peak
    return None


def jit_cost_flops(jitted: Any, *args: Any) -> float | None:
    """FLOPs of one call of a jitted function, from XLA's own cost
    analysis of the lowered/compiled graph. Runs at compile time (the
    engine calls it from ``warmup``), never on the serving path; every
    failure mode degrades to None."""
    try:
        lowered = jitted.lower(*args)
    except Exception:
        return None
    cost = None
    for source in (lambda: lowered.cost_analysis(),
                   lambda: lowered.compile().cost_analysis()):
        try:
            cost = source()
        except Exception:
            cost = None
        if cost is not None:
            break
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if isinstance(cost, dict) and cost.get("flops"):
        return float(cost["flops"])
    return None


# -------------------------------------------------------------- profiler
class ProfilerCapture:
    """On-demand TPU profiler capture wrapping
    ``jax.profiler.start_trace/stop_trace`` with single-flight
    semantics — the state machine behind ``POST /debug/profile/start``
    and ``/debug/profile/stop``. A second start while a capture runs is
    refused (JAX would raise); stop without a start reports cleanly.

    Hardening: a capture started with ``max_capture_s`` (per-start or
    the constructor default) is auto-stopped by a daemon watchdog timer
    — a forgotten ``stop`` can no longer let xprof buffer events
    forever. ``stop(force=True)`` recovers a crashed/leaked capture:
    it calls ``jax.profiler.stop_trace`` even when this state machine
    thinks nothing is running (a previous failed stop cleared the local
    state while JAX kept tracing) and swallows the stop error, so the
    next ``start`` works again."""

    def __init__(self, base_dir: str = "/tmp/gofr_tpu_profiles",
                 logger: Any = None,
                 max_capture_s: float = 0.0) -> None:
        self.base_dir = base_dir
        self.logger = logger
        #: default auto-stop budget for every capture; 0 = unbounded
        #: (per-start ``max_capture_s`` overrides)
        self.max_capture_s = max(0.0, float(max_capture_s))
        self._lock = threading.Lock()
        self._active_dir: str | None = None
        self._started_at: float | None = None
        self._timer: threading.Timer | None = None
        self.auto_stops = 0

    def start(self, trace_dir: str | None = None, *,
              max_capture_s: float | None = None) -> dict:
        with self._lock:
            if self._active_dir is not None:
                return {"ok": False, "error": "capture already running",
                        "dir": self._active_dir}
            path = trace_dir or os.path.join(
                self.base_dir, time.strftime("%Y%m%d-%H%M%S"))
            try:
                os.makedirs(path, exist_ok=True)
                import jax
                jax.profiler.start_trace(path)
            except Exception as exc:
                return {"ok": False, "error": f"{type(exc).__name__}: {exc}"}
            self._active_dir = path
            self._started_at = time.time()
            cap = self.max_capture_s if max_capture_s is None \
                else max(0.0, float(max_capture_s))
            if cap > 0:
                self._timer = threading.Timer(cap, self._expire, (path,))
                self._timer.daemon = True
                self._timer.start()
            if self.logger:
                self.logger.info(f"profiler capture started: {path}")
            return {"ok": True, "dir": path}

    def _expire(self, path: str) -> None:
        """Watchdog body: stop the capture iff it is still the one the
        timer was armed for (a manual stop + fresh start must not be
        killed by the previous capture's timer)."""
        with self._lock:
            if self._active_dir != path:
                return
            self.auto_stops += 1
        result = self.stop()
        if self.logger and result.get("ok"):
            self.logger.warn(
                f"profiler capture auto-stopped at max_capture_s: {path}")

    def stop(self, force: bool = False) -> dict:
        with self._lock:
            timer, self._timer = self._timer, None
            if timer is not None:
                timer.cancel()
            if self._active_dir is None:
                if not force:
                    return {"ok": False, "error": "no capture running"}
                # leaked capture: a crashed stop cleared our state while
                # JAX kept tracing — stop the underlying trace so the
                # state machine and the profiler agree again
                try:
                    import jax
                    jax.profiler.stop_trace()
                except Exception:
                    pass
                if self.logger:
                    self.logger.warn(
                        "profiler force-stop: recovered a leaked capture")
                return {"ok": True, "recovered": True, "dir": None}
            path, self._active_dir = self._active_dir, None
            started, self._started_at = self._started_at, None
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception as exc:
                if force:
                    if self.logger:
                        self.logger.warn(
                            f"profiler force-stop swallowed: {exc!r}")
                    return {"ok": True, "recovered": True, "dir": path,
                            "error": f"{type(exc).__name__}: {exc}"}
                return {"ok": False, "dir": path,
                        "error": f"{type(exc).__name__}: {exc}"}
            if self.logger:
                self.logger.info(f"profiler capture stopped: {path}")
            return {"ok": True, "dir": path,
                    "duration_s": round(time.time() - started, 3)
                    if started else None}

    def status(self) -> dict:
        return {"running": self._active_dir is not None,
                "dir": self._active_dir,
                "auto_stops": self.auto_stops}
