"""Framework handlers for the serving endpoints: /chat and /embed.

The GoFr-style integration point: ``app.post("/chat",
make_chat_handler(engine, tokenizer))`` gives an OpenAI-ish completion
endpoint with SSE streaming; ``make_embed_handler`` serves sentence
embeddings off a BERT encoder.
"""

from __future__ import annotations

import json
import time
from typing import Any

from ..http.errors import (ErrorInvalidParam, ErrorMissingParam,
                           ErrorServiceUnavailable, ErrorTooManyRequests)
from ..http.response import Raw, Stream
from .engine import Engine, SamplingParams
from .scheduler import retry_after_header


def admission_error(req: Any) -> Exception:
    """Typed HTTP error for a refused submission. The scheduler stamps
    a :class:`~gofr_tpu.serving.scheduler.SchedReject` on policy
    refusals — rate limits surface as 429, queue-full/shed as 503,
    both carrying ``Retry-After`` and a machine-readable ``details``
    object (code, tenant, retry_after_s). Untyped failures (engine
    closed/stopped) keep the plain 503."""
    rej = getattr(req, "reject", None)
    if rej is None:
        return ErrorServiceUnavailable(req.error)
    details = {"code": rej.code, "tenant": rej.tenant,
               "retry_after_s": round(rej.retry_after_s, 3)}
    cls = (ErrorTooManyRequests if rej.code == "rate_limited"
           else ErrorServiceUnavailable)
    return cls(req.error, details=details,
               headers=retry_after_header(rej))


def make_chat_handler(engine: Engine, tokenizer: Any):
    """POST /chat: {"prompt": str, "max_tokens"?, "temperature"?,
    "top_p"?, "top_k"?, "stream"?: bool}"""

    async def chat_handler(ctx):
        body = ctx.bind() or {}
        prompt = body.get("prompt")
        if prompt is None and isinstance(body.get("messages"), list):
            prompt = "\n".join(str(m.get("content", ""))
                               for m in body["messages"])
        if not prompt or not isinstance(prompt, str):
            raise ErrorMissingParam("prompt")
        try:
            params = SamplingParams(
                temperature=float(body.get("temperature", 0.7)),
                top_p=float(body.get("top_p", 1.0)),
                top_k=int(body.get("top_k", 0)),
                max_new_tokens=int(body.get("max_tokens",
                                            body.get("max_new_tokens", 128))),
            )
        except (TypeError, ValueError) as exc:
            raise ErrorInvalidParam("temperature/top_p/top_k/max_tokens") \
                from exc
        if params.max_new_tokens < 1 or params.max_new_tokens > 4096:
            raise ErrorInvalidParam("max_tokens")

        prompt_tokens = tokenizer.encode(prompt)
        stream = bool(body.get("stream", False))

        # tenant attribution: the auth principal (set by the auth
        # middleware) resolves to a bounded accounting label that
        # rides the request into spans, metrics and the usage ledger
        resolver = getattr(ctx.container, "tenant_resolver", None)
        tenant = resolver.resolve(ctx.auth_info) if resolver else None

        # the tracer middleware's span is active on this task, so the
        # engine picks the parent from the contextvar; the raw header
        # is the fallback for apps running without the middleware
        req = engine.submit(prompt_tokens, params,
                            traceparent=ctx.header("traceparent") or None,
                            tenant=tenant)
        if req.error:
            # instant failure = admission refused, not a generation
            # bug; the scheduler's typed reject picks 429 vs 503 and
            # carries Retry-After
            raise admission_error(req)

        if stream:
            async def sse():
                gen = engine.stream_request(req)
                try:
                    async for token in gen:
                        text = tokenizer.decode([token])
                        yield ("data: "
                               + json.dumps({"token": token, "text": text})
                               + "\n\n")
                    if req.error:
                        # mid-generation failure (kv loss, shutdown):
                        # truncation must be visible — no [DONE]
                        yield ("data: "
                               + json.dumps({"error": req.error}) + "\n\n")
                    else:
                        yield "data: [DONE]\n\n"
                finally:
                    # deterministic: closing THIS generator (client
                    # gone) must close the engine stream too, which
                    # cancels the request instead of decoding to a
                    # dead socket
                    await gen.aclose()
            return Stream(sse())

        tokens: list[int] = []
        while True:
            token = await req.out_queue.get()
            if token is None:
                break
            tokens.append(token)
        if req.error:
            raise RuntimeError(f"generation failed: {req.error}")
        tpot_ms = None
        if (req.first_token_at is not None and req.finished_at is not None
                and len(tokens) > 1):
            tpot_ms = ((req.finished_at - req.first_token_at) * 1000.0
                       / (len(tokens) - 1))
        return {
            "text": tokenizer.decode(tokens),
            "tokens": tokens,
            "usage": {
                "prompt_tokens": len(prompt_tokens),
                "completion_tokens": len(tokens),
                "ttft_ms": round(req.ttft_ms, 2) if req.ttft_ms else None,
                "tpot_ms": round(tpot_ms, 3) if tpot_ms else None,
                "tenant": tenant,
            },
        }

    return chat_handler


def make_embed_handler(params: Any, config: Any, tokenizer: Any, *,
                       max_len: int = 512, buckets=(16, 32, 64, 128, 256, 512)):
    """POST /embed: {"input": str | [str]} -> {"embeddings": [[...]]}"""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..models.bert import bert_encode, mean_pool_embed

    @jax.jit
    def encode(tokens, mask):
        hidden, _ = bert_encode(params, tokens, config, attention_mask=mask)
        return mean_pool_embed(hidden, mask)

    def embed_handler(ctx):
        body = ctx.bind() or {}
        texts = body.get("input")
        if isinstance(texts, str):
            texts = [texts]
        if not texts or not isinstance(texts, list):
            raise ErrorMissingParam("input")
        start = time.perf_counter()
        token_lists = [tokenizer.encode(t)[:max_len] for t in texts]
        longest = max(len(t) for t in token_lists)
        bucket = next((b for b in buckets if longest <= b), buckets[-1])
        batch = np.zeros((len(texts), bucket), np.int32)
        mask = np.zeros((len(texts), bucket), np.int32)
        for i, toks in enumerate(token_lists):
            toks = toks[:bucket]
            batch[i, :len(toks)] = toks
            mask[i, :len(toks)] = 1
        emb = np.asarray(encode(jnp.asarray(batch), jnp.asarray(mask)))
        return Raw({
            "embeddings": [e.tolist() for e in emb.astype(float)],
            "dim": int(emb.shape[-1]),
            "latency_ms": round((time.perf_counter() - start) * 1000, 2),
        })

    return embed_handler
