"""Continuous-batching inference engine — the TPU serving hot loop.

The component BASELINE.json's north star adds on top of the GoFr
surface: requests from any transport (HTTP handler, gRPC stream,
pub/sub worker) are coalesced in front of the device.

Architecture (one device or one mesh):

- A dedicated **engine thread** owns all device calls, so the asyncio
  serving loop never blocks on the TPU. Handlers ``submit()`` requests
  and consume an ``asyncio.Queue`` of tokens bridged via
  ``loop.call_soon_threadsafe``.
- **Decode is one fixed-shape jitted step** over ``max_batch`` slots
  (inactive slots are masked), so XLA compiles exactly one decode
  graph. KV caches are donated — updated in place in HBM.
- **Prefill is bucketed** (prompt padded to power-of-two lengths) to
  bound recompiles; each bucket compiles once.
- Per-slot sampling params ride as arrays; greedy rows use argmax,
  stochastic rows use gumbel sampling, selected with ``jnp.where`` so
  one graph serves every mix.
- Scheduling: waiting prefills are admitted whenever a slot is free
  (prefill-priority keeps TTFT low; decode continues for everyone else
  next step).

This is the slot-based v1 cache (contiguous per-slot rows); the paged
allocator can replace it behind the same interface.
"""

from __future__ import annotations

import asyncio
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


@dataclass
class SamplingParams:
    temperature: float = 0.7
    top_p: float = 1.0
    top_k: int = 0          # 0 = disabled
    max_new_tokens: int = 128


@dataclass
class GenRequest:
    prompt_tokens: list[int]
    params: SamplingParams
    submitted_at: float = field(default_factory=time.time)
    first_token_at: float | None = None
    finished_at: float | None = None
    # engine-internal
    slot: int = -1
    generated: list[int] = field(default_factory=list)
    out_queue: Any = None          # asyncio.Queue[int | None]
    loop: Any = None               # the submitting event loop
    error: str | None = None

    def _emit(self, token: int | None) -> None:
        if self.out_queue is not None and self.loop is not None:
            self.loop.call_soon_threadsafe(self.out_queue.put_nowait, token)

    @property
    def ttft_ms(self) -> float | None:
        if self.first_token_at is None:
            return None
        return (self.first_token_at - self.submitted_at) * 1000.0


@dataclass
class EngineConfig:
    max_batch: int = 8          # decode slots
    max_seq: int = 1024         # per-slot kv capacity
    prefill_buckets: tuple = (32, 64, 128, 256, 512, 1024)
    eos_id: int = -1            # -1: never stop on eos
    #: decode steps fused into one device call (lax.scan). Each host
    #: round-trip then yields K tokens per slot instead of 1 — the
    #: per-token host/dispatch overhead divides by K. Tokens stream in
    #: bursts of K and admission happens between passes, so large K
    #: trades TTFT/streaming granularity for throughput.
    decode_steps_per_pass: int = 4


class Engine:
    """Continuous batching over a (prefill_fn, decode_fn) model pair.

    prefill_fn(params, tokens[1, S], kv_lengths[1]) -> (logits[1, S, V],
        (k [L,1,S,Hkv,hd], v)) — built from e.g. ``llama_prefill``.
    decode_fn(params, tokens[B], k_cache, v_cache, lengths[B]) ->
        (logits[B, V], k_cache, v_cache) — e.g. ``llama_decode_step``.
    """

    def __init__(self, params: Any, config: EngineConfig, *,
                 prefill_fn: Callable, decode_fn: Callable,
                 make_cache: Callable, metrics: Any = None,
                 logger: Any = None) -> None:
        self.params = params
        self.config = config
        self.metrics = metrics
        self.logger = logger
        self._prefill_raw = prefill_fn
        self._make_cache = make_cache

        cfg = config

        # decode + sampling fused into ONE graph returning just the
        # sampled token ids [B] — the per-step host transfer is 4B/slot
        # instead of the full [B, vocab] logits, and none of the
        # sampling math dispatches eagerly (each eager op is a host
        # round-trip, ruinous over a device tunnel)
        base_key = jax.random.key(int(time.time() * 1e3) % (2**31))
        # disjoint rng streams: prefill and decode fold into separate
        # subkeys so their per-step indices can never collide
        decode_key = jax.random.fold_in(base_key, 0)
        prefill_key = jax.random.fold_in(base_key, 1)

        K = max(1, int(cfg.decode_steps_per_pass))

        def _decode_sample(params, tokens, k_cache, v_cache, lengths,
                           step, temps, top_ps, top_ks):
            # K decode steps in one lax.scan: sampled tokens feed back
            # into the next step on-device; rng derives in-graph from
            # the step counter (no eager random.split per token)
            def one(carry, k):
                toks, kc, vc, lens = carry
                key = jax.random.fold_in(decode_key, step * K + k)
                logits, kc, vc = decode_fn(params, toks, kc, vc, lens)
                nxt = _sample_batch(logits, key, temps, top_ps, top_ks)
                return (nxt, kc, vc, lens + 1), nxt

            (_, k_cache, v_cache, _), toks = jax.lax.scan(
                one, (tokens, k_cache, v_cache, lengths), jnp.arange(K))
            return toks, k_cache, v_cache  # [K, B]
        self._decode = jax.jit(_decode_sample, donate_argnums=(2, 3))
        self._decode_k = K
        self._prefill_base_key = prefill_key
        self._prefill_cache: dict[int, Callable] = {}
        self._prefill_fn = prefill_fn

        # cache insert donates the caches: an in-place HBM write, not a copy
        def _insert(kc, vc, k, v, slot):
            kc = jax.lax.dynamic_update_slice(kc, k.astype(kc.dtype),
                                              (0, slot, 0, 0, 0))
            vc = jax.lax.dynamic_update_slice(vc, v.astype(vc.dtype),
                                              (0, slot, 0, 0, 0))
            return kc, vc
        self._insert = jax.jit(_insert, donate_argnums=(0, 1))

        self.k_cache, self.v_cache = make_cache(cfg.max_batch, cfg.max_seq)
        self.lengths = np.zeros(cfg.max_batch, np.int32)       # kv length per slot
        self.active: list[GenRequest | None] = [None] * cfg.max_batch
        # admission queue: C++ waitable batch queue when a toolchain
        # exists (gofr_tpu/native), queue.Queue-semantics fallback
        from ..native.batch_queue import new_request_queue
        self.waiting = new_request_queue()

        self._rng_step = 0
        self._running = False
        self._thread: threading.Thread | None = None
        self._step_count = 0
        self.total_generated = 0

    # ----------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="gofr-engine")
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        # terminal: refuse new submissions and fail anything stranded in
        # the queue so no submitter waits on a request nothing will run
        self.waiting.close()
        stranded = self.waiting.pop_batch(1 << 16, first_wait_s=0.0)
        for req in stranded or []:
            req.error = "engine stopped"
            req.finished_at = time.time()
            req._emit(None)

    def health_check(self) -> dict:
        return {
            "status": "UP" if self._running else "DOWN",
            "active_slots": sum(r is not None for r in self.active),
            "waiting": self.waiting.qsize(),
            "steps": self._step_count,
            "total_generated": self.total_generated,
        }

    def close(self) -> None:
        self.stop()

    # -------------------------------------------------------------- submit
    def submit(self, prompt_tokens: list[int],
               params: SamplingParams | None = None) -> GenRequest:
        """Called from the asyncio loop; returns a request whose
        ``out_queue`` yields token ids and then ``None``."""
        params = params or SamplingParams()
        # keep the tail of over-long prompts, reserving room to generate
        room = max(1, min(params.max_new_tokens, self.config.max_seq // 2))
        limit = max(1, self.config.max_seq - room - 1)
        if len(prompt_tokens) > limit:
            prompt_tokens = prompt_tokens[-limit:]
        req = GenRequest(prompt_tokens=list(prompt_tokens), params=params)
        try:
            req.loop = asyncio.get_running_loop()
            req.out_queue = asyncio.Queue()
        except RuntimeError:  # submitted from a plain thread (tests/bench)
            req.loop = None
            req.out_queue = None
        if not self.waiting.put(req):  # full/closed: fail loudly, never hang
            req.error = "engine not accepting requests"
            req.finished_at = time.time()
            req._emit(None)
        return req

    def submit_sync(self, prompt_tokens: list[int],
                    params: SamplingParams | None = None) -> GenRequest:
        """Blocking submit for non-async callers; returns when finished."""
        req = self.submit(prompt_tokens, params)
        while req.finished_at is None and req.error is None:
            time.sleep(0.002)
        return req

    async def generate_stream(self, prompt_tokens: list[int],
                              params: SamplingParams | None = None):
        """Async iterator of token ids."""
        req = self.submit(prompt_tokens, params)
        while True:
            token = await req.out_queue.get()
            if token is None:
                break
            yield token

    # ---------------------------------------------------------- scheduling
    def _bucket_for(self, n: int) -> int:
        for b in self.config.prefill_buckets:
            if n <= b:
                return b
        return self.config.prefill_buckets[-1]

    def _get_prefill(self, bucket: int) -> Callable:
        """Fused prefill + first-token sample per bucket: returns
        (token [1] int32, k, v) so the host pulls 4 bytes, not
        [1, S, vocab] logits."""
        fn = self._prefill_cache.get(bucket)
        if fn is None:
            prefill_fn = self._prefill_fn

            base_key = self._prefill_base_key

            def fused(params, tokens, kv_len, step, temp, top_p, top_k):
                key = jax.random.fold_in(base_key, step)
                logits, (k, v) = prefill_fn(params, tokens, kv_len)
                last = logits[0, kv_len[0] - 1]  # last prompt position
                tok = _sample_batch(last[None], key, temp, top_p, top_k)
                return tok, k, v
            fn = jax.jit(fused)
            self._prefill_cache[bucket] = fn
        return fn

    def _free_slot(self) -> int:
        for i, r in enumerate(self.active):
            if r is None:
                return i
        return -1

    def _admit(self, req: GenRequest) -> None:
        slot = self._free_slot()
        if slot < 0:  # raced; requeue for the next pass
            if not self.waiting.put(req):
                req.error = "engine not accepting requests"
                req.finished_at = time.time()
                req._emit(None)
            return
        try:
            self._prefill_into_slot(req, slot)
        except Exception as exc:
            req.error = str(exc)
            req.finished_at = time.time()
            req._emit(None)
            if self.logger:
                self.logger.error(f"prefill failed: {exc!r}")

    def _prefill_into_slot(self, req: GenRequest, slot: int) -> None:
        n = len(req.prompt_tokens)
        bucket = self._bucket_for(n)
        tokens = np.full((1, bucket), 0, np.int32)
        tokens[0, :n] = req.prompt_tokens
        kv_len = jnp.array([n], jnp.int32)
        prefill = self._get_prefill(bucket)
        self._rng_step += 1
        tok, k, v = prefill(
            self.params, jnp.asarray(tokens), kv_len,
            np.int32(self._rng_step),
            jnp.asarray([req.params.temperature], jnp.float32),
            jnp.asarray([req.params.top_p], jnp.float32),
            jnp.asarray([req.params.top_k], jnp.int32))
        # write prompt kv into the slot (donated, in-place)
        self.k_cache, self.v_cache = self._insert(
            self.k_cache, self.v_cache, k, v, slot)
        first = int(tok[0])
        req.slot = slot
        req.first_token_at = time.time()
        req.generated.append(first)
        req._emit(first)
        self.total_generated += 1
        self.lengths[slot] = n
        self.active[slot] = req
        if self.metrics is not None:
            self.metrics.record_histogram(
                "app_chat_ttft_seconds",
                req.first_token_at - req.submitted_at)
        if self._finished(req, first):
            self._retire(slot)

    def _finished(self, req: GenRequest, token: int) -> bool:
        if token == self.config.eos_id:
            return True
        return len(req.generated) >= req.params.max_new_tokens

    def _retire(self, slot: int) -> None:
        req = self.active[slot]
        if req is None:
            return
        req.finished_at = time.time()
        req._emit(None)
        self.active[slot] = None
        self.lengths[slot] = 0

    # -------------------------------------------------------------- decode
    def _decode_step(self) -> None:
        cfg = self.config
        K = self._decode_k
        # a pass appends up to K rows per slot (last write at
        # lengths+K-1 <= max_seq-1); slots without that headroom retire
        # now, truncating at most K-1 tokens at the cache ceiling
        for i, req in enumerate(self.active):
            if req is not None and self.lengths[i] + K > cfg.max_seq:
                self._retire(i)

        tokens = np.zeros(cfg.max_batch, np.int32)
        temps = np.zeros(cfg.max_batch, np.float32)
        top_ps = np.ones(cfg.max_batch, np.float32)
        top_ks = np.zeros(cfg.max_batch, np.int32)
        active_mask = np.zeros(cfg.max_batch, bool)
        for i, req in enumerate(self.active):
            if req is None:
                continue
            active_mask[i] = True
            tokens[i] = req.generated[-1]
            temps[i] = req.params.temperature
            top_ps[i] = req.params.top_p
            top_ks[i] = req.params.top_k
        if not active_mask.any():
            return

        lengths = jnp.asarray(self.lengths)
        self._rng_step += 1
        start = time.perf_counter()
        step_tokens, self.k_cache, self.v_cache = self._decode(
            self.params, jnp.asarray(tokens), self.k_cache, self.v_cache,
            lengths, np.int32(self._rng_step), jnp.asarray(temps),
            jnp.asarray(top_ps), jnp.asarray(top_ks))
        step_np = np.asarray(step_tokens)  # [K, B]
        if self.metrics is not None:
            self.metrics.record_histogram(
                "app_tpu_execute_seconds", time.perf_counter() - start)

        self._step_count += 1
        for i, req in enumerate(self.active):
            if req is None:
                continue
            # the device appended K rows for this slot regardless of
            # where the request stops; overshoot rows are dead weight
            # masked out by kv_lengths after the next prefill
            self.lengths[i] += K
            done = False
            for k in range(K):
                token = int(step_np[k, i])
                req.generated.append(token)
                req._emit(token)
                self.total_generated += 1
                if self._finished(req, token):
                    done = True
                    break
            if done:
                self._retire(i)

    # ---------------------------------------------------------------- loop
    def _loop(self) -> None:
        while self._running:
            free = sum(1 for r in self.active if r is None)
            busy = free < self.config.max_batch
            if free > 0:
                # one batched pop per pass (TTFT priority): blocks while
                # fully idle — in the native queue the engine thread
                # sleeps in C with the GIL released — and is a zero-wait
                # drain between decode steps while busy
                batch = self.waiting.pop_batch(
                    free, first_wait_s=0.0 if busy else 0.05,
                    drain_wait_s=0.0)
                for req in batch or []:
                    self._admit(req)
            if any(r is not None for r in self.active):
                self._decode_step()


def _sample_batch(logits: jnp.ndarray, key: jax.Array,
                  temperatures: jnp.ndarray, top_ps: jnp.ndarray,
                  top_ks: jnp.ndarray | None = None) -> jnp.ndarray:
    """Per-row sampling in one graph: greedy rows (temp==0) via argmax,
    stochastic rows via top-k then top-p filtered gumbel draw
    (``top_ks`` row value 0 disables top-k for that row)."""
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    safe_t = jnp.maximum(temperatures, 1e-6)[:, None]
    scaled = logits / safe_t

    sorted_logits = jnp.sort(scaled, axis=-1)[..., ::-1]
    if top_ks is not None:
        vocab = scaled.shape[-1]
        kth = jnp.clip(top_ks - 1, 0, vocab - 1).astype(jnp.int32)
        k_threshold = jnp.take_along_axis(sorted_logits, kth[:, None],
                                          axis=-1)
        scaled = jnp.where((top_ks[:, None] > 0)
                           & (scaled < k_threshold), NEG_INF, scaled)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = jnp.roll(cum, 1, axis=-1) < top_ps[:, None]
    keep_sorted = keep_sorted.at[..., 0].set(True)
    kept = jnp.where(keep_sorted, sorted_logits, jnp.inf)
    threshold = jnp.min(kept, axis=-1, keepdims=True)
    filtered = jnp.where(scaled < threshold, NEG_INF, scaled)

    gumbel = -jnp.log(-jnp.log(
        jax.random.uniform(key, scaled.shape, minval=1e-20, maxval=1.0) + 1e-20))
    sampled = jnp.argmax(filtered + gumbel, axis=-1).astype(jnp.int32)
    return jnp.where(temperatures <= 0.0, greedy, sampled)
