"""Continuous-batching inference engine — the TPU serving hot loop.

The component BASELINE.json's north star adds on top of the GoFr
surface: requests from any transport (HTTP handler, gRPC stream,
pub/sub worker) are coalesced in front of the device.

Architecture (one device or one mesh):

- A dedicated **engine thread** owns all device calls, so the asyncio
  serving loop never blocks on the TPU. Handlers ``submit()`` requests
  and consume an ``asyncio.Queue`` of tokens bridged via
  ``loop.call_soon_threadsafe``.
- **Decode is one fixed-shape jitted step** over ``max_batch`` slots
  (inactive slots are masked), so XLA compiles exactly one decode
  graph. KV caches are donated — updated in place in HBM.
- **Prefill is bucketed** (prompt padded to power-of-two lengths) to
  bound recompiles; each bucket compiles once.
- Per-slot sampling params ride as arrays; greedy rows use argmax,
  stochastic rows use gumbel sampling, selected with ``jnp.where`` so
  one graph serves every mix.
- Scheduling: waiting prefills are admitted whenever a slot is free
  (prefill-priority keeps TTFT low; decode continues for everyone else
  next step).

Two KV layouts share the loop (``EngineConfig.kv_layout``): "slot"
keeps contiguous per-slot rows; "paged" adds block-table indirection
over a page pool (``ops/paged_kv.py``) with allocation on admission,
frees on retire, and vLLM-style preemption-by-recompute when the pool
runs dry — KV capacity decoupled from ``max_batch x max_seq``.

Scheduler state is **device-resident**: per-slot lengths, sampling
params, page tables and the active mask live as persistent device
arrays, re-uploaded only when an admission/retirement/preemption
event changes them (``_sync_decode_state``). The decode graph advances
lengths and the sampling-rng counter on device, so a steady-state
decode dispatch performs ZERO host->device transfers — re-uploading
unchanged scheduler state every pass is now considered a bug (it was
the measured bottleneck of the overhead-bound BENCH_r05 decode).
"""

from __future__ import annotations

import asyncio
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.annotations import hot_path, hot_path_boundary
from .faults import NO_FAULTS, resolve_plan
from .spec import (MAX_TREE_NODES, DraftTree, NgramIndex, SpecController,
                   build_draft_tree)

NEG_INF = -1e30


@dataclass
class SamplingParams:
    temperature: float = 0.7
    top_p: float = 1.0
    #: 0 disables the *explicit* top-k filter; stochastic sampling is
    #: always bounded to the ``TOPK_BOUND`` (64) most likely tokens —
    #: the engine's sampling graph never materialises the full-vocab
    #: distribution (see ``_sample_batch``).
    top_k: int = 0
    max_new_tokens: int = 128


@dataclass
class GenRequest:
    prompt_tokens: list[int]
    params: SamplingParams
    submitted_at: float = field(default_factory=time.time)
    first_token_at: float | None = None
    finished_at: float | None = None
    # engine-internal
    slot: int = -1
    generated: list[int] = field(default_factory=list)
    out_queue: Any = None          # asyncio.Queue[int | None]
    loop: Any = None               # the submitting event loop
    error: str | None = None
    cancelled: bool = False        # consumer gone: retire, don't decode
    admit_order: int = -1          # paged preemption picks the newest;
                                   # assigned once at first admission and
                                   # kept across preemption-requeues so a
                                   # re-admitted old request stays old
    pending_prefill: bool = False  # mid chunked-prefill OR awaiting a
                                   # dispatched batch prefill: holds a
                                   # slot but must not decode yet
    prefill_offset: int = 0        # next chunk's start position
    prefill_epoch: int = 0         # bumps per batch-prefill dispatch so
                                   # a stale in-flight result can never
                                   # attach to a requeued request
    # -- observability (host-side only; see serving/observability.py)
    trace: Any = None              # (trace_id, parent_span_id) when the
                                   # submitter's trace is sampled — the
                                   # engine.* spans assemble at retire
    admitted_at: float | None = None  # first slot assignment (queue end)
    events: list = field(default_factory=list)  # (name, t0, t1, attrs)
    _obs_done: bool = False        # finalize-once guard (retire + fail)
    tenant: str | None = None      # bounded tenant label from the auth
                                   # principal (TenantResolver); stamped
                                   # into spans/usage, accounted by the
                                   # UsageLedger at retire
    device_s: float = 0.0          # this request's share of each pass's
                                   # busy span (busy/occupancy per pass,
                                   # accumulated at collect — host float
                                   # adds on an existing loop)
    waste_recompute_s: float = 0.0  # slice of device_s re-prefilling KV
                                    # this request already computed once
                                    # (preemption-by-recompute) — the
                                    # per-tenant "who pays for
                                    # preemption" column
    waste_spec_s: float = 0.0       # slice of device_s spent on this
                                    # request's REJECTED draft tokens
    spec_index: Any = None          # per-request NgramIndex (lazy; fed
                                    # incrementally by _draft_proposals,
                                    # rebuilt when the token stream is
                                    # rewritten by preempt/recover)
    lane: str = "interactive"      # scheduler lane (interactive |
                                   # background); explicit submit() lane
                                   # wins over the config's tenant->lane
                                   # mapping
    reject: Any = None             # scheduler.SchedReject stamped when
                                   # admission refused the request —
                                   # handlers turn it into 429/503 with
                                   # Retry-After instead of a blanket 503
    recovered: bool = False        # salvaged across an engine restart
                                   # before its first token: the replay
                                   # prefill recomputes KV it already
                                   # paid for once, priced under the
                                   # preempt_recompute goodput cause
    digest: str | None = None      # output fingerprint, stamped once at
                                   # the retire boundary by the
                                   # integrity plane's digest fold
                                   # (serving/integrity.py)
    probe: str = ""                # golden-canary id when this request
                                   # IS an integrity probe — its device
                                   # time re-prices to integrity_probe
                                   # waste and its digest is judged
                                   # against probe_expected at retire
    probe_expected: str = ""       # the sealed golden digest a probe
                                   # must reproduce bit-for-bit

    def _emit(self, token: int | None) -> None:
        if self.out_queue is not None and self.loop is not None:
            try:
                self.loop.call_soon_threadsafe(self.out_queue.put_nowait,
                                               token)
            except RuntimeError:
                # the submitter's event loop died (client disconnect,
                # worker reload): stop emitting to it — one dead client
                # must never take down the engine hot loop
                self.out_queue = None
                self.loop = None

    @property
    def ttft_ms(self) -> float | None:
        if self.first_token_at is None:
            return None
        return (self.first_token_at - self.submitted_at) * 1000.0


@dataclass
class RestartPolicy:
    """Crash-recovery budget for the in-thread engine supervisor: on a
    hot-loop exception the loop salvages what it safely can (see
    ``Engine._recover``), rebuilds runtime state on the resident
    weights and compiled graphs, sleeps a deterministic exponential
    backoff, and resumes — up to ``max_restarts`` times, after which
    the crash is terminal (health DOWN, the old ``_crash`` semantics).
    """
    max_restarts: int = 3       # lifetime restart budget; 0 = disabled
    backoff_s: float = 0.05     # sleep before restart #1
    backoff_mult: float = 2.0   # growth per successive restart
    max_backoff_s: float = 5.0  # backoff ceiling

    def backoff_for(self, attempt: int) -> float:
        """Deterministic backoff before restart ``attempt`` (1-based)."""
        return min(self.max_backoff_s,
                   self.backoff_s * self.backoff_mult ** max(0, attempt - 1))


@dataclass
class EngineConfig:
    max_batch: int = 8          # decode slots
    max_seq: int = 1024         # per-slot kv capacity
    prefill_buckets: tuple = (32, 64, 128, 256, 512, 1024)
    eos_id: int = -1            # -1: never stop on eos
    #: decode steps fused into one device call (lax.scan). Each host
    #: round-trip then yields K tokens per slot instead of 1 — the
    #: per-token host/dispatch overhead divides by K. Tokens stream in
    #: bursts of K and admission happens between passes, so large K
    #: trades TTFT/streaming granularity for throughput.
    decode_steps_per_pass: int = 8
    #: fused multi-pass decode: how many K-step passes the on-device
    #: decode loop runs per dispatch (M). One dispatch then yields
    #: K x M tokens per slot with device-side token feedback and
    #: length advancement — the Python dispatch/collect overhead per
    #: token divides by another factor of M. Admission, retirement and
    #: draft checks still happen only between dispatches, so large M
    #: trades scheduling granularity (and wasted steps past a
    #: finishing request's budget) for throughput. 1 = the classic
    #: single-pass dispatch.
    decode_passes_per_dispatch: int = 1
    #: persistent XLA compilation cache directory. "auto" (default)
    #: resolves the shared config path (``GOFR_COMPILE_CACHE_DIR`` env
    #: key, else ``~/.cache/gofr_tpu/xla_cache``) so warmup compiles
    #: amortize across processes — bench children, TPU jobs, restarts.
    #: None or "off" disables. Applied at engine construction via
    #: :func:`gofr_tpu.config.env.enable_compile_cache`.
    compile_cache_dir: str | None = "auto"
    #: windowed decode attention: extra decode-graph variants that
    #: touch only the first ``window`` cache rows — attention reads
    #: for the slot layout, gather/scatter width for the paged VIEW
    #: path (the mesh-sharded paged path; the single-device ragged
    #: kernel is already length-bounded and ignores this). Each pass
    #: picks the smallest listed window covering every live length +
    #: K; none covering -> the full-max_seq graph. HBM traffic becomes
    #: O(longest live row), not O(max_seq) — decisive when max_seq >>
    #: typical lengths. Each window is one extra compile (warmed in
    #: warmup()). () = off.
    decode_windows: tuple = ()
    #: waiting requests prefilled per device call. The prefill graph is
    #: a fixed [P, bucket] shape (short groups ride with masked dummy
    #: rows, which cost nothing extra — the shapes are static either
    #: way), so a burst of arrivals costs ceil(n/P) device round-trips
    #: instead of n. Keep modest: P multiplies per-call prefill FLOPs.
    prefill_batch: int = 8
    #: sampling RNG seed; None draws entropy from ``os.urandom`` so two
    #: engines started in the same millisecond never share streams. Set
    #: for reproducible generation in tests/evals.
    seed: int | None = None
    #: admission bound: waiting requests beyond this fail immediately
    #: with "engine overloaded" (surfaced as a 503 by the handlers)
    #: instead of growing an unbounded queue where every TTFT degrades
    #: together. 0 = unbounded. Already-admitted work that bounces
    #: back (preemption, slot races) bypasses the bound.
    max_waiting: int = 0
    #: chunked-prefill pacing: how many bucket-width chunks of a long
    #: prompt run per engine pass. Decode for every other slot
    #: interleaves between passes, so one giant prompt cannot
    #: head-of-line block the whole batch.
    prefill_chunks_per_pass: int = 2
    #: stall detection: with work in flight, a loop that has not
    #: completed a pass for this long (wedged device runtime, hung
    #: tunnel) flips health to DEGRADED so orchestrators can act —
    #: exceptions are contained separately (health DOWN). 0 disables.
    stall_threshold_s: float = 120.0
    #: stall ESCALATION cadence: a watchdog thread polls
    #: ``health_check()`` every this many seconds and, when the stall
    #: flag flips, dumps the flight recorder, emits an ``engine.stall``
    #: span + ``app_engine_stalls`` counter, and leaves health DEGRADED
    #: for the next control-plane heartbeat so the leader can evict
    #: instead of waiting for heartbeat silence. Pure host-side
    #: polling off the hot loop. 0 disables the watchdog.
    watchdog_interval_s: float = 5.0
    #: "slot" = contiguous per-slot rows (max_batch x max_seq, simplest
    #: and fastest per step); "paged" = block-table indirection over a
    #: page pool (ops/paged_kv.py) — capacity decoupled from
    #: max_batch x max_seq, pages allocated on admission and freed on
    #: retire, preemption-by-recompute when the pool runs dry.
    kv_layout: str = "slot"
    #: rows per KV page (paged layout only)
    page_size: int = 64
    #: pool size in pages; None sizes the pool to the full contiguous
    #: capacity (max_batch x ceil(max_seq/page_size)). Smaller values
    #: overcommit: more concurrent short requests in the same HBM.
    kv_pages: int | None = None
    #: KV page storage dtype (paged layout only). "bf16" (default)
    #: stores pages in the model dtype — bit-identical to the classic
    #: pool. "int8" stores narrow codes plus one f32 scale per row
    #: (ops/paged_kv.py quantized pool): pages quantize on write
    #: inside the jitted scatters and the ragged kernels dequantize
    #: in-register after each per-page DMA, so per-row HBM cost falls
    #: from 2·hd to hd+4 bytes — at the same byte budget the pool
    #: holds ~2x the pages (1.88x at hd=64, 1.94x at hd=128).
    kv_dtype: str = "bf16"
    #: explicit KV pool HBM budget in bytes (paged layout only; K and
    #: V together). None derives the budget from ``kv_pages`` (or the
    #: full contiguous capacity) at the NATIVE page cost, so switching
    #: ``kv_dtype`` to int8 under the same budget grows the page count
    #: instead of shrinking the footprint — capacity is the point.
    kv_pool_bytes: int | None = None
    #: paged layout only: retain retired requests' page-aligned prompt
    #: prefixes and share them with later requests bearing the same
    #: prefix (the common system prompt) — the suffix prefills through
    #: the chunk-with-history path, skipping the shared compute
    #: entirely. Shared pages are read-only by construction (decode
    #: and suffix writes land past the aligned prefix) and refcounted;
    #: cache entries evict LRU under pool pressure.
    prefix_cache: bool = True
    #: cap on pages pinned by the prefix cache; None = a quarter of
    #: the pool.
    prefix_cache_pages: int | None = None
    #: prefix-cache digest published to the fleet: the newest N cache
    #: keys are hashed (serving/router.py prefix_hash) at the throttled
    #: gauge boundary and attached to heartbeat summaries so the
    #: leader's router can score hosts by longest resident prefix.
    #: 0 disables the digest (heartbeats carry no prefix_digest key).
    prefix_digest_hashes: int = 64
    #: speculative decoding (opt-in): draft tokens by prompt-lookup
    #: (an n-gram of the recent context matched earlier in
    #: prompt+generated proposes its continuation) and verify them in
    #: ONE parallel pass — accepted drafts + one bonus token land per
    #: pass instead of one token. Greedy outputs are identical to
    #: vanilla decode; non-greedy slots never accept drafts (their
    #: bonus token still samples with their own params).
    speculative: bool = False
    #: max draft tokens verified per pass
    spec_draft: int = 4
    #: n-gram width the prompt-lookup draft matches on
    spec_ngram: int = 3
    #: candidate continuations drafted per pass: the n-gram index
    #: proposes up to this many distinct continuations, trie-merged
    #: into ONE draft tree and verified together under a packed
    #: ancestor bitmask (1 + spec_draft * spec_branches <= 32 nodes).
    spec_branches: int = 2
    #: goodput-driven draft controller: per-slot accept-rate EWMA
    #: priced against fitted decode sec/token and verify row cost —
    #: drafting shrinks/stops per slot when expected accepted tokens
    #: stop paying for the marginal verify rows. False = the static
    #: always-full-depth policy.
    spec_adaptive: bool = True
    #: accept-rate EWMA floor under which a slot's drafting is
    #: disabled (re-probed every spec_probe_interval passes)
    spec_accept_floor: float = 0.1
    #: passes between single-node probes of a disabled slot
    spec_probe_interval: int = 32
    #: paged layout decode path: "auto" = the ragged paged-attention
    #: kernel on TPU (pages read in place, no per-pass view
    #: materialisation) and the gather/scatter view path elsewhere;
    #: "kernel" / "interpret" / "xla" force the native path with that
    #: paged-attention implementation; "view" forces gather/scatter.
    #: Takes effect only when the model family supplies a
    #: ``paged_decode_fn`` (llama does).
    paged_attention: str = "auto"
    #: decode-pipeline depth: dispatched passes left uncollected after
    #: each iteration. 1 overlaps the host round-trip (token download,
    #: stream emission, admissions) with device compute — but tokens
    #: arrive one pass late, each retirement wastes the pass its slot
    #: rides out, and freshly admitted requests see their first token
    #: behind a decode pass. None = adaptive: depth 1 only while at
    #: least ``pipeline_min_slots`` slots are actively decoding (the
    #: saturated regime where overlap pays for the waste); depth 0
    #: otherwise, where the waste dominates (the r4 tiny-config CPU
    #: bench ran ~9x slower always-pipelined: 381.6 -> 41.6 req/s).
    pipeline_depth: int | None = None
    #: adaptive-pipelining threshold (``pipeline_depth=None`` only):
    #: minimum actively-decoding slots before a pass is left in flight.
    pipeline_min_slots: int = 8
    #: flight recorder ring size: per-pass records (kind, occupancy,
    #: queue depth, tokens, dispatch/collect spans, h2d count,
    #: preemptions) kept in a fixed ring, served at ``/debug/engine``,
    #: summarized by ``health_check()`` and dumped on a loop crash.
    #: Recording is append-only host work — zero device perturbation.
    #: 0 disables.
    flight_recorder_size: int = 256
    #: retired-request event logs kept alongside the pass ring
    flight_recorder_requests: int = 32
    #: workload capture: arm the WorkloadRecorder at construction so
    #: every retired request lands in the capture ring (arrival time,
    #: prompt ids, gen params, seed, tenant, outcome) — the replayable
    #: workload file behind ``GET /debug/workload``. Off by default;
    #: ``POST /debug/workload/start`` arms it at runtime regardless.
    #: Recording is retire-time host work — zero hot-path perturbation
    #: (transfer-guard + greedy bit-identity hold with capture ON).
    workload_capture: bool = False
    #: capture ring bound: retired-request records kept (oldest drop,
    #: counted). 0 disables the recorder entirely.
    workload_capture_requests: int = 4096
    #: redact captured workloads: prompt/completion token ids are
    #: replaced by salted hashes (lengths kept) — shippable off-box,
    #: not bit-identity-replayable (serving/observability.py)
    capture_redact: bool = False
    #: goodput accounting + memory watermarks: classify every pass's
    #: busy device time into useful vs. waste causes (padding,
    #: preempt_recompute, spec_rejected, bubble) at collect/retire,
    #: with useful + sum(waste) == busy conserved, and track KV/prefix/
    #: host-RSS high-water marks. Host float arithmetic on existing
    #: collect paths — zero hot-path perturbation (transfer-guard +
    #: greedy bit-identity hold with it ON). Surfaced as
    #: app_engine_goodput_ratio / app_engine_waste_seconds{cause} /
    #: app_engine_*_watermark and GET /debug/efficiency.
    goodput: bool = True
    #: recompile sentinel: after warmup() seals the expected shape set,
    #: a dispatch whose (kind, shape) signature warmup never compiled
    #: bumps app_engine_recompiles and WARNs once with the offending
    #: signature — a shape-induced recompile storm names itself before
    #: p99 does. O(1) host set lookups; engines that never warm up
    #: never seal, so cold compiles stay silent.
    recompile_sentinel: bool = True
    #: pass-cost observatory (serving/costmodel.py): per-dispatch-
    #: signature EWMA + variance of pass device time and per-row/
    #: per-token cost, fed host-side at the existing collect
    #: boundaries with the same durations the goodput ledger bills —
    #: zero hot-path perturbation (transfer-guard + greedy
    #: bit-identity hold with it ON). Surfaced at GET /debug/costs,
    #: in /debug/efficiency, on heartbeat summaries (fleet
    #: federation) and in workload headers (replay divergence).
    cost_model: bool = True
    #: EWMA weight for the per-signature cost mean/variance
    cost_alpha: float = 0.2
    #: serving-path passes per signature before its drift baseline
    #: seals (warmup never feeds the model — its timings are
    #: compile-laden)
    cost_baseline_passes: int = 32
    #: drift sentinel thresholds: an episode opens when a signature's
    #: EWMA exceeds BOTH baseline * cost_drift_ratio and baseline +
    #: cost_drift_sigma * baseline_std (ratio guards near-zero-std
    #: baselines, sigma guards noisy ones); fires one obs.cost_drift
    #: event + app_engine_cost_drift{kind} + one incident bundle per
    #: episode
    cost_drift_ratio: float = 2.0
    cost_drift_sigma: float = 6.0
    #: anomaly-triggered profiling (serving/costmodel.AutoProfiler):
    #: cost drift, SLO fast-burn or a goodput-floor breach arms a
    #: single-flight ProfilerCapture that auto-stops after
    #: autoprof_passes collected passes or autoprof_max_capture_s;
    #: arms are debounced and GOFR_AUTOPROF=0 is the kill-switch.
    #: The artifact path + cost table attach to the incident bundle.
    autoprof: bool = True
    autoprof_passes: int = 64
    autoprof_max_capture_s: float = 30.0
    autoprof_debounce_s: float = 300.0
    #: goodput-ratio floor that arms the autoprofiler (checked at the
    #: throttled gauge cadence once busy_s > 1); 0 disables the floor
    autoprof_goodput_floor: float = 0.0
    autoprof_dir: str = "/tmp/gofr_tpu_profiles"
    #: output-integrity observatory (serving/integrity.py): fold every
    #: retired request into a blake2b fingerprint at the retire
    #: boundary — stamped into GenRequest/flight recorder/workload
    #: records and judged by golden canary probes + fleet divergence
    #: voting. Zero hot-path perturbation: greedy outputs stay
    #: bit-identical with the plane ON.
    integrity: bool = True
    #: golden canary corpus (gofr-golden JSONL sealed from the replay
    #: corpus by GoldenSet.seal) — None disables probing; the
    #: fingerprint fold alone needs no corpus
    integrity_golden_path: str | None = None
    #: cap on golden entries loaded/probed (the corpus is meant to be
    #: tiny — a handful of short greedy prompts)
    integrity_golden_max: int = 8
    #: launch one golden probe on the scheduler's background lane
    #: every N collected passes (pass-count cadence, never wall
    #: clock); 0 disables probing
    integrity_probe_passes: int = 0
    #: consecutive clean probes that close a mismatch episode so a
    #: later mismatch alarms again (hysteresis, mirroring the
    #: cost-drift sentinel)
    integrity_rearm_probes: int = 2
    #: admission/scheduling/shedding policy (serving/scheduler.py):
    #: weighted fair-share dequeue over per-tenant sub-queues,
    #: interactive/background lanes with starvation preemption,
    #: token-bucket rate limits, burn-rate-driven shedding. None =
    #: default SchedulerConfig (fair-share ON — single-tenant traffic
    #: is strict FIFO, bit-identical to the old queue).
    scheduler: Any = None
    #: deterministic fault injection (serving/faults.py): a FaultPlan,
    #: a plan string ("pass_raise:at=3;..."), or None = read the
    #: ``GOFR_FAULTS`` env (unset -> the NO_FAULTS no-op singleton).
    #: Sites are compiled into the hot loop behind an identity
    #: comparison against NO_FAULTS, so the disabled default costs
    #: nothing and transfer-guard/bit-identity invariants hold.
    faults: Any = None
    #: the fleet flight data recorder (serving/events.py): an
    #: EventLedgerConfig, an EventLedger, True/False, or None = default
    #: ledger unless the ``GOFR_EVENTS`` env disables it. Emission only
    #: happens at already-declared @hot_path_boundary sites, so the
    #: zero-hot-path invariant holds with the ledger ON; False wires
    #: the NO_EVENTS no-op singleton everywhere.
    events: Any = None
    #: crash recovery: a RestartPolicy arms the in-thread supervisor —
    #: a hot-loop exception salvages pre-first-token requests into the
    #: recovery buffer, fails mid-stream ones with a typed retryable
    #: error, rebuilds runtime state on the resident weights/compile
    #: cache and resumes after a deterministic backoff. None (default)
    #: keeps the historical fail-fast semantics: any loop exception is
    #: terminal (health DOWN).
    restart_policy: Any = None


class Engine:
    """Continuous batching over a (prefill_fn, decode_fn) model pair.

    prefill_fn(params, tokens[P, S], kv_lengths[P]) -> (logits,
        (k [L,P,S,Hkv,hd], v)) where logits is [P, V] (last-position,
        e.g. ``llama_prefill_last``) or [P, S, V] (full; the engine
        gathers each row's last prompt position).
    decode_fn(params, tokens[B], k_cache, v_cache, lengths[B]) ->
        (logits[B, V], k_cache, v_cache) — e.g. ``llama_decode_step``.
    """

    def __init__(self, params: Any, config: EngineConfig, *,
                 prefill_fn: Callable, decode_fn: Callable,
                 make_cache: Callable, prefill_chunk_fn: Callable
                 | None = None, spec_verify_fn: Callable | None = None,
                 paged_decode_fn: Callable | None = None,
                 paged_chunk_fn: Callable | None = None,
                 paged_verify_fn: Callable | None = None,
                 metrics: Any = None,
                 logger: Any = None, tracer: Any = None) -> None:
        self.params = params
        self.config = config
        self.metrics = metrics
        self.logger = logger
        #: tracer for engine.* request spans (assembled at retire from
        #: host timestamps); None = no spans. ``app.serve_model`` wires
        #: the container's tracer here.
        self.tracer = tracer
        from .observability import (FlightRecorder, GoodputMeter,
                                    RecompileSentinel, UsageLedger,
                                    WatermarkTracker, WorkloadRecorder)
        self.recorder = FlightRecorder(config.flight_recorder_size,
                                       config.flight_recorder_requests)
        #: device-time waste attribution (useful vs padding/
        #: preempt_recompute/spec_rejected/bubble, conserved against
        #: busy time); fed at collect/retire on the engine thread
        self.goodput = GoodputMeter(config.goodput)
        #: KV/prefix/host-RSS high-water marks (throttled gauge cadence)
        self.watermarks = WatermarkTracker(config.goodput)
        #: post-warmup recompile detection by dispatch shape signature
        self.sentinel = RecompileSentinel(config.recompile_sentinel)
        #: pass-cost observatory: per-signature EWMA/variance cost
        #: model + drift sentinel, fed at the collect boundaries with
        #: the same durations the goodput ledger bills
        from .costmodel import AutoProfiler, CostModel
        self.costs = CostModel(config.cost_model,
                               alpha=config.cost_alpha,
                               baseline_passes=config.cost_baseline_passes,
                               drift_ratio=config.cost_drift_ratio,
                               drift_sigma=config.cost_drift_sigma)
        if self.costs.enabled:
            # heartbeat summaries carry the cost table: the leader's
            # straggler math compares hosts on the SAME signature
            self.recorder.cost_source = self.costs.table
        #: anomaly-triggered profiling: drift / fast-burn / goodput
        #: floor arm a bounded single-flight ProfilerCapture
        _capture = None
        if config.autoprof:
            from .observability import ProfilerCapture
            _capture = ProfilerCapture(base_dir=config.autoprof_dir,
                                       logger=logger)
        self.autoprof = AutoProfiler(
            _capture, enabled=config.autoprof,
            passes=config.autoprof_passes,
            max_capture_s=config.autoprof_max_capture_s,
            debounce_s=config.autoprof_debounce_s, logger=logger)
        #: output-integrity observatory: digest folds at the retire
        #: boundary, golden canary probes on the background lane at a
        #: pass-count cadence, heartbeat digest block for the leader's
        #: divergence vote (serving/integrity.py)
        from .integrity import GoldenSet, IntegrityPlane
        _golden = None
        if config.integrity and config.integrity_golden_path:
            # a missing/corrupt corpus must fail at construction, not
            # silently disable probing mid-incident
            _golden = GoldenSet.load(config.integrity_golden_path,
                                     limit=config.integrity_golden_max)
        self.integrity = IntegrityPlane(
            config.integrity, golden=_golden,
            probe_passes=config.integrity_probe_passes,
            rearm_probes=config.integrity_rearm_probes)
        if self.integrity.enabled:
            # heartbeat summaries carry the probe digests: the
            # leader's divergence vote compares hosts on the SAME
            # golden prompt
            self.recorder.integrity_source = self.integrity.summary
        if self.goodput.enabled:
            # heartbeats and workload headers carry the waste digest
            self.recorder.goodput_source = self.goodput.summary
        #: prefix-cache digest for the fleet router: assembled at the
        #: throttled gauge boundary (dirty-flagged by cache mutation
        #: sites), read by the heartbeat thread via an atomic reference
        self._prefix_digest: dict | None = None
        self._prefix_digest_dirty = True
        if config.prefix_digest_hashes > 0:
            self.recorder.prefix_digest_source = self.prefix_digest
        #: workload capture ring (armed lazily — see EngineConfig.
        #: workload_capture); engine_seed is stamped below once the
        #: sampling seed resolves
        self.workload = WorkloadRecorder(config.workload_capture_requests,
                                         redact=config.capture_redact)
        if self.goodput.enabled:
            self.workload.goodput_source = self.goodput.summary
        if self.costs.enabled:
            # captured workloads carry the recording side's cost table
            # (additive header field) so replay can report per-
            # signature divergence next to efficiency_divergence
            self.workload.cost_source = self.costs.table
        #: per-tenant usage metering, fed at retire (_finalize_obs);
        #: always present (host dicts only) — attach_metrics points it
        #: at the metrics manager so app_tenant_* series populate
        self.usage_ledger = UsageLedger()
        #: SLO burn-rate tracker (serving/observability.SLOTracker);
        #: wired by app.serve_model (or set directly) — None = off
        self.slo = None
        #: MFU basis, derived once at compile time in warmup() from the
        #: decode graph's cost_analysis — None until then (gauge stays 0)
        self._flops_per_token: float | None = None
        self._peak_flops: float | None = None
        self._gauge_wall = time.time()
        self._gauge_tokens = 0
        self._make_cache = make_cache
        # chunked prefill: long prompts in bucket-width chunks against
        # the growing cache (slot layout slices the cache; the paged
        # layout writes pages in place via paged_chunk_fn when the
        # ragged kernel path is active, else gathers the slot's view
        # and scatters the chunk back)
        self._prefill_chunk_fn = prefill_chunk_fn
        self._spec_verify_fn = spec_verify_fn
        self._paged_chunk_fn = paged_chunk_fn
        self._paged_verify_fn = paged_verify_fn
        self._spec_enabled = (config.speculative
                              and spec_verify_fn is not None)
        self._spec_toggle = True  # mixed-batch alternation state
        #: goodput-priced speculation policy (serving/spec.py); always
        #: constructed so /debug/efficiency can report it, only
        #: consulted when _spec_enabled
        self._spec_ctrl = SpecController(
            config.max_batch, draft=config.spec_draft,
            branches=config.spec_branches,
            adaptive=config.spec_adaptive,
            accept_floor=config.spec_accept_floor,
            probe_interval=config.spec_probe_interval)
        #: request each controller slot's state belongs to — the
        #: drafting loop resets a slot's EWMA when its tenant changes
        #: (cheaper than hooking every admit/retire site)
        self._spec_ctrl_owner: list = [None] * config.max_batch

        cfg = config
        if cfg.kv_layout not in ("slot", "paged"):
            raise ValueError(f"kv_layout must be 'slot' or 'paged', "
                             f"got {cfg.kv_layout!r}")
        if cfg.paged_attention not in ("auto", "kernel", "interpret",
                                       "xla", "view"):
            raise ValueError(
                f"paged_attention must be one of auto/kernel/interpret/"
                f"xla/view, got {cfg.paged_attention!r}")
        if cfg.kv_dtype not in ("bf16", "int8"):
            raise ValueError(f"kv_dtype must be 'bf16' or 'int8', "
                             f"got {cfg.kv_dtype!r}")
        if cfg.kv_dtype != "bf16" and cfg.kv_layout != "paged":
            raise ValueError("kv_dtype='int8' requires kv_layout="
                             "'paged' (the quantized pool is a page "
                             "pool; the slot layout has no pages)")
        if cfg.kv_pool_bytes is not None and cfg.kv_layout != "paged":
            raise ValueError("kv_pool_bytes sizes the paged pool; "
                             "set kv_layout='paged'")
        if cfg.spec_branches < 1:
            raise ValueError(f"spec_branches must be >= 1, got "
                             f"{cfg.spec_branches}")
        if 1 + cfg.spec_draft * cfg.spec_branches > MAX_TREE_NODES:
            raise ValueError(
                f"1 + spec_draft * spec_branches = "
                f"{1 + cfg.spec_draft * cfg.spec_branches} exceeds the "
                f"{MAX_TREE_NODES}-node packed ancestor bitmask; shrink "
                f"spec_draft or spec_branches")
        if not 0.0 < cfg.spec_accept_floor < 1.0:
            raise ValueError(f"spec_accept_floor must be in (0, 1), "
                             f"got {cfg.spec_accept_floor}")
        if cfg.spec_probe_interval < 1:
            raise ValueError(f"spec_probe_interval must be >= 1, got "
                             f"{cfg.spec_probe_interval}")
        #: dtype the dequantized view/model side of a quantized pool
        #: uses (set by _alloc_pool from the probe allocation); None
        #: until a pool exists — plain pools ignore it entirely
        self._kv_view_dtype = None
        #: allocated KV bytes (both caches, scale leaves included) —
        #: quant.quantized_bytes over the cache pytree, set post-alloc
        self._kv_bytes_total = 0

        # persistent XLA compilation cache BEFORE any graph compiles:
        # warmup's compile wall amortizes across processes (bench
        # children, TPU jobs, restarts) instead of being re-paid by
        # every child — round 5 burned its TPU window ~10:1 on
        # recompiles because nothing set jax_compilation_cache_dir
        from ..config.env import enable_compile_cache
        enable_compile_cache(cfg.compile_cache_dir)

        # decode + sampling fused into ONE graph returning just the
        # sampled token ids [B] — the per-step host transfer is 4B/slot
        # instead of the full [B, vocab] logits, and none of the
        # sampling math dispatches eagerly (each eager op is a host
        # round-trip, ruinous over a device tunnel)
        import os as _os
        seed = (cfg.seed if cfg.seed is not None
                else int.from_bytes(_os.urandom(4), "little"))
        #: the RESOLVED sampling seed (explicit or entropy-drawn) —
        #: captured into workload records so a replay engine built with
        #: EngineConfig(seed=header["engine_seed"]) reproduces the rng
        #: stream; greedy replay is bit-identical either way (argmax)
        self.seed = seed
        self.workload.engine_seed = seed
        if cfg.workload_capture:
            self.workload.start()
        base_key = jax.random.key(seed % (2**31))
        # disjoint rng streams: prefill and decode fold into separate
        # subkeys so their per-step indices can never collide
        decode_key = jax.random.fold_in(base_key, 0)
        prefill_key = jax.random.fold_in(base_key, 1)

        K = max(1, int(cfg.decode_steps_per_pass))
        M = max(1, int(cfg.decode_passes_per_dispatch))
        T = K * M  # tokens per dispatch

        def _fused_decode(step_fn, rng_key, tokens, kc, vc, lengths,
                          step, temps, top_ps, top_ks):
            # T = K x M decode steps in ONE lax.scan: sampled tokens
            # feed back into the next step on-device; rng derives
            # in-graph from the device-resident step counter (no eager
            # random.split, no host scalar upload per pass). The outer
            # passes-per-dispatch loop is fused into the same scan —
            # M multiplies the trip count while the compiled body stays
            # identical, so greedy outputs match M sequential
            # single-pass dispatches bit for bit. rng_key rides as an
            # ARGUMENT (not a captured constant) so the compiled HLO is
            # seed-independent — unseeded engines still hit the
            # persistent compile cache across processes.
            def one(carry, t):
                toks, kc, vc, lens = carry
                key = jax.random.fold_in(rng_key, step * T + t)
                logits, kc, vc = step_fn(toks, kc, vc, lens)
                nxt = _sample_batch(logits, key, temps, top_ps, top_ks)
                return (nxt, kc, vc, lens + 1), nxt

            return jax.lax.scan(
                one, (tokens, kc, vc, lengths), jnp.arange(T))

        def _advance_lengths(lengths, active):
            # persistent device lengths: advance active rows exactly as
            # the host mirror does (clamped at the cache ceiling);
            # pending-prefill sentinels and inactive rows pass through
            return jnp.where(active,
                             jnp.minimum(lengths + T, cfg.max_seq),
                             lengths)

        self._decode_windows: tuple = ()
        self._decode_by_window: dict = {}
        cfg_windows = tuple(sorted(
            w for w in (cfg.decode_windows or ()) if 0 < w < cfg.max_seq))
        #: raw configured windows — chunk walks use these even when
        #: the decode path itself is the ragged kernel (native paged),
        #: whose _decode_windows stays empty
        self._cfg_windows = cfg_windows
        #: native paged hot paths: the model family writes rows/chunks
        #: through the block tables and attends with the ragged paged
        #: kernels — no per-pass dense view of the pool. Chunked
        #: prefill, prefix-suffix reattachment and speculative verify
        #: follow decode onto the native path whenever the kernel path
        #: is active and the family supplies the paged chunk step.
        self._native_chunk = False
        self._native_verify = False
        if cfg.kv_layout == "paged":
            from ..ops.paged_kv import (gather_view, scatter_chunk,
                                        scatter_decode)
            self._scatter_chunk = scatter_chunk
            use_native = paged_decode_fn is not None and (
                cfg.paged_attention in ("kernel", "interpret", "xla")
                or (cfg.paged_attention == "auto"
                    and jax.default_backend() == "tpu"))
            self._native_chunk = use_native and paged_chunk_fn is not None
            self._native_verify = use_native and \
                paged_verify_fn is not None

            if use_native:
                def _decode_sample(params, tokens, use_prev, prev,
                                   k_pool, v_pool, tables, lengths,
                                   active, step, temps, top_ps, top_ks,
                                   rng_key):
                    # native paged path: the model's paged decode step
                    # writes each new row through the table and attends
                    # with the ragged kernel — the pool is only ever
                    # touched in place, no per-pass view (VERDICT r3 #2)
                    toks_in = jnp.where(use_prev, prev, tokens)

                    def step_fn(toks, kp, vp, lens):
                        return paged_decode_fn(params, toks, kp, vp,
                                               tables, lens)

                    (_, k_pool, v_pool, _), toks = _fused_decode(
                        step_fn, rng_key, toks_in, k_pool, v_pool,
                        lengths, step, temps, top_ps, top_ks)
                    return (toks, toks[-1], k_pool, v_pool,  # [T,B],[B]
                            _advance_lengths(lengths, active), step + 1)
                self._decode = jax.jit(_decode_sample,
                                       donate_argnums=(4, 5))
            else:
                pg_rows = max(1, int(cfg.page_size))

                def _make_decode(window=None):
                    # windowed variant: gather (and scatter back) only
                    # the first ceil(window/pg) table columns — the
                    # materialised view is O(window) rows per slot, not
                    # O(max_seq). This is the path mesh-sharded paged
                    # serving runs (the ragged kernel is single-device),
                    # so the win lands on multi-chip TPU too.
                    mp_w = (None if window is None
                            else -(-window // pg_rows))

                    def _decode_sample(params, tokens, use_prev, prev,
                                       k_pool, v_pool, tables, lengths,
                                       active, step, temps, top_ps,
                                       top_ks, rng_key):
                        # ONE gather per T-step pass builds the
                        # slot-contiguous view the dense decode step
                        # runs on; only the T fresh rows scatter back —
                        # the model family never sees pages
                        toks_in = jnp.where(use_prev, prev, tokens)
                        tb = tables if mp_w is None else tables[:, :mp_w]
                        k_view = gather_view(k_pool, tb,
                                             dtype=self._kv_view_dtype)
                        v_view = gather_view(v_pool, tb,
                                             dtype=self._kv_view_dtype)

                        def step_fn(toks, kc, vc, lens):
                            return decode_fn(params, toks, kc, vc, lens)

                        (_, k_view, v_view, _), toks = _fused_decode(
                            step_fn, rng_key, toks_in, k_view, v_view,
                            lengths, step, temps, top_ps, top_ks)
                        k_pool = scatter_decode(k_pool, tb, k_view,
                                                lengths, T)
                        v_pool = scatter_decode(v_pool, tb, v_view,
                                                lengths, T)
                        return (toks, toks[-1], k_pool, v_pool,
                                _advance_lengths(lengths, active),
                                step + 1)
                    return jax.jit(_decode_sample, donate_argnums=(4, 5))

                self._decode = _make_decode()
                self._decode_windows = cfg_windows
                self._decode_by_window = {
                    w: _make_decode(w) for w in self._decode_windows}
        else:
            def _make_decode(window=None):
                def _decode_sample(params, tokens, use_prev, prev,
                                   k_cache, v_cache, lengths, active,
                                   step, temps, top_ps, top_ks,
                                   rng_key):
                    # the prev-token select and the last-row slice both
                    # live IN the graph: an eager `where`/`toks[-1]` on
                    # device arrays costs five op-by-op compiles the
                    # first measured pass pays for (observed 137 ms vs
                    # the 3 ms steady-state pass on the tiny CPU config)
                    toks_in = jnp.where(use_prev, prev, tokens)

                    def step_fn(toks, kc, vc, lens):
                        if window is not None:
                            return decode_fn(params, toks, kc, vc, lens,
                                             attn_window=window)
                        return decode_fn(params, toks, kc, vc, lens)

                    (_, k_cache, v_cache, _), toks = _fused_decode(
                        step_fn, rng_key, toks_in, k_cache, v_cache,
                        lengths, step, temps, top_ps, top_ks)
                    return (toks, toks[-1], k_cache, v_cache,
                            _advance_lengths(lengths, active), step + 1)
                return jax.jit(_decode_sample, donate_argnums=(4, 5))

            self._decode = _make_decode()
            # windowed decode variants: attention reads O(window) rows
            # instead of O(max_seq) when every live length fits the
            # bucket. Opt-in via cfg.decode_windows; each listed
            # window is a separate compile, warmed in warmup(). Model
            # glue must accept attn_window (probed by signature, like
            # head_major).
            import inspect as _inspect
            try:
                supports_window = decode_fn is not None and \
                    "attn_window" in _inspect.signature(
                        decode_fn).parameters
            except (TypeError, ValueError):
                supports_window = False
            self._decode_windows = cfg_windows if supports_window else ()
            self._decode_by_window = {
                w: _make_decode(w) for w in self._decode_windows}
        self._decode_k = K
        #: tokens one decode dispatch yields per slot (K x M)
        self._tokens_per_pass = T
        #: rng keys ride as device-array ARGUMENTS, not jit constants,
        #: so compiled graphs are seed-independent and unseeded
        #: engines still share the persistent compile cache
        self._dev_decode_key = decode_key
        self._prefill_base_key = prefill_key
        self._prefill_cache: dict[Any, Callable] = {}
        self._prefill_fn = prefill_fn

        self._failed: str | None = None
        self._last_beat = time.time()
        self._watchdog: Any = None  # StallWatchdog, started with start()
        #: deterministic fault plan; the disabled default IS the
        #: NO_FAULTS singleton, so every site guards with one identity
        #: comparison (``self.faults is not NO_FAULTS``)
        self.faults = resolve_plan(config.faults)
        # fleet flight data recorder: the causal event ledger every
        # state transition is recorded on, plus the incident detector
        # that snapshots a diagnostic bundle when the fleet does
        # something an operator will be asked about (serving/events.py)
        from .events import IncidentDetector, resolve_ledger
        self.events = resolve_ledger(config.events, metrics=metrics)
        if self.faults is not NO_FAULTS:
            self.faults.events = self.events
        self.watermarks.events = self.events
        self.incidents = IncidentDetector(self.events.config,
                                          ledger=self.events,
                                          logger=logger)
        self.incidents.sources.update({
            "slo": lambda: (self.slo.state()
                            if self.slo is not None else None),
            "scheduler": lambda: self.waiting.state(),
            "goodput": self.goodput.state,
            "watermarks": self.watermarks.state,
            "recorder": self.recorder.snapshot,
            "config": self.config_digest,
            # every bundle ships the per-signature cost table + the
            # autoprofiler state ("which kernel class got slower, and
            # where is the trace") — the cost_drift reason's bundle
            # additionally carries the capture dir in its attrs
            "costs": self.cost_state,
            # ... and the integrity plane's probe/episode state, so an
            # integrity bundle names which golden prompt diverged
            "integrity": self.integrity_state,
        })
        # crash-recovery supervisor state (see _recover / RestartPolicy)
        self._restarts = 0
        self._last_crash: str | None = None
        self._stranded_slots = 0   # active slots a timed-out stop() left
        self._draining = False     # drain(): admission closed, work runs

        # admission queue: the tenant/SLO-aware Scheduler (same
        # put/pop_batch/qsize/close contract as native/batch_queue) —
        # fair-share DRR over per-tenant sub-queues, lanes, rate
        # limits and burn-rate shedding, all at admission boundaries.
        # Single-tenant traffic is strict FIFO, bit-identical to the
        # old queue. Built before attach_metrics so its gauges wire up.
        from .scheduler import Scheduler, SchedulerConfig
        sched_cfg = (config.scheduler if config.scheduler is not None
                     else SchedulerConfig())
        self.waiting = Scheduler(sched_cfg, config.max_waiting,
                                 ledger=self.usage_ledger,
                                 slo_source=lambda: self.slo,
                                 metrics=metrics, logger=logger)
        self.waiting.events = self.events

        if self.metrics is not None:
            self.attach_metrics(self.metrics)

        # prefill buckets wider than the cache would scatter K/V slabs
        # that cannot fit the [.., max_seq, ..] cache axis
        self._usable_buckets = tuple(sorted(
            b for b in cfg.prefill_buckets if b <= cfg.max_seq)) \
            or (cfg.max_seq,)

        if cfg.kv_layout == "paged":
            pg = max(1, int(cfg.page_size))
            self._pages_per_slot = -(-cfg.max_seq // pg)        # ceil
            base_pages = (cfg.kv_pages if cfg.kv_pages is not None
                          else cfg.max_batch * self._pages_per_slot)
            # pools are sized in BYTES, not rows: the page count is
            # budget // per-page-cost for the configured kv_dtype, so
            # an int8 pool at the same budget holds ~2x the pages.
            # The bf16 default without an explicit budget resolves to
            # exactly base_pages (no probe, no arithmetic drift).
            self._n_pages = self._sized_pool_pages(pg, base_pages)
            self.k_cache, self.v_cache = self._alloc_pool(pg)
            self._free_pages = list(range(self._n_pages))
            #: per-slot ordered page ids; OOB id ``n_pages`` = unallocated
            self._tables = np.full((cfg.max_batch, self._pages_per_slot),
                                   self._n_pages, np.int32)
            self._slot_pages = np.zeros(cfg.max_batch, np.int32)
            self._admit_seq = 0
            #: page refcounts: slots and the prefix cache each hold one
            self._page_refs = np.zeros(self._n_pages, np.int32)
            self._prefix_cache: dict[tuple, list[int]] = {}
            #: pins held by the cache (entries may overlap on shared
            #: pages, so this counts references, not distinct pages)
            self._cached_pages = 0
            #: cached key lengths -> entry count: probes test only
            #: these lengths instead of every aligned prefix
            self._prefix_lens: dict[int, int] = {}
            # reattachment needs the chunk-with-history walk; without
            # it a populated cache could never produce a hit
            self._prefix_enabled = (cfg.prefix_cache
                                    and prefill_chunk_fn is not None)
            self._prefix_budget = (cfg.prefix_cache_pages
                                   if cfg.prefix_cache_pages is not None
                                   else max(1, self._n_pages // 4))
        else:
            self.k_cache, self.v_cache = make_cache(cfg.max_batch,
                                                    cfg.max_seq)
            self._prefix_enabled = False  # sharing needs page tables
        # allocated KV footprint (K + V, scale leaves included):
        # quantized_bytes walks the pytree so the quantized pool's q/s
        # split needs no special casing here
        from ..ops.quant import quantized_bytes
        self._kv_bytes_total = int(quantized_bytes(
            (self.k_cache, self.v_cache)))
        self.lengths = np.zeros(cfg.max_batch, np.int32)       # kv length per slot
        self.active: list[GenRequest | None] = [None] * cfg.max_batch
        # already-admitted work bounced back (preemption, slot races,
        # chunk-walk pacing): re-enters ahead of the public queue and
        # NEVER counts against the admission bound — engine-thread
        # only, no lock needed
        self._requeued: list[GenRequest] = []
        self._requeued_set: set[int] = set()  # id() dedup: a request
        #                       preempted in the same pass it requeued
        #                       itself must not enter twice

        # decode pipeline: dispatched-but-uncollected passes (FIFO,
        # depth <= 2), plus the newest pass's last sampled token per
        # slot as a DEVICE array — the next pass's input rides it
        # without a host sync (see the decode section comment)
        from collections import deque
        self._pending: Any = deque()
        self._pending_prefills: Any = deque()
        self._dev_last: Any = None
        # committed device-resident stand-in for "no previous token":
        # building it fresh at dispatch would be an eager op per pass
        self._dev_zero = jnp.zeros(cfg.max_batch, jnp.int32)
        self._dev_last_reqs: list = [None] * cfg.max_batch
        # device-resident scheduler state: the per-slot arrays every
        # decode pass consumes (tokens/use_prev/active/lengths/temps/
        # top_ps/top_ks) live on device and are re-uploaded ONLY when
        # an admission/retirement/preemption/prefill/spec event flips
        # _sched_dirty — steady-state dispatches reuse them with zero
        # host->device transfers. Lengths and the rng step advance
        # on-device inside the decode graph, mirrored on the host.
        self._dev_sched: dict | None = None
        self._sched_dirty = True
        self._active_np = np.zeros(cfg.max_batch, bool)
        self._fresh_rows: list[int] = []
        self._dev_tables: Any = None     # paged: device block tables
        self._tables_dirty = True
        self._dev_rng_step = jnp.zeros((), jnp.int32)
        self._decode_busy_until = 0.0
        self._prefill_busy_until = 0.0

        self._rng_step = 0
        self._running = False
        self._cleaned = False
        self._thread: threading.Thread | None = None
        self._step_count = 0
        self.total_generated = 0
        #: per-phase wall time (device call + sync) for perf accounting;
        #: the bench surfaces these as the per-phase breakdown.
        #: dispatch_s/collect_s are the HOST-side spans of the decode
        #: hot loop (arg prep + async dispatch / post-sync emission);
        #: h2d_transfers counts scheduler-state uploads performed by
        #: decode dispatches — steady-state passes must add zero.
        self.stats = {"prefill_calls": 0, "prefill_s": 0.0,
                      "decode_passes": 0, "decode_s": 0.0,
                      "dispatch_s": 0.0, "collect_s": 0.0,
                      "h2d_transfers": 0, "sched_syncs": 0,
                      "view_bytes_avoided": 0,
                      "prefix_hits": 0, "spec_passes": 0,
                      "spec_accepted": 0, "spec_drafted": 0,
                      "spec_rows": 0, "preemptions": 0,
                      "requeues": 0, "prefix_evictions": 0,
                      "stalls": 0, "recompiles": 0, "cost_drifts": 0,
                      "integrity_failures": 0}
        #: waste-counter watermark already published to the metrics
        #: manager (the throttled gauge pass emits deltas)
        self._waste_published: dict[str, float] = {}

    # ----------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Start (or RESTART) the engine thread. An engine stopped with
        ``stop()``/``drain()`` restarts in place: weights and every
        compiled graph are still resident, so the restart skips
        warmup entirely — only KV bookkeeping and the admission queue
        reset (the queue reopens; tenant/rate-limit state survives)."""
        if self._running:
            return
        prev = self._thread
        if prev is not None and prev.is_alive():
            # a timed-out stop() left the old loop mid device call; a
            # second loop over the same donated caches would corrupt
            # them — the caller must wait the pass out first
            raise RuntimeError(
                "previous engine thread is still in a device call "
                "(stop() timed out); wait for it to exit before start()")
        if self._cleaned:
            # restart after a clean stop (or a terminal crash): stand
            # the runtime back up on the resident weights/compile cache
            self._reset_runtime_state()
            self._cleaned = False
            self._failed = None
            self._stranded_slots = 0
            if hasattr(self.waiting, "reopen"):
                self.waiting.reopen()
        self._draining = False
        self._last_beat = time.time()
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="gofr-engine")
        self._thread.start()
        if self.config.watchdog_interval_s > 0 and self._watchdog is None:
            from .observability import StallWatchdog
            self._watchdog = StallWatchdog(
                self, interval_s=self.config.watchdog_interval_s)
            self._watchdog.start()

    def stop(self, join_timeout_s: float = 30.0) -> None:
        watchdog, self._watchdog = self._watchdog, None
        if watchdog is not None:
            watchdog.stop()
        self._running = False
        # snapshot: concurrent stop() calls are legal (handler + app
        # shutdown hook), and another stopper may null self._thread
        # between our check and use
        thread = self._thread
        if thread is not None:
            # the engine thread runs _shutdown_cleanup itself when the
            # loop exits, so a slow in-flight pass (e.g. a first-hit
            # compile outliving the join timeout) can never race
            # host-side cleanup: whoever finishes the loop retires the
            # streams, exactly once
            thread.join(timeout=join_timeout_s)
            if thread.is_alive():
                # still mid device call (slow compile or wedged
                # runtime): fail the *queued* requests now — the live
                # thread only touches the queue via pop_batch, which
                # returns None once closed — but leave active slots to
                # the thread's own cleanup at pass end, so a stream
                # can never see tokens after its terminal None. The
                # thread handle stays set so repeated stop()/close()
                # never run the full cleanup concurrently with it.
                stranded_active = sum(
                    1 for r in self.active if r is not None)
                self._stranded_slots = stranded_active
                if self.logger:
                    self.logger.warn(
                        f"engine thread still in a device call; "
                        f"{stranded_active} active slot(s) stranded — "
                        "streams retire when the pass completes")
                self.events.emit("engine.stranded_slot",
                                 severity="warn",
                                 cause="stop timed out mid device call",
                                 slots=stranded_active)
                self.waiting.close()
                stranded = self.waiting.pop_batch(1 << 16, first_wait_s=0.0)
                for req in stranded or []:
                    self._fail(req, "engine stopped")
                return
            self._thread = None
        if not self._cleaned:  # loop never started (or crashed mid-start)
            self._shutdown_cleanup("engine stopped")

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful shutdown: close admission (new submits are refused
        with a typed ``draining`` 503 + Retry-After), let queued and
        in-flight requests run to completion, then ``stop()``. Returns
        True when everything retired inside the budget; False when the
        deadline cut stragglers off (they fail with "engine stopped",
        like a plain stop). The engine can ``start()`` again after."""
        deadline = time.time() + timeout_s
        self._draining = True
        self.events.emit("engine.drain", cause="admission closed",
                         timeout_s=timeout_s)
        try:
            drained = False
            while True:
                # engine-thread-owned state read racily from here: all
                # plain loads under the GIL, and the quiesce condition
                # is stable once reached (admission is closed)
                if (self.waiting.qsize() == 0 and not self._requeued
                        and not self._pending
                        and not self._pending_prefills
                        and all(r is None for r in self.active)):
                    drained = True
                    break
                if not self._running or time.time() >= deadline:
                    break
                time.sleep(0.01)
            self.stop(join_timeout_s=max(1.0, deadline - time.time()))
            return drained and not self._stranded_slots
        finally:
            self._draining = False

    def _shutdown_cleanup(self, reason: str) -> None:
        """Terminal teardown: refuse new submissions, fail anything
        stranded in the queue AND anything still holding a slot — no
        submitter may be left waiting on a request nothing will run.
        Runs on whichever thread finishes the loop, exactly once."""
        self._cleaned = True
        self.waiting.close()
        stranded = self.waiting.pop_batch(1 << 16, first_wait_s=0.0)
        for req in stranded or []:
            self._fail(req, reason)
        requeued, self._requeued = self._requeued, []
        self._requeued_set.clear()
        for req in requeued:
            self._fail(req, reason)
        for i, req in enumerate(self.active):
            if req is not None:
                self.active[i] = None
                self.lengths[i] = 0
                self._fail(req, reason)

    def _reset_runtime_state(self) -> None:
        """Stand the runtime back up on the resident weights: no
        in-flight passes, empty KV bookkeeping, a pristine paged
        allocator, device scheduler state marked for re-upload.
        Weights and every compiled graph are untouched — a restarted
        engine serves its first request without recompiling. Shared by
        ``start()``-after-``stop()`` and the crash-recovery supervisor
        (``_recover``); donated caches are re-allocated only when a
        crashing pass actually consumed them."""
        cfg = self.config
        self._pending.clear()
        self._pending_prefills.clear()
        self._dev_last = None
        self._dev_last_reqs = [None] * cfg.max_batch
        self._dev_sched = None
        self._sched_dirty = True
        self._tables_dirty = True
        self._decode_busy_until = 0.0
        self._prefill_busy_until = 0.0
        lost = self._kv_lost()
        if cfg.kv_layout == "paged":
            if lost:
                self.k_cache, self.v_cache = self._alloc_pool(
                    max(1, int(cfg.page_size)))
            self._free_pages = list(range(self._n_pages))
            self._tables[:] = self._n_pages
            self._slot_pages[:] = 0
            self._page_refs[:] = 0
            self._prefix_cache.clear()
            self._prefix_lens.clear()
            self._cached_pages = 0
            self._prefix_digest_dirty = True
        elif lost:
            self.k_cache, self.v_cache = self._make_cache(
                cfg.max_batch, cfg.max_seq)
        self.lengths[:] = 0
        # speculation: slot ownership is void (every slot re-admits),
        # so the next drafting pass re-seeds each slot's accept EWMA;
        # the controller's fitted costs and lifetime totals survive —
        # restart doesn't change what a token costs
        self._spec_ctrl_owner = [None] * cfg.max_batch

    def health_check(self) -> dict:
        status = "DOWN" if (self._failed or not self._running) else "UP"
        active = sum(r is not None for r in self.active)
        waiting = self.waiting.qsize()
        out = {
            "status": status,
            "active_slots": active,
            "waiting": waiting,
            "steps": self._step_count,
            "total_generated": self.total_generated,
        }
        threshold = self.config.stall_threshold_s
        stalled_for = time.time() - self._last_beat
        if (status == "UP" and threshold > 0 and (active or waiting)
                and stalled_for > threshold):
            # work in flight but no pass completing: a wedged device
            # call (hung runtime/tunnel) — exceptions would have gone
            # through _crash, so this is the only way to see a hang
            out["status"] = "DEGRADED"
            out["stalled_for_s"] = round(stalled_for, 1)
        if self.stats.get("stalls"):
            out["stalls"] = self.stats["stalls"]
        if self._restarts:
            out["restarts"] = self._restarts
        if self._last_crash:
            out["last_crash"] = self._last_crash
        if self._stranded_slots:
            out["stranded_slots"] = self._stranded_slots
        if self._failed:
            out["error"] = self._failed
        if self.recorder.enabled:
            out["flight"] = self.recorder.summary()
        return out

    def close(self) -> None:
        # the app-shutdown path: a wedged device call must not hold
        # graceful shutdown for the full join budget — the daemon
        # thread dies with the process, queued requests fail now
        self.stop(join_timeout_s=2.0)

    def attach_metrics(self, metrics: Any) -> None:
        """Point the engine at a metrics manager, registering the
        serving gauges if absent — engines are often built before the
        app exists (``app.serve_model`` attaches the container's
        manager post-hoc; a bare assignment would leave every
        ``set_gauge`` logging 'not registered')."""
        self.metrics = metrics
        for name, desc in (
            ("app_engine_active_slots", "occupied decode slots"),
            ("app_engine_waiting", "requests queued for admission"),
            ("app_engine_kv_pool_utilization",
             "fraction of KV capacity in use (slots + prefix cache)"),
            ("app_engine_kv_pool_fragmentation",
             "fraction of allocated KV page capacity holding no rows"),
            ("app_engine_prefix_cache_entries",
             "prefix-cache entries pinned"),
            ("app_engine_prefix_cache_pages",
             "page references pinned by the prefix cache"),
            ("app_engine_tokens_per_second",
             "generated tokens per second (quarter-second window)"),
            ("app_engine_mfu",
             "decode-path model FLOPs utilization (cost_analysis FLOPs "
             "x tokens/s over the chip peak; 0 when the peak or the "
             "compiled cost is unknown)"),
            ("app_engine_goodput_ratio",
             "useful device time over total busy device time "
             "(1 - waste; see app_engine_waste_seconds for the causes)"),
            ("app_engine_kv_pages_watermark",
             "high-water mark of KV pool pages in use (paged layout)"),
            ("app_engine_kv_rows_watermark",
             "high-water mark of live KV rows (slot layout)"),
            ("app_engine_prefix_pages_watermark",
             "high-water mark of page references pinned by the prefix "
             "cache"),
            ("app_engine_kv_bytes_watermark",
             "high-water mark of KV-pool HBM bytes held by in-use "
             "pages/rows (scale leaves included for int8 pools)"),
            ("app_engine_host_rss_bytes_watermark",
             "host process RSS high-water mark (ru_maxrss)"),
            ("app_engine_spec_accept_rate",
             "lifetime speculative draft acceptance rate "
             "(accepted/drafted; 1.0 before any drafting)"),
        ):
            if metrics.get(name) is None:
                metrics.new_gauge(name, desc)
        for name, desc in (
            ("app_engine_h2d_transfers",
             "host->device scheduler-state uploads by the decode "
             "path (event-driven; zero per steady-state pass)"),
            ("app_engine_preemptions",
             "requests preempted (vLLM-style recompute requeue)"),
            ("app_engine_prefix_evictions",
             "prefix-cache entries evicted under pool pressure"),
            ("app_engine_requeues",
             "admitted work bounced back to the requeue list "
             "(chunk-walk pacing, slot races, preemption)"),
            ("app_engine_spec_drafted",
             "draft tokens offered to speculative verify"),
            ("app_engine_spec_accepted",
             "draft tokens accepted by speculative verify"),
            ("app_engine_stalls",
             "stall episodes escalated by the watchdog (work in "
             "flight, no pass for stall_threshold_s)"),
            ("app_replay_divergence",
             "replayed requests whose token stream diverged from the "
             "recorded completion (serving/replay.py)"),
            ("app_tenant_requests",
             "retired requests by tenant and status (ok/error/"
             "cancelled)"),
            ("app_tenant_prompt_tokens", "prompt tokens by tenant"),
            ("app_tenant_completion_tokens",
             "generated tokens by tenant"),
            ("app_tenant_device_seconds",
             "device busy time attributed to each tenant (per-request "
             "share of every pass's busy span)"),
            ("app_tenant_waste_seconds",
             "per-tenant attributable waste device time by cause "
             "(preempt_recompute, spec_rejected)"),
            ("app_engine_waste_seconds",
             "busy device time classified as waste, by cause (padding/"
             "preempt_recompute/spec_rejected/bubble); useful + waste "
             "== busy is conserved"),
            ("app_engine_recompiles",
             "unexpected post-warmup XLA recompiles detected by the "
             "dispatch-shape sentinel"),
            ("app_engine_cost_drift",
             "pass-cost drift episodes by dispatch kind: a signature's "
             "cost EWMA departed its sealed baseline past the "
             "configured ratio/sigma thresholds (serving/costmodel.py)"),
            ("app_engine_integrity_failures",
             "golden canary probe digest mismatch episodes by kind: "
             "this host produced output whose fingerprint departed the "
             "sealed golden digest (serving/integrity.py)"),
            ("app_engine_restarts",
             "engine loop restarts by the in-thread crash-recovery "
             "supervisor (EngineConfig.restart_policy)"),
            ("app_engine_requests_recovered",
             "pre-first-token requests salvaged into the recovery "
             "buffer and replayed across an engine restart"),
        ):
            if metrics.get(name) is None:
                metrics.new_counter(name, desc)
        for name, desc in (
            ("app_slo_burn_rate",
             "error-budget burn rate by window (1 = spending the "
             "budget at exactly the sustainable pace)"),
            ("app_slo_error_budget_remaining",
             "fraction of the availability error budget left over "
             "SLOConfig.budget_window_s"),
            ("app_sched_lane_depth",
             "queued requests per scheduler lane (interactive/"
             "background)"),
            ("app_sched_tenant_share",
             "per-tenant fraction of windowed device time "
             "(the fair-share dequeue signal)"),
            ("app_sched_shed_active",
             "1 while a burn-rate shed episode is active"),
        ):
            if metrics.get(name) is None:
                metrics.new_gauge(name, desc)
        for name, desc in (
            ("app_sched_rejections",
             "admission refusals by cause (queue_full/rate_limited/"
             "shed) and tenant"),
            ("app_sched_preemptions",
             "scheduler-initiated background preemptions to unstarve "
             "the interactive lane (priced by the preempt_recompute "
             "goodput ledger)"),
            ("app_events_total",
             "event-ledger records by kind (serving/events.py)"),
            ("app_events_dropped",
             "event-ledger ring evictions by kind — a truncated "
             "timeline is visible, never silent"),
        ):
            if metrics.get(name) is None:
                metrics.new_counter(name, desc)
        ttft_buckets = (0.005, 0.01, 0.025, 0.05, 0.075, 0.1, 0.15,
                        0.25, 0.5, 1, 2, 5)
        for name, desc, buckets in (
            ("app_chat_ttft_seconds", "time to first token",
             ttft_buckets),
            ("app_chat_queue_seconds",
             "submit -> first slot assignment (admission queue wait)",
             ttft_buckets),
            ("app_chat_e2e_seconds", "submit -> finish wall time",
             (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60)),
            ("app_chat_tpot_seconds",
             "per-request mean inter-token latency (time per output "
             "token past the first)",
             (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
              0.25, 0.5, 1)),
            ("app_engine_batch_occupancy",
             "active decode slots per pass",
             (1, 2, 4, 8, 16, 32, 64, 128, 256)),
            ("app_tpu_execute_seconds", "device execute wall time",
             (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
              0.25, 0.5, 1, 5)),
            ("app_tenant_queue_seconds",
             "admission queue wait by tenant", ttft_buckets),
            ("app_tenant_e2e_seconds",
             "submit -> finish wall time by tenant",
             (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60)),
        ):
            if metrics.get(name) is None:
                metrics.new_histogram(name, desc, buckets=buckets)
        if self.usage_ledger is not None \
                and self.usage_ledger.metrics is None:
            self.usage_ledger.metrics = metrics
        if self.slo is not None and self.slo.metrics is None:
            self.slo.metrics = metrics
        if getattr(self.waiting, "metrics", None) is None \
                and hasattr(self.waiting, "publish_gauges"):
            self.waiting.metrics = metrics
        if self.events.enabled and self.events.metrics is None:
            self.events.metrics = metrics

    def config_digest(self) -> dict:
        """JSON-safe engine-config summary for incident bundles: plain
        scalars pass through, everything else stringifies (a bundle
        must always serialize)."""
        from dataclasses import fields as _fields
        out = {}
        for f in _fields(self.config):
            value = getattr(self.config, f.name)
            out[f.name] = value if isinstance(
                value, (bool, int, float, str, type(None))) \
                else repr(value)
        out["resolved_seed"] = self.seed
        return out

    def warmup(self, prompt_lens: tuple = (1,), decode: bool = True,
               chunked: bool = False) -> None:
        """Compile serving graphs ahead of traffic: every power-of-two
        prefill group size for each bucket covering ``prompt_lens``,
        plus the decode pass. Pass ``chunked=True`` when prompts longer
        than the widest bucket are expected, so the chunked-prefill
        graph compiles here instead of inline on the first long
        prompt. Dummy rows carry slot == max_batch so the cache
        scatter drops them — real state is untouched. Call before
        ``start()`` (it exercises the donated caches)."""
        cfg = self.config
        paged = cfg.kv_layout == "paged"
        buckets = {self._bucket_for(int(n)) for n in prompt_lens}
        for bucket in sorted(buckets):
            for g in self._group_sizes():
                self.sentinel.observe(self._sig("prefill", bucket, g))
                if paged:  # all-OOB tables: every write drops
                    slots = jnp.full((g, self._pages_per_slot),
                                     self._n_pages, jnp.int32)
                else:
                    slots = jnp.full(g, cfg.max_batch, jnp.int32)
                fn = self._get_prefill(bucket, g)
                toks, self.k_cache, self.v_cache = fn(
                    self.params, jnp.zeros((g, bucket), jnp.int32),
                    jnp.ones(g, jnp.int32), self.k_cache, self.v_cache,
                    slots, np.int32(0),
                    jnp.zeros(g, jnp.float32), jnp.ones(g, jnp.float32),
                    jnp.zeros(g, jnp.int32), self._prefill_base_key)
                jax.block_until_ready(toks)
        if decode:
            b = cfg.max_batch
            tables = (jnp.full((b, self._pages_per_slot), self._n_pages,
                               jnp.int32),) if paged else ()
            for w in (0, *self._decode_windows):
                self.sentinel.observe(self._sig("decode", w))
            variants = [self._decode] + [
                self._decode_by_window[w] for w in self._decode_windows]
            for fn in variants:
                toks, _, self.k_cache, self.v_cache, _, _ = fn(
                    self.params, jnp.zeros(b, jnp.int32),
                    jnp.zeros(b, bool), self._dev_zero,
                    self.k_cache, self.v_cache, *tables,
                    jnp.ones(b, jnp.int32), jnp.zeros(b, bool),
                    jnp.zeros((), jnp.int32),
                    jnp.zeros(b, jnp.float32), jnp.ones(b, jnp.float32),
                    jnp.zeros(b, jnp.int32), self._dev_decode_key)
                jax.block_until_ready(toks)
            # MFU basis: ONE cost_analysis of the (already compiled)
            # decode graph, here at compile time — serve-time MFU gauge
            # updates are pure host arithmetic, never a device sync
            try:
                from .observability import (device_peak_flops,
                                            jit_cost_flops)
                pass_flops = jit_cost_flops(
                    self._decode, self.params, jnp.zeros(b, jnp.int32),
                    jnp.zeros(b, bool), self._dev_zero,
                    self.k_cache, self.v_cache, *tables,
                    jnp.ones(b, jnp.int32), jnp.zeros(b, bool),
                    jnp.zeros((), jnp.int32),
                    jnp.zeros(b, jnp.float32), jnp.ones(b, jnp.float32),
                    jnp.zeros(b, jnp.int32), self._dev_decode_key)
                if pass_flops:
                    self._flops_per_token = pass_flops / float(
                        b * self._tokens_per_pass)
                self._peak_flops = device_peak_flops()
            except Exception:  # cost analysis is best-effort, never fatal
                pass
        if chunked and self._prefill_chunk_fn is not None:
            # compile the chunk-walk graph at every bucket width for
            # both group sizes the walk uses (solo and full wave) —
            # all rows dummy (OOB slots/tables): every cache write
            # drops, the samples are discarded
            P = max(1, cfg.prefill_batch)
            # full graph always; plus the single windowed chunk
            # variant the walk dispatcher may select (paged + windows;
            # the native chunk path is length-bounded and never picks
            # a windowed variant)
            chunk_windows = [None]
            if paged and self._cfg_windows and not self._native_chunk:
                chunk_windows.append(self._cfg_windows[-1])
            for cw in chunk_windows:
                fn = self._get_chunk_prefill(cw)
                for width in self._usable_buckets:
                    if cw is not None and width > cw:
                        continue  # the dispatcher never picks cw then
                    for g in sorted({1, P}):
                        self.sentinel.observe(
                            self._sig("chunk", width, g, cw))
                        if paged:
                            slot_arg = jnp.full(
                                (g, self._pages_per_slot),
                                self._n_pages, jnp.int32)
                        else:
                            slot_arg = jnp.full(g, cfg.max_batch,
                                                jnp.int32)
                        toks, self.k_cache, self.v_cache = fn(
                            self.params, jnp.zeros((g, width), jnp.int32),
                            self.k_cache, self.v_cache, slot_arg,
                            jnp.zeros(g, jnp.int32),
                            jnp.zeros(g, jnp.int32),
                            np.int32(0), jnp.zeros(g, jnp.float32),
                            jnp.ones(g, jnp.float32),
                            jnp.zeros(g, jnp.int32),
                            self._prefill_base_key)
                        jax.block_until_ready(toks)
        if self._spec_enabled:
            # tree-verify graphs: one per pow-2 width bucket
            # (_spec_pass picks the smallest bucket holding the pass's
            # widest tree). Observe AND eagerly compile every bucket —
            # the sealed sentinel treats any unseen post-warmup
            # signature as a regression, and a lazy first compile
            # would stall the serving loop mid-stream. All rows are
            # dummies (OOB offsets/tables): every cache write drops.
            b = cfg.max_batch
            spec_tables = (jnp.full((b, self._pages_per_slot),
                                    self._n_pages, jnp.int32),) \
                if paged else ()
            fn = self._get_spec_verify()
            cap = 1 + cfg.spec_draft * cfg.spec_branches
            w = 2
            while True:
                self.sentinel.observe(self._sig("spec_verify", w))
                _, bonus, _, self.k_cache, self.v_cache = fn(
                    self.params, jnp.zeros((b, w), jnp.int32),
                    jnp.zeros((b, w), jnp.int32),
                    jnp.zeros((b, w), jnp.int32),
                    jnp.ones((b, w), jnp.int32),
                    self.k_cache, self.v_cache, *spec_tables,
                    jnp.full(b, cfg.max_seq, jnp.int32),
                    jnp.ones(b, jnp.int32), np.int32(0),
                    jnp.zeros(b, jnp.float32),
                    jnp.ones(b, jnp.float32),
                    jnp.zeros(b, jnp.int32), self._prefill_base_key)
                jax.block_until_ready(bonus)
                if w >= cap:
                    break
                w *= 2
        self.sentinel.seal()

    def _clamp_prompt(self, tokens: list[int], max_new: int) -> list[int]:
        """Keep the tail of an over-long prompt, reserving room to
        generate. With chunked prefill the cache is the only cap;
        without it the widest prefill graph also bounds admission.
        (Preemption-requeue clamps less aggressively: see ``_preempt``
        — its continuation already fit the cache.)"""
        room = max(1, min(max_new, self.config.max_seq // 2))
        limit = max(1, self.config.max_seq - room - 1)
        if self._prefill_chunk_fn is None:
            limit = min(limit, max(self._usable_buckets))
        return tokens[-limit:] if len(tokens) > limit else tokens

    # -------------------------------------------------------------- submit
    def submit(self, prompt_tokens: list[int],
               params: SamplingParams | None = None, *,
               traceparent: str | None = None,
               tenant: str | None = None,
               lane: str = "interactive") -> GenRequest:
        """Called from the asyncio loop; returns a request whose
        ``out_queue`` yields token ids and then ``None``.

        When a tracer is attached, the request carries the caller's
        trace identity — the active span on the submitting thread/task
        (the HTTP/gRPC middleware span), else a W3C ``traceparent``
        header — and the engine.* child spans assemble at retire.
        ``tenant`` is the resolved bounded-cardinality accounting
        label (handlers pass it from the auth principal); it rides the
        request into spans, the flight-recorder log and the usage
        ledger. ``lane`` routes the request into the scheduler's
        interactive or background lane (the config's
        ``background_tenants`` mapping applies when left default)."""
        params = params or SamplingParams()
        prompt_tokens = self._clamp_prompt(list(prompt_tokens),
                                           params.max_new_tokens)
        req = GenRequest(prompt_tokens=prompt_tokens, params=params,
                         tenant=tenant, lane=lane)
        if self.tracer is not None:
            parent = self.tracer.current_span()
            if parent is not None:
                if parent.sampled:
                    req.trace = (parent.trace_id, parent.span_id)
            elif traceparent:
                from ..tracing.tracer import (_traceparent_sampled,
                                              extract_traceparent)
                remote = extract_traceparent(traceparent)
                if remote is not None and _traceparent_sampled(traceparent):
                    req.trace = remote
        try:
            req.loop = asyncio.get_running_loop()
            req.out_queue = asyncio.Queue()
        except RuntimeError:  # submitted from a plain thread (tests/bench)
            req.loop = None
            req.out_queue = None
        if self.faults is not NO_FAULTS \
                and self.faults.trip("page_exhaustion",
                                     request_id=req.tenant):
            # injected KV-pool exhaustion: refused at admission with a
            # typed retryable 503 — the engine keeps serving
            self._refuse(req, "kv_exhausted",
                         "kv page pool exhausted; retry shortly",
                         retry_after_s=1.0)
            return req
        if self._draining:
            self._refuse(req, "draining",
                         "engine draining for shutdown; retry against "
                         "another replica", retry_after_s=5.0)
            return req
        if not self.waiting.put(req):  # refused/closed: fail loudly,
            # never hang. The scheduler stamps a typed reject
            # (queue_full / rate_limited / shed) for policy refusals;
            # a closed queue stamps nothing — lifecycle refusals
            # (stopped or crashed engine) get their own typed code so
            # clients see 503 + Retry-After + details.code, not a bare
            # string.
            if req.reject is not None and self._running:
                self._fail(req, req.reject.message)
            elif self._running:
                self._fail(req, "engine overloaded: waiting queue full")
            else:
                policy = self.config.restart_policy
                retry = (policy.backoff_for(self._restarts + 1)
                         if policy is not None else 1.0)
                self._refuse(
                    req, "engine_down",
                    "engine not accepting requests"
                    + (f" (last crash: {self._last_crash})"
                       if self._last_crash else ""),
                    retry_after_s=max(1.0, retry))
        return req

    def submit_sync(self, prompt_tokens: list[int],
                    params: SamplingParams | None = None) -> GenRequest:
        """Blocking submit for non-async callers; returns when finished."""
        req = self.submit(prompt_tokens, params)
        while req.finished_at is None and req.error is None:
            time.sleep(0.002)
        return req

    def cancel(self, req: GenRequest) -> None:
        """Abandon a request: a disconnected client must not keep
        burning decode slots. Waiting requests are dropped at
        admission; active slots retire at the next pass."""
        req.cancelled = True

    async def stream_request(self, req: GenRequest):
        """Async iterator of a submitted request's token ids. Closing
        the iterator early (client disconnect) cancels the request."""
        try:
            while True:
                token = await req.out_queue.get()
                if token is None:
                    break
                yield token
        finally:
            if req.finished_at is None:
                self.cancel(req)

    # ---------------------------------------------------------- scheduling
    def _group_sizes(self) -> tuple:
        """Compiled prefill group sizes: powers of two up to
        ``prefill_batch``, plus ``prefill_batch`` itself when it is not
        one — the admission chunk size always has an exact graph."""
        cap = max(1, self.config.prefill_batch)
        sizes = []
        g = 1
        while g < cap:
            sizes.append(g)
            g *= 2
        sizes.append(cap)
        return tuple(sizes)

    def _bucket_for(self, n: int) -> int:
        for b in self._usable_buckets:
            if n <= b:
                return b
        return self._usable_buckets[-1]

    def _get_prefill(self, bucket: int, group: int) -> Callable:
        """Fused group prefill per (bucket, group-size) — ONE device
        call per group: forward [P, bucket], sample each row's first
        token, and scatter the prompt K/V straight into the donated
        caches (dummy rows carry slot == max_batch, dropped by the
        scatter). The host pulls back 4·P bytes of token ids, nothing
        else. Group sizes are powers of two up to ``prefill_batch`` so
        a lone arrival runs a [1, bucket] graph, not the full-width
        one, at the cost of ≤log2(P) extra compiles per bucket."""
        fn = self._prefill_cache.get((bucket, group))
        if fn is None:
            prefill_fn = self._prefill_fn

            paged = self.config.kv_layout == "paged"
            scatter_chunk = getattr(self, "_scatter_chunk", None)

            def fused(params, tokens, kv_len, kc, vc, slots, step,
                      temps, top_ps, top_ks, rng_key):
                key = jax.random.fold_in(rng_key, step)
                logits, (k, v) = prefill_fn(params, tokens, kv_len)
                if logits.ndim == 3:  # full [P, S, V]: keep last position
                    logits = jnp.take_along_axis(
                        logits, jnp.maximum(kv_len - 1, 0)[:, None, None],
                        axis=1)[:, 0]
                toks = _sample_batch(logits, key, temps, top_ps, top_ks)
                if paged:
                    # ``slots`` carries each row's block table [P, Mp];
                    # scatter_chunk (offset 0, per-row prompt length)
                    # writes only the pages each prompt spans — pad
                    # rows past kv_len drop instead of round-tripping
                    # the scatter owns the pool representation: plain
                    # pools cast internally, quantized pools quantize
                    # on write (no .astype on the pool here)
                    zeros = jnp.zeros_like(kv_len)
                    kc = scatter_chunk(kc, slots, k, zeros, kv_len)
                    vc = scatter_chunk(vc, slots, v, zeros, kv_len)
                else:
                    s = k.shape[2]
                    kc = kc.at[:, slots, :s].set(k.astype(kc.dtype),
                                                 mode="drop")
                    vc = vc.at[:, slots, :s].set(v.astype(vc.dtype),
                                                 mode="drop")
                return toks, kc, vc
            fn = jax.jit(fused, donate_argnums=(3, 4))
            self._prefill_cache[(bucket, group)] = fn
        return fn

    def _get_chunk_prefill(self, window: int | None = None) -> Callable:
        """Fused G-slot chunk step: bring each walking slot's cache
        rows into a contiguous view (an index gather for the slot
        layout, a page gather for the paged pool), run one [G, width]
        chunk forward against the histories, splice the written rows
        back, and sample (only each row's final chunk's sample is
        used). The jit retraces per (G, width) — an admission wave of
        prefix-cache suffixes shares ONE dispatch instead of one per
        request, and a short tail pays for its own bucket, not the
        widest (a [1, 512] forward for a 4-token suffix was the r4
        bench's prefix-hit slowdown). Dummy pad rows carry OOB
        slots/tables, so their writes drop.

        ``window`` (paged only): gather/scatter only the table columns
        covering the first ``window`` rows — prefix-suffix walks with
        short histories stop paying O(max_seq) view traffic. The walk
        dispatcher uses the LARGEST configured decode window (one
        extra compile per (G, width)) and falls back to the full graph
        when a walker's history outgrows it."""
        fn = self._prefill_cache.get(("chunk", window))
        if fn is None:
            chunk_fn = self._prefill_chunk_fn

            if self._native_chunk:
                # native paged chunk: the model writes only the pages
                # the chunk spans through the block tables and attends
                # with the ragged chunk kernel — no gather/scatter of
                # a dense per-slot view, so a chunk's HBM traffic is
                # O(history + chunk), not O(pool allocation). The walk
                # is length-bounded by construction; windowed variants
                # exist only to bound the VIEW path's gather.
                native_fn = self._paged_chunk_fn

                def fused(params, tokens, kp, vp, tables, offsets,
                          chunk_lens, step, temps, top_ps, top_ks,
                          rng_key):
                    logits, kp, vp = native_fn(
                        params, tokens, kp, vp, tables, offsets,
                        chunk_lens)
                    key = jax.random.fold_in(rng_key, step)
                    toks = _sample_batch(logits, key, temps,
                                         top_ps, top_ks)
                    return toks, kp, vp
            elif self.config.kv_layout == "paged":
                from ..ops.paged_kv import gather_view, scatter_decode
                pg_rows = max(1, int(self.config.page_size))
                mp_w = None if window is None else -(-window // pg_rows)

                def fused(params, tokens, kp, vp, tables, offsets,
                          chunk_lens, step, temps, top_ps, top_ks,
                          rng_key):
                    width = tokens.shape[1]
                    tables = (tables if mp_w is None
                              else tables[:, :mp_w])
                    k_view = gather_view(kp, tables,
                                         dtype=self._kv_view_dtype)
                    v_view = gather_view(vp, tables,
                                         dtype=self._kv_view_dtype)
                    logits, k_view, v_view = chunk_fn(
                        params, tokens, k_view, v_view, offsets,
                        chunk_lens)
                    # write back exactly each row's chunk range; rows
                    # beyond chunk_len round-trip their gathered values
                    # and unallocated (dummy) pages drop (the scatter
                    # owns the pool dtype/quantization)
                    kp = scatter_decode(kp, tables, k_view,
                                        offsets, width)
                    vp = scatter_decode(vp, tables, v_view,
                                        offsets, width)
                    key = jax.random.fold_in(rng_key, step)
                    toks = _sample_batch(logits, key, temps,
                                         top_ps, top_ks)
                    return toks, kp, vp
            else:
                def fused(params, tokens, kc, vc, slots, offsets,
                          chunk_lens, step, temps, top_ps, top_ks,
                          rng_key):
                    # dummy rows: gather clips to a real slot (read-
                    # only, harmless), scatter drops their write-back
                    kcs = jnp.take(kc, slots, axis=1, mode="clip")
                    vcs = jnp.take(vc, slots, axis=1, mode="clip")
                    logits, kcs, vcs = chunk_fn(
                        params, tokens, kcs, vcs, offsets, chunk_lens)
                    kc = kc.at[:, slots].set(kcs.astype(kc.dtype),
                                             mode="drop")
                    vc = vc.at[:, slots].set(vcs.astype(vc.dtype),
                                             mode="drop")
                    key = jax.random.fold_in(rng_key, step)
                    toks = _sample_batch(logits, key, temps,
                                         top_ps, top_ks)
                    return toks, kc, vc
            fn = jax.jit(fused, donate_argnums=(2, 3))
            self._prefill_cache[("chunk", window)] = fn
        return fn

    def _chunk_window(self, needed: int, width: int) -> int | None:
        """Largest configured decode window, if it covers ``needed``
        rows AND the chunk width (warmup only compiles windowed
        variants for widths <= window — the gates must agree or the
        first wide-bucket suffix walk compiles on the serving path).
        Paged layout only; else None (full graph). The native chunk
        path needs no windows at all — the ragged kernel walks only
        the pages covering each row's history + chunk."""
        if self.config.kv_layout != "paged" or not self._cfg_windows \
                or self._native_chunk:
            return None
        w = self._cfg_windows[-1]
        return w if needed <= w and width <= w else None

    def _finish_walk(self, req: GenRequest, first: int) -> None:
        """A chunk walk covered its whole prompt: emit the first
        sampled token and open the slot for decode."""
        self._sched_dirty = True  # slot flips pending -> decoding
        req.pending_prefill = False
        if self.faults is not NO_FAULTS and \
                self.faults.trip("logit_corrupt", req.tenant):
            first = self._corrupt_token(first)
        now = time.time()  # gofrlint: allow(hot-path-purity) -- first-token boundary of a finished walk: once per request lifetime
        if req.first_token_at is None:  # not a preemption recompute
            req.first_token_at = now
            if self.metrics is not None:
                self.metrics.record_histogram(  # gofrlint: allow(hot-path-purity) -- TTFT observation at the walk's collect boundary, once per request lifetime
                    "app_chat_ttft_seconds", now - req.submitted_at,
                    exemplar_trace_id=req.trace[0] if req.trace else None)
        req.generated.append(first)
        req._emit(first)
        self.total_generated += 1
        self.lengths[req.slot] = len(req.prompt_tokens)
        if self._finished(req, first):
            self._retire(req.slot)

    @hot_path
    def _walk_chunks(self, pairs: list) -> None:
        """Admit (or resume) prompts through the chunk-with-history
        walk — prompts longer than the widest bucket, prefix-cache
        suffixes, preemption recomputes — BATCHED: walkers entering
        together share [G, width] device calls grouped by chunk width,
        so an admission wave of same-system-prompt suffixes costs
        ceil(G/prefill_batch) dispatches instead of G (each dispatch
        is a host round trip; over a device tunnel those dominate the
        wave). At most ``prefill_chunks_per_pass`` chunk rounds run
        per call; unfinished walks requeue so decode for every other
        slot interleaves instead of head-of-line blocking."""
        cfg = self.config
        paged = cfg.kv_layout == "paged"
        widest = max(self._usable_buckets)
        P = max(1, cfg.prefill_batch)
        walkers: list[GenRequest] = []
        if pairs:  # slots change occupancy/pending state below
            self._sched_dirty = True
        for req, slot in pairs:
            prompt = req.prompt_tokens
            if paged and -(-(len(prompt) + 1) // cfg.page_size) \
                    > self._n_pages:
                # an attached prefix (incref'd before this call) must
                # not leak into the slot's table for the next occupant
                self._release_pages(slot)
                if self.active[slot] is req:  # admit-time reservation
                    self.active[slot] = None
                req.prefill_offset = 0
                self._fail(req, "prompt exceeds kv pool")
                continue
            self._dev_last_reqs[slot] = None  # fresh/resumed occupant
            req.prefill_epoch += 1  # orphan any in-flight batch prefill
            self.active[slot] = req
            req.slot = slot
            req.pending_prefill = True
            self._note_admitted(req)
            if paged and req.admit_order < 0:
                req.admit_order = self._admit_seq
                self._admit_seq += 1
            walkers.append(req)
        if not walkers:
            return

        def owns_slot(r: GenRequest) -> bool:
            return (r.finished_at is None and r.slot >= 0
                    and self.active[r.slot] is r)

        start = time.perf_counter()
        dispatched: list[GenRequest] = []  # rows of the in-flight call
        try:
            fn = self._get_chunk_prefill()
            for _ in range(max(1, int(cfg.prefill_chunks_per_pass))):
                live = [r for r in walkers if owns_slot(r)
                        and r.prefill_offset < len(r.prompt_tokens)]
                if not live:
                    break
                # smallest bucket covering each walker's remainder —
                # the last chunk of a walk and prefix-cache suffixes
                # run a graph their own size, not the widest
                by_width: dict[int, list[GenRequest]] = {}
                for r in live:
                    remaining = len(r.prompt_tokens) - r.prefill_offset
                    width = next((b for b in self._usable_buckets
                                  if b >= remaining), widest)
                    by_width.setdefault(width, []).append(r)
                for width, group in by_width.items():
                    for i in range(0, len(group), P):
                        ready = []
                        for r in group[i:i + P]:
                            if not owns_slot(r):
                                continue  # a peer's headroom preempted it
                            if paged:
                                chunk_len = min(
                                    width,
                                    len(r.prompt_tokens) - r.prefill_offset)
                                rows = min(r.prefill_offset + chunk_len + 1,
                                           cfg.max_seq)
                                if not self._ensure_headroom(r.slot, rows):
                                    # the pool can't cover this walk even
                                    # after preempting younger requests:
                                    # release and restart from scratch
                                    # once pages free up
                                    self._release_pages(r.slot)
                                    self._dev_last_reqs[r.slot] = None
                                    self.active[r.slot] = None
                                    r.prefill_offset = 0
                                    self._requeue(r)
                                    continue
                            ready.append(r)
                        ready = [r for r in ready if owns_slot(r)]
                        if not ready:
                            continue
                        # pad to the full group: only (1, P) variants
                        # ever compile per width
                        G = 1 if len(ready) == 1 else P
                        tokens = np.zeros((G, width), np.int32)
                        offs = np.zeros(G, np.int32)
                        lens = np.zeros(G, np.int32)
                        temps = np.zeros(G, np.float32)
                        top_ps = np.ones(G, np.float32)
                        top_ks = np.zeros(G, np.int32)
                        if paged:  # dummy rows all-OOB: writes drop
                            slots_arg = np.full(
                                (G, self._pages_per_slot), self._n_pages,
                                np.int32)
                        else:
                            slots_arg = np.full(G, cfg.max_batch, np.int32)
                        for row, r in enumerate(ready):
                            chunk = r.prompt_tokens[
                                r.prefill_offset:r.prefill_offset + width]
                            tokens[row, :len(chunk)] = chunk
                            offs[row] = r.prefill_offset
                            lens[row] = len(chunk)
                            temps[row] = r.params.temperature
                            top_ps[row] = r.params.top_p
                            top_ks[row] = r.params.top_k
                            slots_arg[row] = self._tables[r.slot] \
                                if paged else r.slot
                        self._rng_step += 1
                        dispatched = ready
                        cw = self._chunk_window(int((offs + lens).max()),
                                                width)
                        call = (self._get_chunk_prefill(cw) if cw
                                else fn)
                        self._note_dispatch_shape("chunk", width, G, cw)
                        c0 = time.perf_counter()
                        self.goodput.note_dispatch(c0)
                        w0 = time.time()  # gofrlint: allow(hot-path-purity) -- span timestamps use wall clock; once per chunk dispatch (the walk is synchronous by design)
                        toks, self.k_cache, self.v_cache = call(
                            self.params, jnp.asarray(tokens),
                            self.k_cache, self.v_cache,
                            jnp.asarray(slots_arg), jnp.asarray(offs),
                            jnp.asarray(lens), np.int32(self._rng_step),
                            jnp.asarray(temps), jnp.asarray(top_ps),
                            jnp.asarray(top_ks),
                            self._prefill_base_key)
                        self.stats["prefill_calls"] += 1
                        if self._native_chunk:
                            self._note_view_avoided(G)
                        c_dur = time.perf_counter() - c0
                        chunk_sig = self._sig_str("chunk", width, G, cw)
                        if self.recorder.enabled:
                            self.recorder.record_pass(
                                "prefill_chunk", rows=len(ready),
                                width=width, sig=chunk_sig,
                                dur=round(c_dur, 6),
                                view_avoided=self._native_chunk,
                                queue_depth=self.waiting.qsize())
                        # goodput: a walker with a first token already
                        # emitted is re-prefilling KV it computed once
                        # (preemption recompute); pad rows are padding
                        recomp = sum(1 for r in ready
                                     if r.first_token_at is not None
                                     or r.recovered)
                        self.goodput.add_prefill(
                            "prefill_chunk", c_dur, G,
                            len(ready) - recomp, recomp)
                        # cost observatory: same duration the ledger
                        # just billed; tokens = the compiled shape's
                        # G x width positions (what the graph costs)
                        self._note_pass_cost(
                            "chunk", chunk_sig, c_dur,
                            rows=len(ready), tokens=G * width)
                        w1 = time.time()  # gofrlint: allow(hot-path-purity) -- span timestamps use wall clock; once per chunk dispatch
                        for r in ready:
                            r.device_s += c_dur / len(ready)
                            if r.first_token_at is not None or r.recovered:
                                r.waste_recompute_s += c_dur / len(ready)
                            self._req_event(
                                r, "prefill", w0, w1,
                                {"bucket": width,
                                 "offset": int(r.prefill_offset),
                                 "view_avoided": self._native_chunk})
                        toks_np = None
                        for row, r in enumerate(ready):
                            r.prefill_offset += int(lens[row])
                            if r.prefill_offset >= len(r.prompt_tokens):
                                if toks_np is None:
                                    toks_np = np.asarray(toks)  # gofrlint: allow(hot-path-purity) -- this sync IS the walk's collect: finished walkers' first tokens cross to host here
                                self._finish_walk(r, int(toks_np[row]))
                        dispatched = []
        except Exception as exc:
            # fail the rows of the crashing dispatch; walkers that
            # were not in it keep their state and requeue below
            for r in (dispatched or
                      [w for w in walkers if owns_slot(w)
                       and w.pending_prefill]):
                if r.slot >= 0 and self.active[r.slot] is r:
                    self.active[r.slot] = None
                    if paged:
                        self._release_pages(r.slot)
                r.pending_prefill = False
                self._fail(r, str(exc))
            if self.logger:
                self.logger.error(f"chunked prefill failed: {exc!r}")  # gofrlint: allow(hot-path-purity) -- failure path: the chunk dispatch raised; rows are being failed, not served
            self._recover_lost_cache(exc)
        self._note_prefill_span(start)
        self._update_kv_watermarks()
        self._note_device_idle()
        for r in walkers:  # more chunks next pass
            if owns_slot(r) and r.pending_prefill \
                    and r.prefill_offset < len(r.prompt_tokens):
                self._requeue(r)

    def _free_slot(self) -> int:
        for i, r in enumerate(self.active):
            if r is None:
                return i
        return -1

    # ------------------------------------------------------ paged alloc
    def _decref_page(self, page: int) -> None:
        self._page_refs[page] -= 1
        if self._page_refs[page] <= 0:
            self._page_refs[page] = 0
            self._free_pages.append(page)

    @hot_path_boundary(
        "pool-pressure eviction event; runs only after an allocation already missed")
    def _evict_prefix_entries(self, pages_needed: int) -> None:
        """Drop LRU prefix-cache entries (insertion order IS the LRU
        order — touches reinsert) until the free list can cover
        ``pages_needed`` or the cache is empty."""
        while len(self._free_pages) < pages_needed and self._prefix_cache:
            key = next(iter(self._prefix_cache))
            pages = self._prefix_cache.pop(key)
            self.stats["prefix_evictions"] += 1
            if self.metrics is not None:
                self.metrics.increment_counter("app_engine_prefix_evictions")
            count = self._prefix_lens.get(len(key), 0) - 1
            if count > 0:
                self._prefix_lens[len(key)] = count
            else:
                self._prefix_lens.pop(len(key), None)
            self._cached_pages -= len(pages)
            for page in pages:
                self._decref_page(page)
        self._prefix_digest_dirty = True

    def _alloc_pages(self, slot: int, rows: int) -> bool:
        """Grow ``slot``'s block table to cover ``rows`` logical rows;
        False when the free list cannot even after evicting cached
        prefixes (caller preempts or defers)."""
        pg = self.config.page_size
        need = min(-(-rows // pg), self._pages_per_slot)
        have = int(self._slot_pages[slot])
        if need <= have:
            return True
        if need - have > len(self._free_pages):
            self._evict_prefix_entries(need - have)
        if need - have > len(self._free_pages):
            return False
        for i in range(have, need):
            page = self._free_pages.pop()
            self._tables[slot, i] = page
            self._page_refs[page] = 1
        self._slot_pages[slot] = need
        self._tables_dirty = True
        return True

    def _release_pages(self, slot: int) -> None:
        if self.config.kv_layout != "paged":
            return  # slot layout: kv rows are per-slot, nothing pooled
        n = int(self._slot_pages[slot])
        if n:
            self._tables_dirty = True
        for i in range(n):
            self._decref_page(int(self._tables[slot, i]))
        self._tables[slot, :] = self._n_pages
        self._slot_pages[slot] = 0

    # ------------------------------------------------------ prefix cache
    def _probe_prefix(self, prompt: list[int]) -> int:
        """-> covered rows of the longest cached page-aligned prefix
        of ``prompt`` (0 = miss). Always leaves >= 1 suffix token so
        the first sample has a position to come from. Only lengths
        that actually exist in the cache are tested."""
        if not self._prefix_enabled or not self._prefix_cache:
            return 0
        limit = len(prompt) - 1
        for length in sorted(self._prefix_lens, reverse=True):
            if length <= limit \
                    and tuple(prompt[:length]) in self._prefix_cache:
                return length
        return 0

    def _attach_prefix(self, slot: int, prompt: list[int],
                       covered: int) -> None:
        """Point ``slot``'s table at the cached pages for
        ``prompt[:covered]`` (increfs them) — the slot starts with the
        shared prefix KV already in place."""
        key = tuple(prompt[:covered])
        pages = self._prefix_cache.pop(key)   # LRU touch: reinsert at
        self._prefix_cache[key] = pages       # the fresh end
        for i, page in enumerate(pages):
            self._tables[slot, i] = page
            self._page_refs[page] += 1
        self._slot_pages[slot] = len(pages)
        self._tables_dirty = True
        self.stats["prefix_hits"] += 1

    def _register_prefix(self, slot: int, req: GenRequest) -> None:
        """At retire: pin the page-aligned prompt prefix for reuse.
        Decode wrote only past the prompt, so these pages hold exactly
        the prefix KV."""
        cfg = self.config
        if not self._prefix_enabled:
            return
        pg = cfg.page_size
        prompt = req.prompt_tokens
        aligned = ((len(prompt) - 1) // pg) * pg
        n = aligned // pg
        if n < 1 or int(self._slot_pages[slot]) < n:
            return
        # when the full prefix exceeds the budget, pin the longest
        # aligned prefix that fits — partial reuse beats none
        n = min(n, self._prefix_budget - self._cached_pages)
        if n < 1:
            return
        aligned = n * pg
        key = tuple(prompt[:aligned])
        if key in self._prefix_cache:
            return
        pages = [int(self._tables[slot, i]) for i in range(n)]
        for page in pages:
            self._page_refs[page] += 1
        self._prefix_cache[key] = pages
        self._prefix_lens[aligned] = self._prefix_lens.get(aligned, 0) + 1
        self._cached_pages += n
        self._prefix_digest_dirty = True

    @hot_path_boundary(
        "event-driven eviction; its host work is amortized over the recompute prefill it schedules, not paid per pass")
    def _preempt(self, slot: int) -> None:
        """Evict a request, keeping its stream open: pages return to
        the pool now, the request re-enters the queue with prompt =
        original prompt + everything generated, and the next prefill
        recomputes its KV and samples its next token — vLLM-style
        preemption-by-recompute, which on TPU costs one extra bucketed
        prefill instead of a cache swap to host memory."""
        req = self.active[slot]
        if req is None:
            return
        self.stats["preemptions"] += 1
        if self.metrics is not None:
            self.metrics.increment_counter("app_engine_preemptions")
        _now = time.time()
        self._req_event(req, "preempt", _now, _now,
                        {"slot": slot, "generated": len(req.generated)})
        # the request re-enters by recompute with host-side state; a
        # surviving _dev_last entry from its old life in this slot must
        # never match it again (its generated[] diverges from the
        # discarded in-flight pass), and neither may an in-flight batch
        # prefill's first token (epoch bump) — the recompute re-admits
        # through whichever prefill path fits its new prompt
        self._dev_last_reqs[slot] = None
        self._sched_dirty = True
        req.pending_prefill = False
        req.prefill_epoch += 1
        self.active[slot] = None
        self.lengths[slot] = 0
        self._release_pages(slot)
        # the continuation IS the cache content at eviction (<= max_seq
        # rows by construction): re-prefilling it reproduces the exact
        # token positions, so greedy outputs cannot diverge. Only the
        # widest prefill bucket truncates (divergence then unavoidable
        # without chunked prefill — requires buckets narrower than
        # max_seq, non-default).
        req.prompt_tokens = list(req.prompt_tokens) + list(req.generated)
        limit = min(max(self._usable_buckets), self.config.max_seq)
        if self._prefill_chunk_fn is not None:
            # chunked prefill re-admits any continuation the cache can
            # hold — no bucket truncation
            limit = self.config.max_seq
        if len(req.prompt_tokens) > limit:
            req.prompt_tokens = req.prompt_tokens[-limit:]
        # any chunk/suffix progress is gone with the pages: restart
        # from zero (a cached prefix can re-attach at re-admission)
        req.prefill_offset = 0
        self._requeue(req)

    def _ensure_headroom(self, slot: int, rows: int) -> bool:
        """Allocate pages for ``rows`` logical rows, preempting the
        newest *younger* active request as needed — an older request
        (closer to completion) is never evicted for a newer one. False
        when no younger victim remains and the pool still cannot cover
        this slot (the caller preempts ``slot`` itself)."""
        mine = self.active[slot].admit_order
        while not self._alloc_pages(slot, rows):
            victims = [i for i, r in enumerate(self.active)
                       if r is not None and i != slot
                       and r.admit_order > mine]
            if not victims:
                return False
            self._preempt(max(
                victims, key=lambda i: self.active[i].admit_order))
        return True

    @hot_path_boundary(
        "starvation-triggered preemption decision at the admission boundary; rate-capped by the scheduler, not steady-state")
    def _sched_starvation_preempt(self) -> bool:
        """When the scheduler reports interactive starvation with the
        batch full, preempt the newest background slot through the
        existing preemption-by-recompute machinery (the
        ``preempt_recompute`` goodput ledger prices it) and route the
        victim back through the scheduler instead of the ``_requeued``
        fast lane — which bypasses admission and would hand the freed
        slot straight back to the victim."""
        sched = self.waiting
        if not hasattr(sched, "starving_interactive") \
                or not sched.starving_interactive():
            return False
        victims = [i for i, r in enumerate(self.active)
                   if r is not None and not r.pending_prefill
                   and not r.cancelled
                   and getattr(r, "lane", None) == "background"]
        if not victims:
            return False
        # newest victim loses; the slot layout never stamps
        # admit_order (-1 everywhere), so fall back to submit time
        slot = max(victims, key=lambda i: (self.active[i].admit_order,
                                           self.active[i].submitted_at))
        req = self.active[slot]
        self._preempt(slot)
        if id(req) in self._requeued_set:
            self._requeued_set.discard(id(req))
            self._requeued = [r for r in self._requeued if r is not req]
            sched.readmit(req)  # head of its background sub-queue
        if hasattr(sched, "note_preempted"):
            sched.note_preempted()
        return True

    @hot_path_boundary(
        "event-driven backpressure bookkeeping (admission races, pool pressure), not steady-state")
    def _requeue(self, req: GenRequest) -> None:
        if id(req) not in self._requeued_set:
            self._requeued_set.add(id(req))
            self._requeued.append(req)
            self.stats["requeues"] += 1
            if self.metrics is not None:
                self.metrics.increment_counter("app_engine_requeues")

    def _alloc_head_major(self, n_pages: int, page: int):
        """One head-major pool pair [L, Hkv, Np, pg, hd] in the MODEL
        dtype. Cache constructors that know the layout build it
        directly (``head_major=True``); older ones return
        [L, Np, pg, Hkv, hd] and pay a one-off transpose."""
        import inspect

        from ..ops.paged_kv import pool_from_cache_shape
        try:
            aware = "head_major" in inspect.signature(
                self._make_cache).parameters
        except (TypeError, ValueError):  # builtins/partials: no sig
            aware = False
        if aware:
            # signature-probed, NOT try/except TypeError: an error
            # raised INSIDE an aware constructor must surface as
            # itself, not silently re-run the legacy path
            return self._make_cache(n_pages, page, head_major=True)
        kc, vc = self._make_cache(n_pages, page)
        return pool_from_cache_shape(kc), pool_from_cache_shape(vc)

    def _alloc_pool(self, page: int):
        """Allocate the paged pool (ops/paged_kv.py: the kernel's
        per-(head, page) DMA must slice only untiled leading dims).
        ``kv_dtype="int8"`` re-lays the zero allocation as the
        quantized ``{"q", "s"}`` pytree — every later write quantizes
        inside the jitted scatters, so this is the only place the
        representation is chosen."""
        kc, vc = self._alloc_head_major(self._n_pages, page)
        # the model dtype the view fallback dequantizes back to
        leaf = jax.tree_util.tree_leaves(kc)[0]
        self._kv_view_dtype = leaf.dtype
        if self.config.kv_dtype == "int8":
            from ..ops.paged_kv import quantize_pool
            kc, vc = quantize_pool(kc), quantize_pool(vc)
        return kc, vc

    def _sized_pool_pages(self, page: int, base_pages: int) -> int:
        """Resolve the pool's page count from its BYTE budget. The
        budget is ``kv_pool_bytes`` when set, else ``base_pages`` at
        the native per-page cost — so flipping ``kv_dtype`` to int8
        keeps the footprint and roughly doubles the pages. The bf16
        default with no explicit budget short-circuits to
        ``base_pages`` exactly (no probe allocation, no rounding)."""
        cfg = self.config
        if cfg.kv_dtype == "bf16" and cfg.kv_pool_bytes is None:
            return max(1, int(base_pages))
        from ..ops.paged_kv import pool_row_bytes, pool_shape
        probe_k, _ = self._alloc_head_major(1, page)
        pg = pool_shape(probe_k)[3]
        native_page = 2 * pg * pool_row_bytes(probe_k)   # K + V
        if cfg.kv_dtype == "int8":
            from ..ops.paged_kv import quantize_pool
            per_page = 2 * pg * pool_row_bytes(quantize_pool(probe_k))
        else:
            per_page = native_page
        budget = (cfg.kv_pool_bytes if cfg.kv_pool_bytes is not None
                  else base_pages * native_page)
        return max(1, int(budget) // per_page)

    def _kv_lost(self) -> bool:
        """True when a failed donated dispatch consumed either cache —
        pytree-aware (a quantized pool is multiple leaves)."""
        return any(leaf.is_deleted() for leaf in
                   jax.tree_util.tree_leaves((self.k_cache,
                                              self.v_cache)))

    @hot_path_boundary(
        "device-loss recovery path: the engine is already off the fast path when this runs")
    def _recover_lost_cache(self, exc: BaseException) -> None:
        """A failed prefill may have consumed the donated caches; if
        so every active slot's KV went with them — fail those streams
        honestly and stand up fresh caches so the engine keeps serving
        new requests."""
        if not self._kv_lost():
            return
        cfg = self.config
        for i, other in enumerate(self.active):
            if other is not None:
                self.active[i] = None
                self._fail(other, f"kv cache lost to failed prefill: "
                                  f"{exc}")
        self.lengths[:] = 0
        self._sched_dirty = True
        self._tables_dirty = True
        if cfg.kv_layout == "paged":  # same geometry, pristine allocator
            self.k_cache, self.v_cache = self._alloc_pool(
                max(1, int(cfg.page_size)))
            self._free_pages = list(range(self._n_pages))
            self._tables[:] = self._n_pages
            self._slot_pages[:] = 0
            self._page_refs[:] = 0
            self._prefix_cache.clear()
            self._prefix_lens.clear()
            self._cached_pages = 0
            self._prefix_digest_dirty = True
        else:
            self.k_cache, self.v_cache = self._make_cache(
                cfg.max_batch, cfg.max_seq)

    def _sig(self, *parts: Any) -> tuple:
        """Sentinel shape signature for a dispatch site. A non-default
        ``kv_dtype`` changes every compiled graph on the paged path
        (quantized pools are a different pytree), so it is folded into
        the signature — bf16 signatures stay seed-identical."""
        if self.config.kv_dtype != "bf16":
            return (*parts, self.config.kv_dtype)
        return parts

    @hot_path_boundary(
        "O(1) host set probe per dispatch; the metric/log fire only on an anomalous post-warmup recompile")
    def _note_dispatch_shape(self, *sig: Any) -> None:
        """Recompile-sentinel hook at every device dispatch site: a
        novel post-warmup shape signature means XLA is lowering a new
        graph on the serving path — count it and WARN once with the
        offending shape (O(1) host set lookup otherwise)."""
        sig = self._sig(*sig)
        if not self.sentinel.dispatch(sig):
            return
        self.stats["recompiles"] += 1
        if self.metrics is not None:
            self.metrics.increment_counter("app_engine_recompiles")
        if self.logger is not None:
            self.logger.warn(
                "unexpected post-warmup recompile: dispatch shape was "
                "never compiled during warmup",
                signature="/".join(str(p) for p in sig))
        self.events.emit(
            "obs.recompile", severity="warn",
            signature="/".join(str(p) for p in sig))

    def _sig_str(self, *parts: Any) -> str:
        """The sentinel's rendered signature string — the join key the
        cost table, flight-recorder pass records, /debug/costs and the
        fleet federation all share."""
        return "/".join(str(p) for p in self._sig(*parts))

    @hot_path_boundary(
        "cost-model fold at the collect boundary: host float EWMA "
        "updates over the pass duration the collect already measured; "
        "the event/metric/WARN/incident and the profiler arm fire only "
        "on a rare drift-episode entry")
    def _note_pass_cost(self, kind: str, sig_str: str, dur: float, *,
                        rows: int = 0, tokens: int = 0) -> None:
        """Feed one collected pass to the cost observatory. Called at
        every collect site with the SAME duration the goodput ledger
        bills, so /debug/costs conserves against busy seconds. A drift
        episode entry (CostModel.observe returns a record once per
        episode) emits obs.cost_drift, WARNs once, bumps
        app_engine_cost_drift{kind}, arms the autoprofiler and opens a
        cost_drift incident bundle carrying the capture dir. The
        integrity plane's probe cadence ticks here too — one int
        compare per pass when probing is off, a background-lane submit
        when it fires (pass-count-driven, never wall clock)."""
        self.autoprof.note_pass()
        probe = self.integrity.note_pass()
        if probe is not None:
            self._launch_probe(probe)
        if not self.costs.enabled:
            return
        skew = 0.0
        if self.faults is not NO_FAULTS \
                and self.faults.trip("cost_skew", sig_str):
            # deterministic drift induction: inflate the OBSERVED
            # duration only — no sleep, no token perturbation, greedy
            # outputs stay bit-identical (serving/faults.py)
            skew = self.faults.payload("cost_skew")
        drift = self.costs.observe(kind, sig_str, dur, rows=rows,
                                   tokens=tokens, skew_s=skew)
        if drift is None:
            return
        self.stats["cost_drifts"] += 1
        if self.metrics is not None:
            self.metrics.increment_counter("app_engine_cost_drift",
                                           kind=kind)
        if self.logger is not None:
            self.logger.warn(
                "pass cost drifted off its sealed baseline",
                signature=sig_str, ewma_s=drift["ewma_s"],
                baseline_s=drift["baseline_s"], ratio=drift["ratio"])
        self.events.emit("obs.cost_drift", severity="warn",
                         signature=sig_str, pass_kind=kind,
                         ratio=drift["ratio"], ewma_s=drift["ewma_s"],
                         baseline_s=drift["baseline_s"])
        capture = self.autoprof.arm(
            "cost_drift", f"pass cost drift: {sig_str}")
        self.incidents.trigger(
            "cost_drift", cause=f"pass cost drift: {sig_str}",
            attrs={**drift,
                   "autoprof_dir": (capture or {}).get("dir")})

    def cost_state(self) -> dict:
        """The per-model ``GET /debug/costs`` payload: the full cost
        table plus the autoprofiler's state — also an incident-bundle
        source, so every bundle names which kernel class got slower."""
        return {"costs": self.costs.state(),
                "autoprof": self.autoprof.state()}

    def integrity_state(self) -> dict:
        """The per-model ``GET /debug/integrity`` payload: digest-fold
        totals, golden corpus, probe results and the mismatch-episode
        latch — also an incident-bundle source, so an integrity bundle
        names which golden prompt diverged."""
        return self.integrity.state()

    def _launch_probe(self, entry) -> None:
        """Submit one golden canary through the normal admission path
        on the scheduler's BACKGROUND lane — a probe must never crowd
        out interactive traffic (it yields to it by lane policy), and
        it must exercise exactly the serving path users ride, or a
        clean probe would prove nothing. The GenRequest is built
        directly (not via ``submit``) so the probe marker is stamped
        before any admission refusal can retire the request."""
        p = entry.params
        params = SamplingParams(temperature=p["temperature"],
                                top_p=p["top_p"], top_k=p["top_k"],
                                max_new_tokens=p["max_new_tokens"])
        req = GenRequest(
            prompt_tokens=self._clamp_prompt(list(entry.prompt_tokens),
                                             params.max_new_tokens),
            params=params, tenant="_integrity", lane="background")
        req.probe = entry.id
        req.probe_expected = entry.digest
        if self._draining or not self.waiting.put(req):
            # refused at admission (drain window, queue_full, shed):
            # release the in-flight latch — the cadence retries later
            self.integrity.probe_aborted()

    @hot_path_boundary(
        "integrity fold at the retire boundary: one blake2b over token "
        "ids the collects already emitted plus host dict bookkeeping "
        "for probe results; the WARN/event/metric/incident fire only "
        "on a rare probe-mismatch episode entry — runs once per "
        "request, never per pass")
    def _note_integrity(self, req: GenRequest) -> None:
        """Feed one retired request to the integrity plane: stamp the
        output fingerprint (flight recorder and workload records pick
        it up downstream in ``_finalize_obs``), re-price golden-probe
        device time to the ``integrity_probe`` waste cause, emit the
        probe's ``obs.integrity`` event, and on a mismatch episode
        entry (IntegrityPlane.fold returns a record once per episode)
        WARN once, bump ``app_engine_integrity_failures{kind}`` and
        open an incident bundle."""
        mismatch = self.integrity.fold(req)
        if req.probe:
            # canary device time is correctness verification, not
            # serving goodput — move it to the conserving ledger's
            # integrity_probe cause (busy unchanged)
            self.goodput.reprice_probe(req.device_s)
            self.integrity.probe_device_s += req.device_s
            rec = self.integrity.last.get(req.probe)
            if rec is not None and req.error is None \
                    and not req.cancelled:
                self.events.emit(
                    "obs.integrity",
                    severity="info" if rec["ok"] else "warn",
                    golden_id=req.probe, digest=rec["digest"],
                    expected=req.probe_expected, ok=rec["ok"],
                    seq=rec["seq"])
        if mismatch is None:
            return
        self.stats["integrity_failures"] += 1
        if self.metrics is not None:
            self.metrics.increment_counter(
                "app_engine_integrity_failures", kind="probe_mismatch")
        if self.logger is not None:
            self.logger.warn(
                "golden probe digest mismatch: this host's greedy "
                "output diverged from its sealed expectation",
                golden_id=mismatch["golden_id"],
                digest=mismatch["digest"],
                expected=mismatch["expected"])
        self.incidents.trigger(
            "integrity",
            cause=f"golden probe digest mismatch: "
                  f"{mismatch['golden_id']}",
            attrs=dict(mismatch))

    def _corrupt_token(self, token: int) -> int:
        """The ``logit_corrupt`` fault site's host-visible effect: the
        device's sampled token is replaced deterministically, as a
        corrupted logit row would have sampled a different id (the
        real logits never cross to the host — the zero-h2d invariant —
        so the collected token IS where device corruption becomes
        observable). The perturbed id never lands on ``eos_id``:
        stream lengths are preserved, nothing crashes, only digests
        diverge."""
        alt = token ^ 1
        if alt == self.config.eos_id:
            alt = token ^ 2
        return alt

    def _note_device_idle(self) -> None:
        """Goodput bubble tracking: a synchronous collect finished and
        no dispatched pass remains in flight — from the host's view the
        device is idle. Record whether work was waiting (queued,
        requeued, or active slots mid-generation) so the gap until the
        next dispatch can be classified as bubble waste."""
        if not self.goodput.enabled:
            return
        if self._pending or self._pending_prefills:
            return  # a pass is still in flight: the device isn't idle
        backlog = (bool(self._requeued) or self.waiting.qsize() > 0
                   or any(r is not None and not r.pending_prefill
                          for r in self.active))
        self.goodput.note_pass_end(time.perf_counter(), backlog)

    def _req_event(self, req: GenRequest, name: str, t0: float,
                   t1: float, attrs: dict | None = None) -> None:
        """Append a lifecycle event (bounded) — spans and the flight
        recorder's request log assemble from these at retire."""
        if len(req.events) < 64:
            req.events.append((name, t0, t1, attrs or {}))

    @hot_path_boundary(
        "admission boundary: closes the queue-wait span exactly once per request")
    def _note_admitted(self, req: GenRequest) -> None:
        """First slot assignment: the queue span ends here. Recompute
        re-admissions (preemption, pool-exhaustion restarts) keep the
        original admission time — the queue wait was paid once."""
        if req.admitted_at is None:
            now = time.time()
            req.admitted_at = now
            if self.metrics is not None:
                self.metrics.record_histogram(
                    "app_chat_queue_seconds", now - req.submitted_at,
                    exemplar_trace_id=req.trace[0] if req.trace else None)

    def _finalize_obs(self, req: GenRequest) -> None:
        """Terminal observability for a request (exactly once): latency
        histograms, the flight-recorder request log, and the engine.*
        span assembly. All host arithmetic over timestamps already
        collected — called before the terminal None is emitted so a
        drained stream implies the spans are exported."""
        if req._obs_done:
            return
        req._obs_done = True
        end = req.finished_at or time.time()
        exemplar = req.trace[0] if req.trace else None
        n = len(req.generated)
        ttft_s = ((req.first_token_at - req.submitted_at)
                  if req.first_token_at is not None else None)
        tpot_s = ((end - req.first_token_at) / (n - 1)
                  if req.first_token_at is not None and n > 1 else None)
        e2e_s = end - req.submitted_at
        if self.metrics is not None and req.error is None \
                and not req.cancelled:
            self.metrics.record_histogram("app_chat_e2e_seconds", e2e_s,
                                          exemplar_trace_id=exemplar)
            if tpot_s is not None:
                self.metrics.record_histogram(
                    "app_chat_tpot_seconds", tpot_s,
                    exemplar_trace_id=exemplar)
        if self.usage_ledger is not None:
            status = ("cancelled" if req.cancelled
                      else "error" if req.error is not None else "ok")
            queue_s = ((req.admitted_at - req.submitted_at)
                       if req.admitted_at is not None else 0.0)
            self.usage_ledger.record(
                tenant=req.tenant or "anonymous", status=status,
                prompt_tokens=len(req.prompt_tokens),
                completion_tokens=n, queue_s=queue_s, e2e_s=e2e_s,
                device_s=req.device_s,
                waste_recompute_s=req.waste_recompute_s,
                waste_spec_s=req.waste_spec_s, t=end)
        if self.slo is not None and not req.cancelled \
                and getattr(req, "reject", None) is None \
                and not req.probe:
            # golden canary probes are synthetic traffic: a corrupted
            # host's probes must alarm the INTEGRITY plane, not burn
            # the availability error budget into a shed episode.
            # Likewise, typed admission refusals (429/shed) are policy, not
            # service failures: counting them as SLO errors would let
            # one tenant's flood burn the global budget and trip the
            # shedder against everyone else (a rejection -> burn ->
            # shed feedback loop). They are priced by
            # app_sched_rejections instead.
            good = self.slo.judge(error=req.error, ttft_s=ttft_s,
                                  tpot_s=tpot_s, e2e_s=e2e_s)
            self.slo.record(good, t=end)
            # the same verdict feeds the scheduler's per-tenant burn
            # column (the /debug/scheduler victim/offender view)
            if hasattr(self.waiting, "note_retire"):
                self.waiting.note_retire(req.tenant, good, t=end)
        if self.integrity.enabled:
            # digest fold BEFORE the recorder/workload writes below,
            # so both records carry the fingerprint
            self._note_integrity(req)
        if self.recorder.enabled:
            from .observability import request_summary
            self.recorder.record_request(request_summary(req))
        if self.workload.capturing and not req.probe:
            # golden probes stay out of the capture ring: the replay
            # corpus (and any golden set sealed from it) must hold
            # real traffic, not the canaries checking it
            self.workload.record(req)
        if self.tracer is not None and req.trace is not None:
            try:
                from .observability import emit_engine_spans
                emit_engine_spans(self.tracer, req)
            except Exception:  # tracing must never take down a stream
                pass

    @hot_path_boundary(
        "terminal error path; observability assembly mirrors _retire")
    def _fail(self, req: GenRequest, error: str) -> None:
        req.error = error
        req.finished_at = time.time()
        self._finalize_obs(req)
        req._emit(None)

    @hot_path_boundary(
        "lifecycle refusal path (drain/crash window), not steady-state")
    def _refuse(self, req: GenRequest, code: str, detail: str, *,
                retry_after_s: float = 1.0) -> None:
        """Fail ``req`` with a typed, machine-readable reject — the
        same :class:`~.scheduler.SchedReject` shape the scheduler
        stamps for policy refusals, so the handlers' structured-error
        path (503 + ``Retry-After`` + ``details.code``, OpenAI-compat
        included) covers lifecycle refusals (drain, crash window, KV
        exhaustion) too. Typed rejects are policy, not service
        failures: ``_finalize_obs`` keeps them out of the SLO burn."""
        from .scheduler import SchedReject
        req.reject = SchedReject(code=code, tenant=req.tenant,
                                 retry_after_s=retry_after_s,
                                 detail=detail)
        self._fail(req, req.reject.message)

    def _admit_batch(self, reqs: list[GenRequest]) -> None:
        """Admit a burst: group by prompt bucket, prefill each group in
        chunks of ``prefill_batch`` with one device call per chunk.
        Prompts wider than every bucket take the chunked path."""
        by_bucket: dict[int, list[GenRequest]] = {}
        walkers: list = []
        widest = max(self._usable_buckets)

        def reserve_for_walk(req: GenRequest, slot: int) -> None:
            # hold the slot NOW: walkers dispatch together after the
            # bucket groups, and _free_slot must not hand their slot
            # to a later request in this same batch
            self.active[slot] = req
            req.slot = slot
            walkers.append((req, slot))

        for req in reqs:
            if req.finished_at is not None:
                continue  # failed/retired while queued
            if (not req.pending_prefill and req.slot >= 0
                    and self.active[req.slot] is req):
                continue  # already serving (stale duplicate entry)
            if req.pending_prefill:  # resuming a chunk walk
                if req.slot >= 0 and self.active[req.slot] is req:
                    walkers.append((req, req.slot))
                elif req.finished_at is None:
                    # slot lost (pool-exhaustion restart / preemption):
                    # re-admit from scratch
                    slot = self._free_slot()
                    if slot < 0:
                        self._requeue(req)
                    else:
                        reserve_for_walk(req, slot)
                continue
            if self._prefix_enabled and req.prefill_offset == 0:
                covered = self._probe_prefix(req.prompt_tokens)
                if covered:
                    slot = self._free_slot()
                    if slot < 0:
                        self._requeue(req)
                    else:
                        # shared prefix KV attaches; only the suffix
                        # computes, through the chunk-with-history walk
                        self._attach_prefix(slot, req.prompt_tokens,
                                            covered)
                        req.prefill_offset = covered
                        _now = time.time()
                        self._req_event(req, "prefill", _now, _now,
                                        {"prefix_hit": True,
                                         "covered_rows": covered})
                        reserve_for_walk(req, slot)
                    continue
            if (self._prefill_chunk_fn is not None
                    and len(req.prompt_tokens) > widest):
                slot = self._free_slot()
                if slot < 0:  # raced out of slots; try next pass
                    self._requeue(req)
                else:
                    reserve_for_walk(req, slot)
                continue
            bucket = self._bucket_for(len(req.prompt_tokens))
            by_bucket.setdefault(bucket, []).append(req)
        P = max(1, self.config.prefill_batch)
        for bucket, group in by_bucket.items():
            for i in range(0, len(group), P):
                self._prefill_group(bucket, group[i:i + P])
        if walkers:
            # after the bucket dispatches: their device work overlaps
            # the walk's synchronous rounds
            self._walk_chunks(walkers)
        # below the pipelining threshold the decode pass these prefills
        # would hide behind is cheap and TTFT is the scarce resource —
        # sync first tokens out now instead of after the next pass
        if self._pending_prefills and self._pipeline_depth() == 0:
            self._collect_prefills()

    @hot_path
    def _prefill_group(self, bucket: int, chunk: list[GenRequest]) -> None:
        cfg = self.config
        paged = cfg.kv_layout == "paged"
        placed: list[GenRequest] = []
        for req in chunk:
            slot = self._free_slot()
            if slot < 0:  # raced out of slots; back to the requeue list
                self._requeue(req)
                continue
            if paged:
                pg = cfg.page_size
                if -(-(len(req.prompt_tokens) + 1) // pg) > self._n_pages:
                    # can never fit, no matter what retires
                    self._fail(req, "prompt exceeds kv pool")
                    continue
                if not self._alloc_pages(slot, len(req.prompt_tokens) + 1):
                    # pool busy: requeue and wait for retires to free
                    # pages
                    self._requeue(req)
                    continue
                if req.admit_order < 0:
                    req.admit_order = self._admit_seq
                    self._admit_seq += 1
            req.slot = slot
            self._dev_last_reqs[slot] = None  # fresh occupant: host token
            self.active[slot] = req       # reserve before the next scan
            self._note_admitted(req)
            placed.append(req)
        if not placed:
            return

        # smallest compiled group size that fits: sparse traffic pays
        # for a [1..2, bucket] forward, bursts amortise the full width
        P = next(g for g in self._group_sizes() if g >= len(placed))
        self._rng_step += 1
        self._note_dispatch_shape("prefill", bucket, P)
        start = time.perf_counter()
        self.goodput.note_dispatch(start)
        try:
            tokens = np.zeros((P, bucket), np.int32)
            kv_len = np.ones(P, np.int32)                # dummy rows: length 1
            if paged:  # per-row block tables; dummy rows all-OOB: dropped
                slots = np.full((P, self._pages_per_slot), self._n_pages,
                                np.int32)
            else:      # slot ids; dummy rows OOB: dropped
                slots = np.full(P, cfg.max_batch, np.int32)
            temps = np.zeros(P, np.float32)
            top_ps = np.ones(P, np.float32)
            top_ks = np.zeros(P, np.int32)
            for row, req in enumerate(placed):
                n = len(req.prompt_tokens)
                tokens[row, :n] = req.prompt_tokens
                kv_len[row] = n
                slots[row] = self._tables[req.slot] if paged else req.slot
                temps[row] = req.params.temperature
                top_ps[row] = req.params.top_p
                top_ks[row] = req.params.top_k

            prefill = self._get_prefill(bucket, P)
            toks, self.k_cache, self.v_cache = prefill(
                self.params, jnp.asarray(tokens), jnp.asarray(kv_len),
                self.k_cache, self.v_cache, jnp.asarray(slots),
                np.int32(self._rng_step), jnp.asarray(temps),
                jnp.asarray(top_ps), jnp.asarray(top_ks),
                self._prefill_base_key)
            self.stats["prefill_calls"] += 1
        except Exception as exc:
            for req in placed:
                self.active[req.slot] = None
                if paged:
                    self._release_pages(req.slot)
                self._fail(req, str(exc))
            if self.logger:
                self.logger.error(f"prefill failed: {exc!r}")  # gofrlint: allow(hot-path-purity) -- failure path: the prefill already raised; the engine is off the fast path
            self._recover_lost_cache(exc)
            return

        # PIPELINED: don't block on the first tokens here — the decode
        # pass for everyone else dispatches first, and the tokens are
        # collected when the device gets there (_collect_prefills).
        # Until then the slots hold their requests but don't decode.
        self._sched_dirty = True  # freshly occupied slots go pending
        for req in placed:
            req.pending_prefill = True
            req.prefill_epoch += 1
        self._pending_prefills.append({
            "toks": toks,
            "placed": list(placed),
            "slots": [r.slot for r in placed],
            "epochs": [r.prefill_epoch for r in placed],
            "t0": start,
            "wall0": time.time(),  # span timestamps use wall clock  # gofrlint: allow(hot-path-purity) -- span timestamps use wall clock; once per prefill dispatch, never per decode pass
            "bucket": bucket,
        })

    @hot_path
    def _collect_prefills(self) -> None:
        """Sync dispatched batch prefills: emit first tokens, open the
        slots for decode. Requests whose slot changed hands or that
        were re-dispatched since (epoch mismatch) are discarded — their
        current life owns its own prefill."""
        if self._pending_prefills:
            # collected slots flip pending -> decoding with new lengths
            self._sched_dirty = True
        while self._pending_prefills:
            rec = self._pending_prefills.popleft()
            try:
                toks_np = np.asarray(rec["toks"])  # gofrlint: allow(hot-path-purity) -- this sync IS the prefill collect: first tokens cross to host here by design
            except Exception as exc:
                for req, slot, epoch in zip(rec["placed"], rec["slots"],
                                            rec["epochs"]):
                    if req.prefill_epoch != epoch:
                        continue  # re-dispatched elsewhere since
                    req.pending_prefill = False
                    if self.active[slot] is req:
                        self.active[slot] = None
                        if self.config.kv_layout == "paged":
                            self._release_pages(slot)
                    if req.finished_at is None:
                        self._fail(req, str(exc))
                if self.logger:
                    self.logger.error(f"prefill failed: {exc!r}")  # gofrlint: allow(hot-path-purity) -- failure path: device collect raised; slots are being failed, not served
                self._recover_lost_cache(exc)
                continue
            self._note_prefill_span(rec["t0"])
            now = time.time()  # gofrlint: allow(hot-path-purity) -- wall-clock span assembly at the prefill collect boundary, once per batch
            pass_dur = time.perf_counter() - rec["t0"]
            pass_share = pass_dur / max(1, len(rec["placed"]))
            # the dispatch's (bucket, group) signature: group size is
            # the padded batch axis the graph compiled for
            prefill_sig = self._sig_str("prefill", rec.get("bucket"),
                                        int(toks_np.shape[0]))
            if self.recorder.enabled:
                self.recorder.record_pass(
                    "prefill", rows=len(rec["placed"]),
                    bucket=rec.get("bucket"), sig=prefill_sig,
                    dur=round(pass_dur, 6),
                    occupancy=sum(r is not None for r in self.active),
                    queue_depth=self.waiting.qsize())
            fresh_rows = recompute_rows = 0
            for row, (req, slot, epoch) in enumerate(
                    zip(rec["placed"], rec["slots"], rec["epochs"])):
                if (req.prefill_epoch != epoch
                        or self.active[slot] is not req
                        or req.finished_at is not None):
                    # preempted/retired/re-admitted since: the row's
                    # compute is discarded — preemption-class waste
                    recompute_rows += 1
                    continue
                req.pending_prefill = False
                req.device_s += pass_share
                if req.first_token_at is not None or req.recovered:
                    # a recompute row: the KV it just prefilled was
                    # already computed in its pre-preemption (or
                    # pre-restart) life
                    recompute_rows += 1
                    req.waste_recompute_s += pass_share
                else:
                    fresh_rows += 1
                self._req_event(req, "prefill", rec.get("wall0", now),
                                now, {"bucket": rec.get("bucket"),
                                      "rows": len(rec["placed"])})
                first = int(toks_np[row])
                if self.faults is not NO_FAULTS and \
                        self.faults.trip("logit_corrupt", req.tenant):
                    first = self._corrupt_token(first)
                if req.first_token_at is None:  # not a recompute
                    req.first_token_at = now
                    if self.metrics is not None:
                        self.metrics.record_histogram(  # gofrlint: allow(hot-path-purity) -- TTFT observation at the collect boundary, once per request lifetime
                            "app_chat_ttft_seconds",
                            now - req.submitted_at,
                            exemplar_trace_id=req.trace[0]
                            if req.trace else None)
                req.generated.append(first)
                req._emit(first)
                self.total_generated += 1
                self.lengths[slot] = len(req.prompt_tokens)
                if self._finished(req, first):
                    self._retire(slot)
            self.goodput.add_prefill("prefill", pass_dur,
                                     int(toks_np.shape[0]), fresh_rows,
                                     recompute_rows)
            self._note_pass_cost(
                "prefill", prefill_sig, pass_dur,
                rows=int(toks_np.shape[0]),
                tokens=int(toks_np.shape[0]) * (rec.get("bucket") or 0))
            self._update_kv_watermarks()
        self._note_device_idle()

    def _note_view_avoided(self, n_rows: int) -> None:
        """Account HBM bytes a dense-view round trip would have moved
        for a dispatch of ``n_rows`` slots that ran on the native
        paged path instead (gather of the K and V per-slot views; the
        write-back scatter is smaller and not counted). Surfaced in
        ``stats`` next to ``h2d_transfers`` as the paged twin of the
        transfer counters: steady native serving grows it every chunk/
        verify dispatch, the view path leaves it flat."""
        if self.config.kv_layout != "paged":
            return
        from ..ops.paged_kv import pool_row_bytes, pool_shape
        pg = pool_shape(self.k_cache)[3]
        row_bytes = pool_row_bytes(self.k_cache)
        self.stats["view_bytes_avoided"] += \
            2 * n_rows * self._pages_per_slot * pg * row_bytes

    def _note_prefill_span(self, start: float) -> None:
        """prefill_s accumulates a UNION of dispatch→sync spans: two
        bucket groups dispatched back-to-back and collected after the
        same decode pass cover nearly the same wall interval — naive
        sums would double-count (same watermark trick as decode_s)."""
        end = time.perf_counter()
        self.stats["prefill_s"] += end - max(start,
                                             self._prefill_busy_until)
        self._prefill_busy_until = end

    def _retire_unservable(self) -> None:
        """Shared pre-pass sweep: cancelled or at-ceiling slots leave
        before any device compute (decode and verify passes alike)."""
        for i, req in enumerate(self.active):
            if req is None:
                continue
            if req.cancelled:
                # a cancelled slot's in-flight tokens are discarded by
                # design — retire now, the collect discard-check holds
                self._retire(i)
            elif self.lengths[i] >= self.config.max_seq:
                # lengths advance at DISPATCH, so an uncollected pass
                # may still hold this slot's final tokens — settle it
                # (which usually retires the slot via valid < K) before
                # declaring the slot spent
                if any(rec["mask"][i] and rec["reqs"][i] is req
                       for rec in self._pending):
                    self._drain_pending()
                if (self.active[i] is req
                        and self.lengths[i] >= self.config.max_seq):
                    self._retire(i)

    def _note_pass(self, stat_key: str, start: float) -> None:
        """Per-device-pass accounting shared by decode and verify."""
        elapsed = time.perf_counter() - start
        self.stats[stat_key] += 1
        self.stats["decode_s"] += elapsed
        if self.metrics is not None:
            self.metrics.record_histogram("app_tpu_execute_seconds",
                                          elapsed)
        self._step_count += 1

    def _finished(self, req: GenRequest, token: int) -> bool:
        if token == self.config.eos_id:
            return True
        return len(req.generated) >= req.params.max_new_tokens

    @hot_path_boundary(
        "terminal per-request path: host-side span/metric/ledger assembly at retire is the architecture (PRs 3-5); runs once per request, never per pass")
    def _retire(self, slot: int) -> None:
        req = self.active[slot]
        if req is None:
            return
        self._dev_last_reqs[slot] = None  # device-token lineage ends here
        self._sched_dirty = True
        req.finished_at = time.time()
        self._finalize_obs(req)  # before the terminal None: a drained
        #                          stream implies spans are exported
        req._emit(None)
        self.active[slot] = None
        self.lengths[slot] = 0
        if self.config.kv_layout == "paged":
            if req.error is None and not req.cancelled:
                self._register_prefix(slot, req)
            self._release_pages(slot)

    # -------------------------------------------------------------- decode
    #
    # The decode path is PIPELINED: each iteration dispatches pass N+1
    # to the device and only then blocks on pass N's tokens, so the
    # host round trip (token download, stream emission, admission
    # bookkeeping) overlaps device compute instead of serialising with
    # it.  Pass N+1's input tokens come straight from pass N's device
    # output (``_dev_last``) — no host sync sits between passes.  The
    # cost: a slot that finishes in pass N still rides pass N+1 with
    # garbage output (discarded at collect), one wasted pass per
    # retirement.  Anything that mutates request state an uncollected
    # pass still owns (_retire, _preempt, spec passes) settles the
    # pipeline first.

    def _pipeline_depth(self) -> int:
        """How many dispatched passes to leave in flight right now.

        Adaptive by default: overlap only pays at saturation, where
        per-pass host work is large (many streams) and retirements are
        rare relative to passes; below ``pipeline_min_slots`` decoding
        slots the wasted pass per retirement and the one-pass token lag
        cost more than the overlap buys (VERDICT r4 weak #2)."""
        cfg = self.config
        if cfg.pipeline_depth is not None:
            return max(0, int(cfg.pipeline_depth))
        decoding = sum(1 for r in self.active
                       if r is not None and not r.pending_prefill)
        return 1 if decoding >= cfg.pipeline_min_slots else 0

    @hot_path
    def _decode_step(self) -> None:
        before = len(self._pending)
        self._decode_dispatch()
        if len(self._pending) == before:
            # nothing dispatched (every slot mid chunk-walk): settle
            # whatever is in flight so those streams don't stall
            self._drain_pending()
        else:
            depth = self._pipeline_depth()
            while len(self._pending) > depth:
                self._decode_collect()

    @hot_path
    def _drain_pending(self) -> None:
        while self._pending:
            self._decode_collect()

    @hot_path
    def _sync_decode_state(self) -> None:
        """Rebuild + upload the per-slot scheduler arrays the decode
        graph consumes. Called ONLY when an event (admission, retire,
        preemption, prefill transition, spec pass) flipped
        ``_sched_dirty`` — steady-state passes reuse the device copies
        untouched, and the decode graph itself advances lengths and
        the rng counter on device."""
        cfg = self.config
        b = cfg.max_batch
        tokens = np.zeros(b, np.int32)
        use_prev = np.zeros(b, bool)
        temps = np.zeros(b, np.float32)
        top_ps = np.ones(b, np.float32)
        top_ks = np.zeros(b, np.int32)
        active = np.zeros(b, bool)
        device_lengths = self.lengths.copy()
        fresh: list[int] = []
        for i, req in enumerate(self.active):
            if req is None:
                continue
            if req.pending_prefill:
                # mid chunked-prefill: the slot holds real KV rows the
                # chunk walk wrote — the decode pass must neither write
                # into them (length = max_seq makes the scatter drop)
                # nor emit its garbage samples
                device_lengths[i] = cfg.max_seq
                continue
            active[i] = True
            if (self._dev_last is not None
                    and self._dev_last_reqs[i] is req):
                # continuing slot: its true last token is pass N's
                # device output — feed it without syncing
                use_prev[i] = True
            else:
                tokens[i] = req.generated[-1]
                fresh.append(i)
            temps[i] = req.params.temperature
            top_ps[i] = req.params.top_p
            top_ks[i] = req.params.top_k
        self._dev_sched = {
            "tokens": jnp.asarray(tokens),
            "use_prev": jnp.asarray(use_prev),
            "active": jnp.asarray(active),
            "lengths": jnp.asarray(device_lengths),
            "temps": jnp.asarray(temps),
            "top_ps": jnp.asarray(top_ps),
            "top_ks": jnp.asarray(top_ks),
        }
        self._active_np = active
        self._fresh_rows = fresh
        self._sched_dirty = False
        self.stats["sched_syncs"] += 1
        self.stats["h2d_transfers"] += 7
        if self.metrics is not None:
            self.metrics.add_counter("app_engine_h2d_transfers", 7.0)  # gofrlint: allow(hot-path-purity) -- event-driven sched sync: this write records the h2d-invariant counter (zero per steady-state pass)

    @hot_path
    def _tables_arg(self):
        """Device-resident block tables, re-uploaded only when the
        host tables changed (page alloc/free/prefix attach) — page
        growth is the one mid-steady-state table event, every
        ``page_size // tokens_per_pass`` passes per slot."""
        if self._tables_dirty or self._dev_tables is None:
            self._dev_tables = jnp.asarray(self._tables)
            self._tables_dirty = False
            self.stats["h2d_transfers"] += 1
            if self.metrics is not None:
                self.metrics.add_counter("app_engine_h2d_transfers", 1.0)  # gofrlint: allow(hot-path-purity) -- event-driven table upload: page growth, not steady state; the write records the h2d invariant
        return self._dev_tables

    @hot_path
    def _decode_dispatch(self) -> None:
        cfg = self.config
        T = self._tokens_per_pass
        paged = cfg.kv_layout == "paged"
        h2d0 = self.stats["h2d_transfers"]  # this pass's upload delta
        # pre-pass sweep retires cancelled/at-ceiling slots, which
        # settles the pipeline per-slot via _retire
        self._retire_unservable()
        if paged:
            # grow each slot's block table to cover this pass, evicting
            # the newest requests when the pool runs dry (they resume
            # by recompute); iterate oldest-first so survivors are the
            # requests closest to completion
            order = sorted(
                (i for i, r in enumerate(self.active) if r is not None),
                key=lambda i: self.active[i].admit_order)
            for i in order:
                if self.active[i] is None:  # preempted by an earlier slot
                    continue
                if self.active[i].pending_prefill:
                    continue  # chunk walk allocates its own pages
                rows = min(int(self.lengths[i]) + T, cfg.max_seq)
                if not self._ensure_headroom(i, rows):
                    self._preempt(i)  # pool can't hold even this one now

        host0 = time.perf_counter()
        if self._sched_dirty:
            self._sync_decode_state()
        active_mask = self._active_np
        if not active_mask.any():
            return
        st = self._dev_sched

        # steps whose cache write would land past max_seq-1 are dropped
        # by the device scatter and attend to stale rows; their samples
        # are garbage — account the valid prefix NOW on the host mirror
        # (the graph advances the device lengths with the same clamp)
        decode = self._decode
        win = 0
        if self._decode_windows:
            # smallest compiled window covering every live row this
            # pass will touch (len + T); pending-prefill slots carry
            # the max_seq drop sentinel and decode garbage either way,
            # so only active slots bound the window
            needed = int(self.lengths[active_mask].max()) + T
            for w in self._decode_windows:
                if needed <= w:
                    decode = self._decode_by_window[w]
                    win = w
                    break
        self._note_dispatch_shape("decode", win)
        valid = np.where(active_mask,
                         np.minimum(T, cfg.max_seq - self.lengths),
                         0).astype(np.int32)
        self.lengths += valid

        start = time.perf_counter()
        self.goodput.note_dispatch(start)
        prev = (self._dev_last if self._dev_last is not None
                else self._dev_zero)
        tables = (self._tables_arg(),) if paged else ()
        (step_tokens, self._dev_last, self.k_cache, self.v_cache,
         new_lengths, self._dev_rng_step) = decode(
            self.params, st["tokens"], st["use_prev"], prev,
            self.k_cache, self.v_cache, *tables, st["lengths"],
            st["active"], self._dev_rng_step, st["temps"],
            st["top_ps"], st["top_ks"], self._dev_decode_key)
        st["lengths"] = new_lengths  # device mirror of self.lengths
        self._dev_last_reqs = [
            req if active_mask[i] else None
            for i, req in enumerate(self.active)]
        if self._fresh_rows:
            # rows fed from host tokens this pass continue from the
            # device output next pass: their use_prev flips — one more
            # sync, then steady state
            self._sched_dirty = True
        disp = time.perf_counter() - host0
        self._pending.append({
            "toks": step_tokens,
            "reqs": list(self.active),
            "mask": active_mask,
            "valid": valid,
            "t0": start,
            "disp": disp,
            "win": win,
            "h2d": self.stats["h2d_transfers"] - h2d0,
        })
        self.stats["dispatch_s"] += disp

    @hot_path
    def _decode_collect(self) -> None:
        """Sync the oldest in-flight pass: emit its tokens, retire
        finished slots.  Slots whose request was retired or preempted
        since dispatch are discarded (their rows decoded garbage)."""
        if not self._pending:
            return
        if self.faults is not NO_FAULTS:
            # corrupt-pass injection: a pass HAS dispatched, so tokens
            # are in flight — recovery must take the mid-stream
            # typed-retryable branch, never the bit-identical replay
            self.faults.trip("nan_logits")
        rec = self._pending.popleft()
        step_np = np.asarray(rec["toks"])  # [T, B] — blocks on device  # gofrlint: allow(hot-path-purity) -- this sync IS the decode collect: the token download is the pass's one sanctioned device read
        # decode_s = wall time with a decode pass in flight (dispatch →
        # sync complete), accumulated as a UNION of spans — consecutive
        # passes overlap (N+1 dispatches before N collects), and host/
        # prefill work overlapping a pass still counts as decode here,
        # so the bench's residual host_s is true dead time
        end = time.perf_counter()
        busy = end - max(rec["t0"], self._decode_busy_until)
        self._decode_busy_until = end
        self.stats["decode_passes"] += 1
        self.stats["decode_s"] += busy
        occupancy = int(rec["mask"].sum())
        if self.metrics is not None:
            self.metrics.record_histogram("app_tpu_execute_seconds", busy)  # gofrlint: allow(hot-path-purity) -- per-pass observation at the collect sync point, host floats already paid for
            self.metrics.record_histogram("app_engine_batch_occupancy",  # gofrlint: allow(hot-path-purity) -- per-pass observation at the collect sync point, host floats already paid for
                                          float(occupancy))
        self._step_count += 1
        # KV watermark BEFORE retires zero the finishing slots: the
        # dispatch already advanced lengths, so this is the pass peak
        self._update_kv_watermarks()
        emitted = 0
        credited = 0  # rows whose request actually kept this pass
        share = busy / occupancy if occupancy else 0.0
        for i, req in enumerate(rec["reqs"]):
            if req is None or not rec["mask"][i]:
                continue
            if self.active[i] is not req or req.finished_at is not None:
                continue  # retired/preempted since dispatch: discard
            # device-time attribution: this pass's busy span split
            # evenly across its occupied rows — the per-tenant
            # device_seconds the usage ledger accounts at retire
            req.device_s += share
            credited += 1
            done = False
            for k in range(int(rec["valid"][i])):
                token = int(step_np[k, i])
                if self.faults is not NO_FAULTS and \
                        self.faults.trip("logit_corrupt", req.tenant):
                    token = self._corrupt_token(token)
                req.generated.append(token)
                req._emit(token)
                self.total_generated += 1
                emitted += 1
                if self._finished(req, token):
                    done = True
                    break
            if done or rec["valid"][i] < self._tokens_per_pass:
                self._retire(i)
        collect = time.perf_counter() - end
        self.stats["collect_s"] += collect
        # goodput: rows that kept the pass are useful; empty slots,
        # pending-prefill sentinels and retired requests riding out a
        # pipelined pass are padding waste
        self.goodput.add_decode(busy, credited, self.config.max_batch)
        # fit the controller's sec/token price from the same busy span
        # the goodput ledger bills — an accepted draft token is worth
        # exactly what a plain-decode token costs
        self._spec_ctrl.note_decode(busy, emitted)
        decode_sig = self._sig_str("decode", rec.get("win", 0))
        self._note_pass_cost("decode", decode_sig, busy,
                             rows=credited, tokens=emitted)
        if self.recorder.enabled:
            # the pass record: everything here is a host int/float the
            # collect already computed — no device reads beyond the
            # token sync that IS the collect
            self.recorder.record_pass(
                "decode", dur=round(busy, 6),
                dispatch_s=round(rec.get("disp", 0.0), 6),
                collect_s=round(collect, 6), occupancy=occupancy,
                sig=decode_sig,
                queue_depth=self.waiting.qsize(), tokens=emitted,
                h2d=rec.get("h2d", 0),
                preemptions=self.stats["preemptions"])
        self._note_device_idle()

    # ------------------------------------------------- speculative decode
    def _get_spec_verify(self) -> Callable:
        """Fused tree-verify pass over all slots: feed each row's
        draft tree (node 0 = the committed last token, topological
        packing) at its cache offset, greedy-predict every node under
        the packed ancestor bitmask, resolve the longest fully
        accepted root-to-leaf path in-graph, compact the accepted
        path's KV rows into contiguous cache positions, and emit one
        bonus token sampled at the deepest accepted node — per-row
        sampling params decide the bonus (greedy rows take the argmax
        path inside _sample_batch). Returns (accepted[B], bonus[B],
        path[B, W]): ``path[b, k]`` is the node index at depth k of
        the accepted path, valid for k <= accepted[b]. One jitted
        closure serves every pow-2 width bucket (jit re-traces per
        bucket; warmup pre-observes and pre-compiles them)."""
        fn = self._prefill_cache.get("spec")
        if fn is None:
            verify_fn = self._spec_verify_fn
            paged = self.config.kv_layout == "paged" \
                and not self._native_verify
            if paged:
                from ..ops.paged_kv import gather_view, scatter_decode
            if self._native_verify:
                from ..ops.paged_kv import pool_move_rows
            max_seq = self.config.max_seq

            def _resolve_tree(logits, tokens, parents, depths,
                              chunk_lens, step, temps, top_ps, top_ks,
                              rng_key):
                b, w = tokens.shape
                pred = jnp.argmax(logits, axis=-1)         # [B, W]
                # node j is accepted iff its parent is accepted and
                # its token equals the parent's greedy prediction;
                # node 0 (the committed root) always is. Topological
                # packing (parents[j] < j) makes one forward sweep
                # over the static width exact.
                acc = jnp.zeros((b, w), bool).at[:, 0].set(True)
                for j in range(1, w):
                    pj = parents[:, j:j + 1]               # [B, 1]
                    p_acc = jnp.take_along_axis(acc, pj, axis=1)[:, 0]
                    p_pred = jnp.take_along_axis(pred, pj, axis=1)[:, 0]
                    ok = p_acc & (tokens[:, j] == p_pred) \
                        & (j < chunk_lens)
                    acc = acc.at[:, j].set(ok)
                # deepest accepted node; argmax ties break to the
                # LOWEST node index = the earliest-proposed chain
                score = jnp.where(acc, depths, -1)
                best = jnp.argmax(score, axis=1).astype(jnp.int32)
                n_acc = jnp.take_along_axis(
                    depths, best[:, None], axis=1)[:, 0]
                # root-first path-by-depth: walk parents w static
                # steps from best, scattering each visited node index
                # at its own depth (the walk idles at the root once it
                # arrives — rewrites of path[:, 0] with 0 are no-ops)
                path = jnp.zeros((b, w), jnp.int32)
                cur = best
                for _ in range(w):
                    d_cur = jnp.take_along_axis(
                        depths, cur[:, None], axis=1)      # [B, 1]
                    hit = jnp.arange(w)[None, :] == d_cur
                    path = jnp.where(hit, cur[:, None], path)
                    cur = jnp.take_along_axis(
                        parents, cur[:, None], axis=1)[:, 0]
                bonus_logits = jnp.take_along_axis(
                    logits, best[:, None, None], axis=1)[:, 0]
                key = jax.random.fold_in(rng_key, step)
                bonus = _sample_batch(bonus_logits, key, temps,
                                      top_ps, top_ks)
                return n_acc, bonus, path

            def _path_moves(offsets, path, n_acc, w):
                # KV compaction plan: the accepted node at depth k was
                # written at row offsets + path[k] and belongs at
                # offsets + k. k = 0 is an in-place no-op (path[0] is
                # the root); k > n_acc rows get an out-of-bounds dst
                # and drop. Inactive slots (offsets = max_seq) drop
                # everything the same way.
                k_arange = jnp.arange(w, dtype=jnp.int32)[None, :]
                src = offsets[:, None] + path              # [B, W]
                dst = jnp.where(k_arange <= n_acc[:, None],
                                offsets[:, None] + k_arange, max_seq)
                return src, dst

            def _move_rows_dense(cache, src, dst):
                # gather ALL src rows, then scatter — overlap-safe
                # compaction on [L, B, S, H, D] caches; OOB dst drops
                s = cache.shape[2]
                src_c = jnp.clip(src, 0, s - 1)
                rows = jnp.take_along_axis(
                    cache, src_c[None, :, :, None, None], axis=2)
                bidx = jnp.arange(cache.shape[1])[:, None]
                return cache.at[:, bidx, dst].set(rows, mode="drop")

            if self._native_verify:
                # native paged verify: the model writes the fed node
                # rows through the tables and attends with the ragged
                # tree kernel — verify reads only the pages each row's
                # history + tree window spans, no dense view; the
                # accepted path compacts by moving RAW pool rows
                # (quantized pools move codes+scales untouched, so the
                # commit is exact — no requantization)
                native_verify = self._paged_verify_fn

                def fused(params, tokens, parents, depths, tree_masks,
                          kc, vc, tables, offsets, chunk_lens, step,
                          temps, top_ps, top_ks, rng_key):
                    logits, kc, vc = native_verify(
                        params, tokens, kc, vc, tables, offsets,
                        chunk_lens, tree_depths=depths,
                        tree_masks=tree_masks)
                    n_acc, bonus, path = _resolve_tree(
                        logits, tokens, parents, depths, chunk_lens,
                        step, temps, top_ps, top_ks, rng_key)
                    src, dst = _path_moves(offsets, path, n_acc,
                                           tokens.shape[1])
                    kc = pool_move_rows(kc, tables, src, dst)
                    vc = pool_move_rows(vc, tables, src, dst)
                    return n_acc, bonus, path, kc, vc
            elif paged:
                def fused(params, tokens, parents, depths, tree_masks,
                          kc, vc, tables, offsets, chunk_lens, step,
                          temps, top_ps, top_ks, rng_key):
                    s_width = tokens.shape[1]
                    k_view = gather_view(kc, tables,
                                         dtype=self._kv_view_dtype)
                    v_view = gather_view(vc, tables,
                                         dtype=self._kv_view_dtype)
                    logits, k_view, v_view = verify_fn(
                        params, tokens, k_view, v_view, offsets,
                        chunk_lens, tree_depths=depths,
                        tree_masks=tree_masks)
                    n_acc, bonus, path = _resolve_tree(
                        logits, tokens, parents, depths, chunk_lens,
                        step, temps, top_ps, top_ks, rng_key)
                    src, dst = _path_moves(offsets, path, n_acc,
                                           s_width)
                    k_view = _move_rows_dense(k_view, src, dst)
                    v_view = _move_rows_dense(v_view, src, dst)
                    kc = scatter_decode(kc, tables, k_view,
                                        offsets, s_width)
                    vc = scatter_decode(vc, tables, v_view,
                                        offsets, s_width)
                    return n_acc, bonus, path, kc, vc
            else:
                def fused(params, tokens, parents, depths, tree_masks,
                          kc, vc, offsets, chunk_lens, step, temps,
                          top_ps, top_ks, rng_key):
                    logits, kc, vc = verify_fn(
                        params, tokens, kc, vc, offsets, chunk_lens,
                        tree_depths=depths, tree_masks=tree_masks)
                    n_acc, bonus, path = _resolve_tree(
                        logits, tokens, parents, depths, chunk_lens,
                        step, temps, top_ps, top_ks, rng_key)
                    src, dst = _path_moves(offsets, path, n_acc,
                                           tokens.shape[1])
                    kc = _move_rows_dense(kc, src, dst)
                    vc = _move_rows_dense(vc, src, dst)
                    return n_acc, bonus, path, kc, vc
            fn = jax.jit(fused, donate_argnums=(5, 6))
            self._prefill_cache["spec"] = fn
        return fn

    @hot_path_boundary(
        "drafting policy: O(1)-amortized n-gram index maintenance plus "
        "controller pricing, host work that runs only for greedy slots "
        "on a speculation pass — never inside the plain decode pass")
    def _draft_proposals(self, req: GenRequest):
        """Prompt-lookup drafting on the request's incremental n-gram
        index: the stream's final n-gram proposes up to
        ``spec_branches`` distinct continuations (newest occurrences
        first), trie-merged into one :class:`DraftTree`. The
        controller prices each slot's depth/branching per pass; a
        (0, 0) plan skips drafting entirely. Returns a DraftTree with
        at least one draft node, or [] when this pass shouldn't
        draft. The index replaces the old per-pass O(context) rescan
        with O(new tokens) maintenance + O(branches) dict probes."""
        cfg = self.config
        slot = req.slot
        ctrl = self._spec_ctrl
        if 0 <= slot < ctrl.max_batch:
            if self._spec_ctrl_owner[slot] is not req:
                # new tenant in this slot: its predecessor's
                # accept-rate history doesn't transfer
                ctrl.reset_slot(slot)
                self._spec_ctrl_owner[slot] = req
            depth, branches = ctrl.plan(slot)
        else:
            depth, branches = cfg.spec_draft, cfg.spec_branches
        # never draft past the token budget: the bonus token always
        # lands, so at most remaining-1 drafts can be kept
        remaining = req.params.max_new_tokens - len(req.generated)
        depth = min(depth, max(0, remaining - 1))
        if depth <= 0 or branches <= 0:
            return []
        n = max(1, cfg.spec_ngram)
        idx = req.spec_index
        if (idx is None or idx.n != n
                or idx.prompt_len != len(req.prompt_tokens)):
            # first drafting pass — or the token stream was rewritten
            # under the index (preemption/recovery fold generated
            # tokens back into the prompt): rebuild from scratch
            idx = NgramIndex(n)
            idx.extend(req.prompt_tokens)
            idx.prompt_len = len(req.prompt_tokens)
            req.spec_index = idx
        stream_len = idx.prompt_len + len(req.generated)
        if idx.size < stream_len:
            idx.extend(req.generated[idx.size - idx.prompt_len:])
        chains = idx.propose(depth, branches)
        if not chains:
            return []
        tree = build_draft_tree(
            req.generated[-1], chains,
            max_nodes=1 + cfg.spec_draft * cfg.spec_branches)
        return tree if tree.n_draft else []

    @hot_path_boundary(
        "speculative verify collect: the accept/path/bonus download IS "
        "the pass's sanctioned device sync, and the controller/ledger "
        "bookkeeping is priced against the multi-token verify pass it "
        "rides, not per decode pass")
    def _spec_pass(self, proposals: dict) -> None:
        """One speculative tree-verify pass over every active slot.
        Slots without drafts ride along as a lone root node — for
        them this is exactly a single decode step."""
        cfg = self.config
        paged = cfg.kv_layout == "paged"
        # verify feeds each row's true last token from host state and
        # appends host-side — the decode pipeline must be settled, its
        # device-resident last token invalidated, and the scheduler
        # state resynced before the next decode dispatch (lengths
        # advance host-side below)
        self._drain_pending()
        self._dev_last = None
        self._sched_dirty = True
        self._retire_unservable()
        b = cfg.max_batch
        # normalize: monkeypatched _draft_proposals hooks may return a
        # plain token list (the historical single-chain shape)
        trees: dict[int, DraftTree] = {}
        for i, drafted in proposals.items():
            req = self.active[i]
            if req is None or req.pending_prefill:
                continue
            if not isinstance(drafted, DraftTree):
                drafted = DraftTree.from_chain(req.generated[-1],
                                               drafted)
            if drafted.n_draft:
                trees[i] = drafted
        # pow-2 width buckets: the widest tree this pass picks the
        # verify graph, so the compiled-shape set stays small and
        # warmup can observe/compile every bucket up front
        widest = max((t.n_nodes for t in trees.values()), default=1)
        width = 2
        while width < widest:
            width *= 2
        tokens = np.zeros((b, width), np.int32)
        parents = np.zeros((b, width), np.int32)
        depths = np.zeros((b, width), np.int32)
        masks = np.ones((b, width), np.int32)
        chunk_lens = np.ones(b, np.int32)
        offsets = np.full(b, cfg.max_seq, np.int32)  # inactive: drop
        temps = np.zeros(b, np.float32)
        top_ps = np.ones(b, np.float32)
        top_ks = np.zeros(b, np.int32)
        rows = []
        for i, req in enumerate(self.active):
            if req is None or req.pending_prefill:
                continue
            tokens[i, 0] = req.generated[-1]
            tree = trees.get(i)
            if tree is not None:
                n = tree.n_nodes
                tokens[i, :n] = tree.tokens
                parents[i, :n] = tree.parents
                depths[i, :n] = tree.depths
                masks[i, :n] = tree.masks
                chunk_lens[i] = n
            offsets[i] = int(self.lengths[i])
            temps[i] = req.params.temperature
            top_ps[i] = req.params.top_p
            top_ks[i] = req.params.top_k
            rows.append(i)
        if not rows:
            return
        if paged:
            # headroom for every fed row (draft nodes write cache rows
            # too); an earlier row's headroom may preempt a later one
            for i in list(rows):
                if self.active[i] is None:  # preempted as a victim
                    continue
                rows_needed = min(int(self.lengths[i])
                                  + int(chunk_lens[i]), cfg.max_seq)
                if not self._ensure_headroom(i, rows_needed):
                    self._preempt(i)
        tables = (self._tables_arg(),) if paged else ()
        self._rng_step += 1
        self._note_dispatch_shape("spec_verify", width)
        start = time.perf_counter()
        self.goodput.note_dispatch(start)
        w0 = time.time()
        fn = self._get_spec_verify()
        accepted_dev, bonus_dev, path_dev, self.k_cache, \
            self.v_cache = fn(
                self.params, jnp.asarray(tokens), jnp.asarray(parents),
                jnp.asarray(depths), jnp.asarray(masks), self.k_cache,
                self.v_cache, *tables, jnp.asarray(offsets),
                jnp.asarray(chunk_lens), np.int32(self._rng_step),
                jnp.asarray(temps), jnp.asarray(top_ps),
                jnp.asarray(top_ks), self._prefill_base_key)
        accepted = np.asarray(accepted_dev)
        bonus = np.asarray(bonus_dev)
        path = np.asarray(path_dev)
        if self._native_verify:
            self._note_view_avoided(b)
        self._note_pass("spec_passes", start)
        spec_dur = time.perf_counter() - start
        w1 = time.time()
        pass_drafted = pass_accepted = pass_rows = 0
        row_stats: list[tuple[int, int]] = []  # (drafted, accepted)
        live = sum(1 for r in self.active
                   if r is not None and not r.pending_prefill)
        verify_share = (spec_dur / live) if live else 0.0
        for i, req in enumerate(self.active):
            if req is None or req.pending_prefill:
                continue
            req.device_s += verify_share
            tree = trees.get(i)
            n_drafted = tree.n_draft if tree is not None else 0
            n_acc = min(int(accepted[i]), n_drafted)
            if n_drafted:
                # the rejected-draft slice of this row's device time:
                # positions computed and thrown away, billed to the
                # tenant that drafted them
                req.waste_spec_s += verify_share \
                    * (n_drafted - n_acc) / (1 + n_drafted)
                self._spec_ctrl.note_result(i, n_drafted, n_acc)
            row_stats.append((n_drafted, n_acc))
            pass_drafted += n_drafted
            pass_accepted += n_acc
            pass_rows += 1
            if n_drafted:
                self._req_event(req, "spec_verify", w0, w1,
                                {"drafted": n_drafted,
                                 "accepted": n_acc})
            # the accepted root-to-leaf path's tokens, in depth order,
            # then the bonus sampled at the deepest accepted node
            emitted = [tree.tokens[int(path[i, k])]
                       for k in range(1, n_acc + 1)] if tree else []
            emitted.append(int(bonus[i]))
            self.stats["spec_accepted"] += n_acc
            # offered drafts this row — the honest acceptance-rate
            # denominator (spec_passes counts batched passes, so
            # accepted/passes*draft overstates with G rows per pass);
            # spec_rows counts row-participations: each emits exactly
            # one bonus token, the per-row tokens-per-verify base
            self.stats["spec_drafted"] += n_drafted
            self.stats["spec_rows"] += 1
            # rows for the fed tokens were written at offsets..; only
            # the accepted prefix (plus the already-cached last token)
            # counts — rejected rows are overwritten by later passes
            # and never attended (length-masked)
            ceiling = cfg.max_seq - int(self.lengths[i])
            done = False
            kept = 0
            for token in emitted:
                if kept >= ceiling:
                    done = True
                    break
                if self.faults is not NO_FAULTS and \
                        self.faults.trip("logit_corrupt", req.tenant):
                    token = self._corrupt_token(token)
                req.generated.append(token)
                req._emit(token)
                self.total_generated += 1
                kept += 1
                if self._finished(req, token):
                    done = True
                    break
            self.lengths[i] += kept
            if done or kept >= ceiling:
                self._retire(i)
        if self.metrics is not None and pass_drafted:
            self.metrics.add_counter("app_engine_spec_drafted",
                                     float(pass_drafted))
            self.metrics.add_counter("app_engine_spec_accepted",
                                     float(pass_accepted))
        self.goodput.add_spec(spec_dur, b, row_stats)
        # fit the controller's verify row cost from the same span the
        # ledger bills, so policy and waste accounting can't diverge
        self._spec_ctrl.note_verify(spec_dur, pass_rows, width)
        spec_sig = self._sig_str("spec_verify", width)
        self._note_pass_cost("spec_verify", spec_sig, spec_dur,
                             rows=pass_rows,
                             tokens=pass_accepted + pass_rows)
        self._update_kv_watermarks()
        if self.recorder.enabled:
            self.recorder.record_pass(
                "spec_verify", rows=pass_rows, drafted=pass_drafted,
                accepted=pass_accepted,
                dur=round(time.perf_counter() - start, 6),
                occupancy=pass_rows, sig=spec_sig,
                queue_depth=self.waiting.qsize())
        self._note_device_idle()

    def _update_kv_watermarks(self) -> None:
        """KV high-water marks, sampled at collect sites so a short
        burst's peak is caught before its slots retire — an O(1) page
        count (paged) or an O(max_batch) length sum (slot), pure host
        compares."""
        wm = self.watermarks
        if not wm.enabled:
            return
        if self.config.kv_layout == "paged":
            used = self._n_pages - len(self._free_pages)
            wm.update("kv_pages", float(used))
            wm.update("prefix_pages", float(self._cached_pages))
            wm.update("kv_bytes",
                      used * self._kv_bytes_total
                      / max(1, self._n_pages))
        else:
            rows = float(self.lengths.sum())
            wm.update("kv_rows", rows)
            wm.update("kv_bytes",
                      rows * self._kv_bytes_total
                      / max(1, self.config.max_batch
                            * self.config.max_seq))

    def _update_watermarks(self) -> None:
        """Advance every memory high-water mark (throttled cadence):
        the KV marks plus host RSS (one getrusage syscall)."""
        wm = self.watermarks
        if not wm.enabled:
            return
        self._update_kv_watermarks()
        wm.update_rss()

    def efficiency_state(self) -> dict:
        """The ``GET /debug/efficiency`` payload for this engine:
        goodput classification, memory watermarks, recompile sentinel
        state — all host-side reads."""
        self._update_watermarks()
        cfg = self.config
        cap_tokens = (self._n_pages * max(1, int(cfg.page_size))
                      if cfg.kv_layout == "paged"
                      else cfg.max_batch * cfg.max_seq)
        return {"goodput": self.goodput.state(),
                "watermarks": self.watermarks.state(),
                "recompiles": self.sentinel.state(),
                "spec": self._spec_ctrl.state(),
                "costs": self.costs.state(),
                "kv_bytes": self._kv_bytes_total,
                "kv_bytes_per_token": round(
                    self._kv_bytes_total / max(1, cap_tokens), 3)}

    def _update_gauges(self) -> None:
        m = self.metrics
        if m is not None:
            m.set_gauge(
                "app_engine_active_slots",
                float(sum(r is not None for r in self.active)))
            m.set_gauge("app_engine_waiting",
                        float(self.waiting.qsize()))
        # derived gauges + watermarks, throttled: pure host arithmetic
        # over counters the loop already maintains — never a device sync
        now = time.time()
        dt = now - self._gauge_wall
        if dt < 0.25:
            return
        self._update_watermarks()
        self._refresh_prefix_digest()
        tps = (self.total_generated - self._gauge_tokens) / dt
        self._gauge_wall = now
        self._gauge_tokens = self.total_generated
        if m is None:
            return
        m.set_gauge("app_engine_tokens_per_second", round(tps, 2))
        gp = self.goodput
        if gp.enabled and gp.busy_s > 0:
            ratio = gp.useful_s / gp.busy_s
            m.set_gauge("app_engine_goodput_ratio", round(ratio, 6))
            # goodput-floor breach arms a bounded auto-capture; off by
            # default (floor 0.0), and the 1s busy guard keeps a cold
            # engine's first noisy ratio from tripping it
            floor = self.config.autoprof_goodput_floor
            if floor > 0.0 and gp.busy_s > 1.0 and ratio < floor:
                self.autoprof.arm(
                    "goodput_floor",
                    f"goodput ratio {ratio:.3f} below floor {floor:.3f}")
            for cause, total in gp.waste_s.items():
                delta = total - self._waste_published.get(cause, 0.0)
                if delta > 0:  # counters take deltas, the meter totals
                    m.add_counter("app_engine_waste_seconds", delta,
                                  cause=cause)
                    self._waste_published[cause] = total
        wm = self.watermarks
        if wm.enabled:
            for mark, gauge in (
                ("kv_pages", "app_engine_kv_pages_watermark"),
                ("kv_rows", "app_engine_kv_rows_watermark"),
                ("kv_bytes", "app_engine_kv_bytes_watermark"),
                ("prefix_pages", "app_engine_prefix_pages_watermark"),
                ("host_rss_bytes",
                 "app_engine_host_rss_bytes_watermark"),
            ):
                value = wm.get(mark)
                if value is not None:
                    m.set_gauge(gauge, value)
        mfu = (tps * self._flops_per_token / self._peak_flops
               if self._flops_per_token and self._peak_flops else 0.0)
        m.set_gauge("app_engine_mfu", round(mfu, 6))
        if self._spec_enabled:
            m.set_gauge("app_engine_spec_accept_rate",
                        round(self._spec_ctrl.accept_rate(), 6))
        if hasattr(self.waiting, "publish_gauges"):
            self.waiting.publish_gauges(m)
        cfg = self.config
        if cfg.kv_layout == "paged":
            used = self._n_pages - len(self._free_pages)
            m.set_gauge("app_engine_kv_pool_utilization",
                        round(used / max(1, self._n_pages), 4))
            # fragmentation: allocated page capacity not holding live
            # rows (pending-prefill slots report their walk progress)
            cap_rows = int(self._slot_pages.sum()) * cfg.page_size
            live = int(self.lengths.sum()) + sum(
                r.prefill_offset for r in self.active
                if r is not None and r.pending_prefill)
            frag = 1.0 - live / cap_rows if cap_rows else 0.0
            m.set_gauge("app_engine_kv_pool_fragmentation",
                        round(min(1.0, max(0.0, frag)), 4))
            m.set_gauge("app_engine_prefix_cache_entries",
                        float(len(self._prefix_cache)))
            m.set_gauge("app_engine_prefix_cache_pages",
                        float(self._cached_pages))
        else:
            m.set_gauge("app_engine_kv_pool_utilization",
                        round(float(self.lengths.sum())
                              / (cfg.max_batch * cfg.max_seq), 4))
            m.set_gauge("app_engine_kv_pool_fragmentation", 0.0)

    @hot_path_boundary(
        "prefix-digest assembly at the throttled gauge cadence: host-side "
        "hashing over cache keys already resident, skipped entirely unless "
        "a cache mutation set the dirty flag, published by atomic "
        "reference swap for the heartbeat thread")
    def _refresh_prefix_digest(self) -> None:
        """Rebuild the fleet-router digest when the prefix cache
        changed since the last gauge pass: one truncated content hash
        per resident cache key (newest ``prefix_digest_hashes``
        entries — the LRU end the router should bet on)."""
        if not self._prefix_digest_dirty:
            return
        self._prefix_digest_dirty = False
        limit = max(0, int(self.config.prefix_digest_hashes))
        if not self._prefix_enabled or not limit:
            self._prefix_digest = None
            return
        from .router import prefix_hash
        keys = list(self._prefix_cache)
        if len(keys) > limit:
            keys = keys[-limit:]
        self._prefix_digest = {
            "page": int(self.config.page_size),
            "entries": len(self._prefix_cache),
            "pages": int(self._cached_pages),
            "hashes": [prefix_hash(k) for k in keys],
        }

    def prefix_digest(self) -> dict | None:
        """Latest published digest (atomic reference read — safe from
        the heartbeat thread); None when disabled or cache-less."""
        return self._prefix_digest

    # ---------------------------------------------------------------- loop
    def _loop(self) -> None:
        try:
            while self._running:
                self._last_beat = time.time()
                if self.faults is not NO_FAULTS:
                    # deterministic chaos (serving/faults.py), armed
                    # only when a plan is loaded: pass_raise throws
                    # into the recovery path below, pass_stall /
                    # pass_latency wedge the loop so the watchdog and
                    # the control plane see a genuine stall
                    self.faults.trip("pass_raise")
                    self.faults.trip("pass_stall")
                    self.faults.trip("pass_latency")
                free = sum(1 for r in self.active if r is None)
                busy = free < self.config.max_batch
                if free == 0 and not self._requeued:
                    # full batch, nothing bounced back: if the
                    # interactive lane is starving behind background
                    # work, preempt-by-recompute frees a slot for it
                    # (rate-capped by the scheduler)
                    if self._sched_starvation_preempt():
                        free = sum(1 for r in self.active if r is None)
                if free > 0 or self._requeued:
                    # requeued (already-admitted) work goes first,
                    # bypasses the admission bound, and drains even
                    # with zero free slots — mid-walk chunked prefills
                    # HOLD their slot and must keep resuming; then one
                    # batched pop per pass (TTFT priority): blocks
                    # while fully idle — in the native queue the
                    # engine thread sleeps in C with the GIL released
                    # — and is a zero-wait drain between decode steps
                    # while busy
                    batch, self._requeued = self._requeued, []
                    self._requeued_set.clear()
                    # mid-walk resumes already hold their slot: they
                    # must not eat capacity meant for waiting requests
                    needing_slots = sum(
                        1 for r in batch
                        if not (r.pending_prefill and r.slot >= 0
                                and self.active[r.slot] is r))
                    take = free - needing_slots
                    if take > 0:
                        popped = self.waiting.pop_batch(
                            take,
                            first_wait_s=0.0 if (busy or batch) else 0.05,
                            drain_wait_s=0.0)
                        batch = batch + (popped or [])
                    if batch:
                        live = []
                        for r in batch:
                            if r.cancelled:  # dropped before prefill
                                if (r.pending_prefill and r.slot >= 0
                                        and self.active[r.slot] is r):
                                    # mid chunk-walk: free the slot too
                                    self._retire(r.slot)
                                elif r.finished_at is None:
                                    r.finished_at = time.time()
                                    r._emit(None)
                            else:
                                live.append(r)
                        if live:
                            self._admit_batch(live)
                if any(r is not None for r in self.active):
                    proposals: dict[int, Any] = {}  # slot -> DraftTree
                    decoding = 0
                    if self._spec_enabled:
                        for i, r in enumerate(self.active):
                            if (r is None or r.pending_prefill
                                    or r.cancelled):
                                continue
                            decoding += 1
                            if r.params.temperature == 0.0:
                                drafted = self._draft_proposals(r)
                                if drafted:
                                    proposals[i] = drafted
                    # mixed batches alternate: a verify pass advances
                    # non-drafting slots by ONE token, so they get a
                    # full K-step decode pass every other iteration —
                    # bounding their slowdown instead of starving them
                    # while a peer keeps drafting
                    run_spec = bool(proposals) and (
                        len(proposals) == decoding or self._spec_toggle)
                    if run_spec:
                        self._spec_toggle = False
                        self._spec_pass(proposals)
                    else:
                        self._spec_toggle = True
                        self._decode_step()
                    self._collect_prefills()
                else:
                    # nothing active: settle any in-flight pass so its
                    # final tokens reach their streams
                    self._drain_pending()
                    self._collect_prefills()
                self._update_gauges()
            # clean stop with work still in flight: the tokens are
            # real — emit them before failing what remains
            self._drain_pending()
            self._collect_prefills()
        except Exception as exc:  # containment: never die silently
            if self._recover(exc):
                # runtime state rebuilt on the resident weights and
                # compile cache: resume serving and replay the recovery
                # buffer. Recursion depth is bounded by
                # restart_policy.max_restarts.
                self._loop()
            else:
                self._crash(exc)
        else:
            self._shutdown_cleanup("engine stopped")

    def _recover(self, exc: BaseException) -> bool:
        """In-thread crash-recovery supervisor: when
        ``config.restart_policy`` has budget left, salvage what can be
        salvaged, rebuild the runtime on the resident weights and
        compiled graphs, sleep a deterministic exponential backoff and
        report True so ``_loop`` resumes. False = no policy, budget
        exhausted, or the engine was stopping anyway — the crash is
        terminal (``_crash``).

        Salvage rules (the no-duplicate-token invariant): a request
        that has NOT emitted its first token replays invisibly — it
        goes to the recovery buffer (the ``_requeued`` fast lane, which
        bypasses the admission bound) and re-prefills from its prompt,
        priced as ``preempt_recompute`` waste via the ``recovered``
        flag. A MID-STREAM request already holds tokens the engine
        cannot un-send, so replaying it risks duplicates — it fails
        with a typed retryable ``engine_restart`` reject (503 +
        Retry-After + details.code through the handlers)."""
        policy = self.config.restart_policy
        if policy is None or not self._running:
            return False
        if self._restarts >= policy.max_restarts:
            # budget exhausted: this crash is terminal — snapshot an
            # incident bundle before _crash tears down (the bundle's
            # timeline seals with the engine.crash event _crash emits)
            self.incidents.trigger(
                "restart_budget",
                cause=f"{self._restarts} restarts >= budget "
                      f"{policy.max_restarts}; last crash: "
                      f"{type(exc).__name__}: {exc}")
            return False
        self._restarts += 1
        self._last_crash = f"{type(exc).__name__}: {exc}"
        backoff = policy.backoff_for(self._restarts)
        if self.logger:
            self.logger.error(
                f"engine loop crashed ({self._last_crash}); restarting "
                f"{self._restarts}/{policy.max_restarts} after "
                f"{backoff:.2f}s backoff")
            self.recorder.dump(self.logger, reason=self._last_crash)
        if self.metrics is not None:
            self.metrics.increment_counter("app_engine_restarts")
        self.events.emit("engine.restart", severity="error",
                         cause=self._last_crash,
                         restart=self._restarts,
                         max_restarts=policy.max_restarts,
                         backoff_s=round(backoff, 3))
        from .scheduler import SchedReject
        recovered = 0
        for i, req in enumerate(self.active):
            if req is None:
                continue
            self.active[i] = None
            self.lengths[i] = 0
            if req.finished_at is not None:
                continue
            if req.cancelled:  # consumer gone: just close the stream
                req.finished_at = time.time()
                self._finalize_obs(req)
                req._emit(None)
            elif req.first_token_at is None:
                req.pending_prefill = False
                req.prefill_epoch += 1
                req.prefill_offset = 0
                req.slot = -1
                req.recovered = True
                self._requeue(req)
                recovered += 1
            else:
                req.reject = SchedReject(
                    code="engine_restart", tenant=req.tenant,
                    retry_after_s=max(1.0, backoff),
                    detail="engine restarted mid-stream; the partial "
                           "output is stale — retry the request")
                self._fail(req, req.reject.message)
        # dispatched-but-uncollected passes died with the crash; the
        # recovery buffer (_requeued) survives untouched and replays
        # first once the loop resumes
        self._reset_runtime_state()
        if recovered:
            if self.metrics is not None:
                self.metrics.add_counter("app_engine_requests_recovered",
                                         float(recovered))
            if self.logger:
                self.logger.warn(
                    f"recovery buffer: {recovered} request(s) replay "
                    "after restart")
        deadline = time.time() + backoff
        while self._running and time.time() < deadline:
            # interruptible backoff: stop() during the sleep resumes
            # the loop, which then exits through the CLEAN path
            time.sleep(min(0.05, max(0.0, deadline - time.time())))
        self._last_beat = time.time()
        self.events.emit("engine.recovery",
                         cause=self._last_crash,
                         restart=self._restarts, recovered=recovered)
        return True

    def _crash(self, exc: BaseException) -> None:
        """The hot loop threw: fail every in-flight request, refuse new
        ones, and flip health DOWN so orchestrators can see it.

        The reference refuses to let one request take the process down
        (panic recovery, /root/reference/pkg/gofr/handler.go:141); for
        an engine thread the equivalent blast-radius control is failing
        fast and loudly rather than hanging every stream forever."""
        self._failed = f"{type(exc).__name__}: {exc}"
        self._running = False
        self.events.emit("engine.crash", severity="error",
                         cause=self._failed, restarts=self._restarts)
        if self.logger:
            self.logger.error(f"engine loop crashed: {exc!r}")
            # post-mortem: the last N pass records tell you what the
            # loop was doing when it died
            self.recorder.dump(self.logger, reason=self._failed)
        self._shutdown_cleanup(f"engine crashed: {self._failed}")


#: static cap on the candidate set per row. ``lax.top_k`` over this many
#: columns replaces a full-vocab bitonic sort (128k wide on Llama-3 —
#: measured as the single largest cost in the fused decode graph). Any
#: realistic top-k/top-p nucleus fits in 64 candidates; rows whose
#: nucleus would be wider are truncated to the 64 most likely tokens.
TOPK_BOUND = 64


def _sample_batch(logits: jnp.ndarray, key: jax.Array,
                  temperatures: jnp.ndarray, top_ps: jnp.ndarray,
                  top_ks: jnp.ndarray | None = None) -> jnp.ndarray:
    """Per-row sampling in one graph: greedy rows (temp==0) take the
    top-1 candidate; stochastic rows gumbel-sample within the
    ``TOPK_BOUND`` most likely tokens after the row's top-k filter and
    a top-p filter applied *on the top-k-renormalised* distribution
    (``top_ks`` row value 0 disables top-k for that row).

    All-greedy batches (the common serving case and every benchmark)
    take a ``lax.cond`` fast path: a plain argmax, skipping the
    vocab-wide ``lax.top_k`` whose cost scales with B x V and is pure
    waste when no row samples. The predicate is traced, so one compile
    covers both regimes.
    """
    logits = logits.astype(jnp.float32)
    bound = min(TOPK_BOUND, logits.shape[-1])

    def _greedy(_):
        # tie-break assumption: argmax here and idx[:, 0] from the
        # mixed branch's lax.top_k both resolve exact logit ties to
        # the LOWEST index in XLA — if either ever changes, the same
        # greedy row could emit different tokens depending on whether
        # a batchmate samples (ADVICE r5)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _full(_):
        safe_t = jnp.maximum(temperatures, 1e-6)[:, None]
        vals, idx = jax.lax.top_k(logits / safe_t, bound)  # sorted desc

        # top-k first: mask candidates beyond each row's k (0 = disabled)
        pos = jnp.arange(bound)[None, :]
        if top_ks is not None:
            k_eff = jnp.where(top_ks > 0, jnp.minimum(top_ks, bound),
                              bound)
            vals = jnp.where(pos < k_eff[:, None], vals, NEG_INF)

        # then top-p on the renormalised survivor distribution
        probs = jax.nn.softmax(vals, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = jnp.roll(cum, 1, axis=-1) < top_ps[:, None]
        keep = keep.at[..., 0].set(True)
        filtered = jnp.where(keep, vals, NEG_INF)

        gumbel = -jnp.log(-jnp.log(
            jax.random.uniform(key, vals.shape, minval=1e-20,
                               maxval=1.0) + 1e-20))
        choice = jnp.argmax(filtered + gumbel, axis=-1)
        sampled = jnp.take_along_axis(idx, choice[:, None], axis=-1)[:, 0]
        # temperature scaling is monotonic, so idx[:, 0] IS the argmax
        return jnp.where(temperatures <= 0.0, idx[:, 0],
                         sampled).astype(jnp.int32)

    return jax.lax.cond(jnp.all(temperatures <= 0.0), _greedy, _full, None)
