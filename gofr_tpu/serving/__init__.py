from .engine import Engine, EngineConfig, GenRequest, SamplingParams
from .tokenizer import ByteTokenizer, Tokenizer

__all__ = ["Engine", "EngineConfig", "GenRequest", "SamplingParams",
           "ByteTokenizer", "Tokenizer"]
