"""Speculative decoding support: incremental n-gram drafting, draft
trees, and the goodput-priced speculation controller.

Three host-side pieces the engine composes (device work — tree verify,
acceptance, KV compaction — lives in the fused closures of
``serving/engine.py`` and the kernels of ``ops/paged_attention.py``):

- :class:`NgramIndex` — a per-request index from n-gram to the
  positions it occurs at, extended O(1) per retired token. Replaces
  the O(context) rescan the old ``_draft_proposals`` ran every decode
  pass; rebuilt from scratch only when the request's token stream is
  rewritten under it (preemption folds generated tokens into the
  prompt; recovery replays it).
- :class:`DraftTree` — up to 32 draft nodes packed topologically
  (parent index < child index, node 0 = the committed root token),
  with per-node parent / depth / packed ancestor bitmask arrays in
  exactly the layout the tree-verify kernel consumes.
- :class:`SpecController` — per-slot accept-rate EWMA priced against
  fitted decode sec/token and verify row cost. Drafting happens only
  when the expected accepted tokens are worth more than the marginal
  verify rows; slots whose acceptance collapses are disabled and
  re-probed on a fixed cadence. Everything it learns comes from the
  same measurements the ``spec_rejected`` goodput cause is billed
  from, so "the controller thinks speculation pays" and "the waste
  ledger says it paid" can be cross-checked in ``/debug/efficiency``.
"""

from __future__ import annotations

from dataclasses import dataclass

#: packed ancestor masks are int32 bitfields — a draft tree can never
#: exceed this many nodes (root included)
MAX_TREE_NODES = 32


# ------------------------------------------------------------ draft tree

@dataclass
class DraftTree:
    """A verified-together draft tree. Node 0 is the ROOT — the last
    committed token, whose verify logits re-derive the first draft
    prediction. Nodes are packed topologically: ``parents[i] < i`` for
    every i > 0, so the verify kernel's ragged page walk stays exact
    and acceptance can be resolved in one forward sweep.

    ``masks[i]`` packs node i's ancestor-or-self set as bits over the
    node index: bit j set iff node j is on the root-to-i path
    (including i itself). ``masks[0] == 1``.
    """

    tokens: list[int]
    parents: list[int]
    depths: list[int]
    masks: list[int]

    @property
    def n_nodes(self) -> int:
        return len(self.tokens)

    @property
    def n_draft(self) -> int:
        """Drafted (non-root) nodes."""
        return len(self.tokens) - 1

    @property
    def max_depth(self) -> int:
        return max(self.depths)

    @classmethod
    def root(cls, root_token: int) -> "DraftTree":
        return cls([int(root_token)], [0], [0], [1])

    @classmethod
    def from_chain(cls, root_token: int, proposals) -> "DraftTree":
        """A single linear continuation — the historical draft shape
        (``spec_branches=1``), and the normalization target for
        monkeypatched ``_draft_proposals`` hooks that return a plain
        token list."""
        tree = cls.root(root_token)
        cur = 0
        for tok in proposals:
            cur = tree.add(cur, int(tok))
        return tree

    def add(self, parent: int, token: int) -> int:
        """Append a child of ``parent``; returns the new node index.
        Raises if the tree is at the bitmask capacity."""
        i = len(self.tokens)
        if i >= MAX_TREE_NODES:
            raise ValueError(f"draft tree exceeds {MAX_TREE_NODES} nodes")
        if not 0 <= parent < i:
            raise ValueError(f"parent {parent} out of range for node {i}")
        self.tokens.append(int(token))
        self.parents.append(parent)
        self.depths.append(self.depths[parent] + 1)
        self.masks.append(self.masks[parent] | (1 << i))
        return i

    def path_to(self, node: int) -> list[int]:
        """Node indices on the root-to-``node`` path, root first."""
        path = []
        cur = node
        while True:
            path.append(cur)
            if cur == 0:
                break
            cur = self.parents[cur]
        path.reverse()
        return path


def build_draft_tree(root_token: int, chains,
                     max_nodes: int = MAX_TREE_NODES) -> DraftTree:
    """Trie-merge candidate continuation chains into one DraftTree.
    Chains sharing a prefix share nodes (the whole point of tree
    verify: k continuations of a hot n-gram usually agree for a few
    tokens before they fork). Chains are consumed in order; growth
    stops silently at ``max_nodes``."""
    tree = DraftTree.root(root_token)
    children: dict[int, dict[int, int]] = {}
    for chain in chains:
        cur = 0
        for tok in chain:
            tok = int(tok)
            kids = children.setdefault(cur, {})
            nxt = kids.get(tok)
            if nxt is None:
                if tree.n_nodes >= max_nodes:
                    break
                nxt = tree.add(cur, tok)
                kids[tok] = nxt
            cur = nxt
    return tree


# ----------------------------------------------------------- ngram index

class NgramIndex:
    """Incremental n-gram -> positions index over one request's token
    stream (prompt + generated). ``extend`` is O(1) amortized per new
    token; ``propose`` is O(branches) dictionary probes. The index
    tracks how many tokens it has folded in (``size``) so the engine
    can detect a rewritten stream (preempt/recover fold generated
    tokens back into the prompt) and rebuild instead of extending."""

    __slots__ = ("n", "tokens", "positions", "prompt_len")

    def __init__(self, n: int):
        self.n = n
        self.tokens: list[int] = []
        self.positions: dict[tuple, list[int]] = {}
        #: length of the request's prompt when this index was built —
        #: the engine's O(1) rewrite detector (preemption is the only
        #: thing that grows a prompt mid-flight)
        self.prompt_len = -1

    @property
    def size(self) -> int:
        return len(self.tokens)

    def extend(self, new_tokens) -> None:
        toks = self.tokens
        pos = self.positions
        n = self.n
        for t in new_tokens:
            toks.append(int(t))
            start = len(toks) - n
            if start >= 0:
                key = tuple(toks[start:])
                pos.setdefault(key, []).append(start)

    def propose(self, depth: int, branches: int) -> list[list[int]]:
        """Up to ``branches`` candidate continuations of the stream's
        final n-gram, each up to ``depth`` tokens, newest occurrence
        first, distinct first tokens (two chains opening with the same
        token would collapse to one trie branch anyway — spend the
        budget on genuinely different continuations)."""
        toks = self.tokens
        n = self.n
        if depth <= 0 or branches <= 0 or len(toks) < n:
            return []
        hits = self.positions.get(tuple(toks[-n:]))
        if not hits:
            return []
        chains: list[list[int]] = []
        seen: set[int] = set()
        for start in reversed(hits):
            cont = start + n
            if cont >= len(toks):
                continue  # the suffix's own occurrence
            chain = toks[cont:cont + depth]
            if chain[0] in seen:
                continue
            seen.add(chain[0])
            chains.append(chain)
            if len(chains) >= branches:
                break
        return chains


# ------------------------------------------------------------ controller

class SpecController:
    """Per-slot speculation policy, fitted online.

    Learns three things: a decode **sec/token** EWMA (what an accepted
    draft token is worth), a verify **row cost** EWMA (what a drafted
    node costs), and a per-slot **accept-rate** EWMA (how often this
    request's drafts survive). A pass drafts to depth d only while the
    marginal expected value ``accept^d * sec_per_token`` exceeds the
    marginal cost ``branches * row_cost`` of carrying depth d's nodes
    through the verify matmuls. Slots start optimistic (EWMA 1.0 — the
    first drafts always run) and are DISABLED when the EWMA falls
    under ``accept_floor``; a disabled slot sends a single-node probe
    every ``probe_interval`` passes and re-enables on a surviving
    probe. ``adaptive=False`` reproduces the historical static policy
    (always full depth, single chain honored via branches).
    """

    def __init__(self, max_batch: int, *, draft: int, branches: int,
                 adaptive: bool = True, accept_floor: float = 0.1,
                 probe_interval: int = 32, alpha: float = 0.2):
        self.max_batch = max_batch
        self.draft = draft
        self.branches = branches
        self.adaptive = adaptive
        self.accept_floor = accept_floor
        self.probe_interval = probe_interval
        self.alpha = alpha
        self.sec_per_token: float | None = None
        self.row_cost: float | None = None
        self.drafted_total = 0
        self.accepted_total = 0
        self.accept = [1.0] * max_batch
        self.disabled = [False] * max_batch
        self._idle = [0] * max_batch

    # ---- lifecycle ---------------------------------------------------
    def reset_slot(self, slot: int) -> None:
        """New request admitted to ``slot``: forget the old tenant's
        acceptance history, restart optimistic."""
        self.accept[slot] = 1.0
        self.disabled[slot] = False
        self._idle[slot] = 0

    # ---- measurements ------------------------------------------------
    def _ewma(self, old: float | None, new: float) -> float:
        if old is None:
            return new
        return (1.0 - self.alpha) * old + self.alpha * new

    def note_decode(self, busy_s: float, emitted: int) -> None:
        """A plain decode pass emitted ``emitted`` tokens over
        ``busy_s`` device-seconds — the price an accepted draft token
        undercuts."""
        if emitted > 0 and busy_s > 0:
            self.sec_per_token = self._ewma(self.sec_per_token,
                                            busy_s / emitted)

    def note_verify(self, busy_s: float, rows: int, width: int) -> None:
        """A verify pass carried ``rows`` live slots at ``width`` node
        rows each over ``busy_s`` device-seconds."""
        total = rows * width
        if total > 0 and busy_s > 0:
            self.row_cost = self._ewma(self.row_cost, busy_s / total)

    def note_result(self, slot: int, drafted: int, accepted: int) -> None:
        """One slot's verify outcome: ``accepted`` of ``drafted``
        drafted tokens survived."""
        if drafted <= 0:
            return
        self.drafted_total += drafted
        self.accepted_total += accepted
        rate = accepted / drafted
        if self.disabled[slot]:
            # probe outcome: a surviving probe re-enables the slot at
            # the observed rate; a dead probe leaves it disabled until
            # the next probe window
            if rate >= self.accept_floor:
                self.disabled[slot] = False
                self.accept[slot] = max(rate, self.accept_floor)
            return
        self.accept[slot] = (1.0 - self.alpha) * self.accept[slot] \
            + self.alpha * rate
        if self.accept[slot] < self.accept_floor:
            self.disabled[slot] = True
            self._idle[slot] = 0

    # ---- policy ------------------------------------------------------
    def plan(self, slot: int) -> tuple[int, int]:
        """(depth, branches) to draft for ``slot`` this pass;
        (0, 0) means skip drafting."""
        if not self.adaptive:
            return self.draft, self.branches
        if self.disabled[slot]:
            self._idle[slot] += 1
            if self._idle[slot] >= self.probe_interval:
                self._idle[slot] = 0
                return 1, 1
            return 0, 0
        a = self.accept[slot]
        spt, rc = self.sec_per_token, self.row_cost
        if spt is None or rc is None:
            # not calibrated yet: draft at full config depth — the
            # first verify/decode passes fit the EWMAs
            return self.draft, self.branches
        marginal_cost = rc * self.branches
        depth = 0
        value = spt
        for d in range(1, self.draft + 1):
            value *= a  # a^d * sec_per_token
            if value > marginal_cost:
                depth = d
            else:
                break
        if depth == 0:
            return 0, 0
        return depth, self.branches

    def accept_rate(self) -> float:
        """Lifetime accepted/drafted (1.0 before any drafting — the
        optimistic bootstrap, and keeps the gauge in [0, 1])."""
        if self.drafted_total == 0:
            return 1.0
        return self.accepted_total / self.drafted_total

    def state(self) -> dict:
        """Snapshot for ``/debug/efficiency``."""
        return {
            "adaptive": self.adaptive,
            "draft": self.draft,
            "branches": self.branches,
            "accept_rate": round(self.accept_rate(), 4),
            "drafted": self.drafted_total,
            "accepted": self.accepted_total,
            "sec_per_token": self.sec_per_token,
            "verify_row_cost": self.row_cost,
            "slots": [
                {"accept_ewma": round(self.accept[i], 4),
                 "disabled": self.disabled[i]}
                for i in range(self.max_batch)
            ],
        }
