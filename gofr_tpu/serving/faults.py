"""Deterministic fault injection for chaos testing the serving stack.

A :class:`FaultPlan` names *sites* — fixed points in the engine and
control plane where a failure can be made to happen — and arms each
with a trigger expressed in **invocation counts** (and optionally a
request id), never wall clock and never RNG: the same plan against the
same traffic fires at exactly the same step every run, so a chaos test
that passes once passes always and a failure reproduces bit-identically
under ``git bisect``.

Sites (see docs/operations.md "Surviving a crash" for the operator
view):

========================  =====================================================
``pass_raise``            raise :class:`InjectedFault` at the top of an engine
                          loop iteration (before any dispatch) — exercises the
                          crash-recovery supervisor's requeue-and-replay path
``pass_stall``            ``time.sleep(seconds)`` inside a loop iteration —
                          simulates a wedged device call; drives the stall
                          watchdog → DEGRADED → leader-evict path
``pass_latency``          same sleep, by convention a *small* one — simulates
                          a slow pass without tripping the watchdog
``nan_logits``            raise :class:`InjectedFault` at decode *collect* —
                          the pass already dispatched, tokens are in flight,
                          so recovery must take the mid-stream
                          typed-retryable branch, never the replay branch
``page_exhaustion``       report the KV page pool exhausted at admission —
                          the request is refused with a typed 503, the
                          engine keeps running
``heartbeat_drop``        ``WorkerAgent`` silently skips a heartbeat —
                          simulates a lossy control network
``join_refused``          ``WorkerAgent.join()`` raises — simulates a leader
                          that is down or rejecting, exercising join backoff
``leader_down``           the leader answers every control RPC with a 503 —
                          simulates a dead front door without killing the
                          process; drives the missed-ack failover path
``leader_partition``      the leader refuses control RPCs from one host
                          (``request=host_id``) — an asymmetric network
                          partition: only that host elects
``ack_drop``              ``WorkerAgent`` discards a *successful* heartbeat
                          ack — the leader saw the beat, the worker counts a
                          miss; exercises one-way control-network loss
``stale_epoch_replay``    the leader answers a heartbeat with ``epoch - 1``
                          — a replayed/stale ack; exercises worker-side
                          epoch fencing (the ack must be rejected)
``cost_skew``             report-only: the engine inflates the duration it
                          feeds the pass-cost model by ``seconds`` for the
                          dispatch signature in ``request`` — deterministic
                          drift induction with zero sleep and zero token
                          perturbation (greedy outputs stay bit-identical)
``logit_corrupt``         report-only: the engine perturbs a collected token
                          at the emit boundary — the host-visible consequence
                          of corrupted device logits (the real logits never
                          cross to the host). Nothing crashes, stream lengths
                          are preserved, but output digests diverge — the CI
                          driver for the integrity observatory
                          (serving/integrity.py). Scope to one request class
                          with ``request=<tenant>``; scope to one host by
                          arming only that host's plan
========================  =====================================================

The disabled plan is the module-level :data:`NO_FAULTS` singleton; call
sites guard with ``plan is not NO_FAULTS`` so the default hot path pays
one identity comparison and nothing else.  :meth:`FaultPlan.trip` is a
``@hot_path_boundary`` — when a plan *is* armed, firing a fault is the
whole point, so the purity walk deliberately stops there.

Plan syntax (``EngineConfig.faults`` / ``GOFR_FAULTS``)::

    site[:key=value[,key=value...]][;site...]

    GOFR_FAULTS="pass_raise:at=3"
    GOFR_FAULTS="pass_stall:at=5,seconds=2.5;heartbeat_drop:at=2,times=4"

Keys: ``at`` (1-based invocation index where firing starts, default 1),
``times`` (number of consecutive firings, default 1; ``0`` means every
invocation from ``at`` on), ``seconds`` (sleep payload for the stall /
latency sites), ``request`` (only invocations carrying this request id
are counted or fired).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

from ..analysis.annotations import hot_path_boundary
from .events import NO_EVENTS

SITES = frozenset({
    "pass_raise", "pass_stall", "pass_latency", "page_exhaustion",
    "nan_logits", "heartbeat_drop", "join_refused",
    "leader_down", "leader_partition", "ack_drop", "stale_epoch_replay",
    "cost_skew", "logit_corrupt",
})

# sites whose firing is a raise vs. a sleep; the rest report True and
# let the call site decide what "exhausted"/"dropped" means locally
_RAISE_SITES = frozenset({"pass_raise", "nan_logits"})
_SLEEP_SITES = frozenset({"pass_stall", "pass_latency"})


class InjectedFault(RuntimeError):
    """A deliberately injected failure. Distinguishable from organic
    errors in logs and in ``health_check()['last_crash']``."""


@dataclass
class FaultSpec:
    """One armed site. ``seen`` is the deterministic trigger state: it
    counts matching invocations of :meth:`FaultPlan.trip`, nothing
    else — no clocks, no RNG."""
    site: str
    at: int = 1          # 1-based invocation index where firing starts
    times: int = 1       # consecutive firings; 0 = forever from ``at``
    seconds: float = 0.0  # sleep payload (stall / latency sites)
    request: str = ""    # only count/fire invocations with this request id
    seen: int = field(default=0, repr=False)

    def armed_for(self, count: int) -> bool:
        if count < self.at:
            return False
        return self.times <= 0 or count < self.at + self.times


class FaultPlan:
    """An immutable set of :class:`FaultSpec` with per-spec
    deterministic counters. Build with :meth:`parse` or pass specs
    directly; the empty plan should be :data:`NO_FAULTS`."""

    def __init__(self, specs=()):  # noqa: D401 - simple container
        self.specs = list(specs)
        self._by_site: dict[str, list[FaultSpec]] = {}
        for spec in self.specs:
            if spec.site not in SITES:
                raise ValueError(
                    f"unknown fault site {spec.site!r}; known: "
                    f"{', '.join(sorted(SITES))}")
            self._by_site.setdefault(spec.site, []).append(spec)
        # observability for tests and /debug surfaces
        self.fired: dict[str, int] = {}
        #: EventLedger fault firings are recorded on; the engine wires
        #: its ledger onto armed plans only (NO_FAULTS stays pristine)
        self.events = NO_EVENTS

    # ------------------------------------------------------------ state
    @property
    def enabled(self) -> bool:
        return bool(self.specs)

    def reset(self) -> None:
        """Rewind every trigger counter (reuse one plan across runs)."""
        for spec in self.specs:
            spec.seen = 0
        self.fired.clear()

    def payload(self, site: str) -> float:
        """Largest ``seconds`` payload armed for ``site`` — the side
        channel the report-only sites carry a magnitude through (e.g.
        ``cost_skew``'s synthetic duration inflation). Static per plan,
        so the injected value is as deterministic as the trigger."""
        return max((s.seconds for s in self._by_site.get(site, ())),
                   default=0.0)

    def describe(self) -> list[dict]:
        return [{"site": s.site, "at": s.at, "times": s.times,
                 "seconds": s.seconds, "request": s.request,
                 "seen": s.seen} for s in self.specs]

    # ----------------------------------------------------------- firing
    @hot_path_boundary("fault injection: when a plan is armed, the raise/"
                       "sleep/counter work here IS the injected fault — "
                       "sites guard with 'plan is not NO_FAULTS' so the "
                       "disabled default pays one identity comparison")
    def trip(self, site: str, request_id=None) -> bool:
        """Count one invocation of ``site`` and fire if a spec's window
        covers it. Raises :class:`InjectedFault` for the raise sites,
        sleeps for the stall/latency sites, returns True for the
        report-only sites (page_exhaustion / heartbeat_drop /
        join_refused / cost_skew / logit_corrupt)."""
        specs = self._by_site.get(site)
        if not specs:
            return False
        fired = False
        for spec in specs:
            if spec.request and spec.request != (request_id or ""):
                continue
            spec.seen += 1
            if not spec.armed_for(spec.seen):
                continue
            fired = True
            self.fired[site] = self.fired.get(site, 0) + 1
            if site in _SLEEP_SITES and spec.seconds > 0.0:
                time.sleep(spec.seconds)
        if fired:
            self.events.emit("fault.trip", severity="warn",
                             request_id=request_id, cause=site,
                             fired=self.fired[site])
        if fired and site in _RAISE_SITES:
            raise InjectedFault(f"injected fault: {site}")
        return fired

    # ---------------------------------------------------------- parsing
    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        """Parse ``site[:k=v[,k=v...]][;site...]`` (module docstring).
        An empty/blank string parses to :data:`NO_FAULTS`. Malformed
        plans fail loudly with the offending token in the message —
        a typo'd ``GOFR_FAULTS`` silently arming nothing would make a
        chaos drill vacuously green."""
        text = (text or "").strip()
        if not text:
            return NO_FAULTS
        specs = []
        for clause in text.split(";"):
            clause = clause.strip()
            if not clause:
                raise ValueError(
                    f"empty fault clause (stray ';') in {text!r}")
            site, _, argstr = clause.partition(":")
            site = site.strip()
            if not site:
                raise ValueError(
                    f"bad fault clause {clause!r}: missing site name "
                    f"before ':'; valid sites: {', '.join(sorted(SITES))}")
            if site not in SITES:
                raise ValueError(
                    f"unknown fault site {site!r} in clause {clause!r}; "
                    f"valid sites: {', '.join(sorted(SITES))}")
            kw: dict = {}
            for pair in filter(None, (p.strip() for p in argstr.split(","))):
                key, sep, val = pair.partition("=")
                key = key.strip()
                if not sep or key not in ("at", "times", "seconds", "request"):
                    raise ValueError(
                        f"bad fault clause {clause!r}: {pair!r} is not "
                        "key=value with key in at/times/seconds/request")
                if key == "request":
                    kw[key] = val.strip()
                elif key == "seconds":
                    try:
                        kw[key] = float(val)
                    except ValueError:
                        raise ValueError(
                            f"bad fault clause {clause!r}: seconds "
                            f"expects a number, got {val!r}") from None
                else:
                    try:
                        kw[key] = int(val)
                    except ValueError:
                        raise ValueError(
                            f"bad fault clause {clause!r}: {key} "
                            f"expects an integer, got {val!r}") from None
            if kw.get("at", 1) < 1:
                raise ValueError(f"bad fault clause {clause!r}: at >= 1")
            specs.append(FaultSpec(site=site, **kw))
        return cls(specs) if specs else NO_FAULTS

    def __repr__(self) -> str:
        if not self.specs:
            return "FaultPlan(disabled)"
        return f"FaultPlan({'; '.join(s.site for s in self.specs)})"


#: The disabled plan. Call sites compare identity (``is not NO_FAULTS``)
#: so the default path costs one pointer comparison; never mutate it.
NO_FAULTS = FaultPlan(())


def plan_from_env(env: str = "GOFR_FAULTS") -> FaultPlan:
    return FaultPlan.parse(os.environ.get(env, ""))


def resolve_plan(value) -> FaultPlan:
    """Normalize ``EngineConfig.faults``: None → ``GOFR_FAULTS`` env
    (unset → :data:`NO_FAULTS`), a string → :meth:`FaultPlan.parse`,
    a plan → itself (empty plans collapse to the singleton so identity
    guards stay valid)."""
    if value is None:
        return plan_from_env()
    if isinstance(value, str):
        return FaultPlan.parse(value)
    if isinstance(value, FaultPlan):
        return value if value.specs else NO_FAULTS
    raise TypeError(f"faults must be None, str or FaultPlan, got "
                    f"{type(value).__name__}")


__all__ = ["FaultPlan", "FaultSpec", "InjectedFault", "NO_FAULTS",
           "SITES", "plan_from_env", "resolve_plan"]
