"""Model -> Engine glue: build engines from model families."""

from __future__ import annotations

from typing import Any

from ..models.llama import (
    LlamaConfig,
    llama_decode_step,
    llama_init,
    llama_prefill_last,
    make_empty_cache,
)
from .engine import Engine, EngineConfig


def llama_engine(params: Any, model_config: LlamaConfig,
                 engine_config: EngineConfig | None = None, *,
                 metrics: Any = None, logger: Any = None,
                 implementation: str = "auto") -> Engine:
    engine_config = engine_config or EngineConfig()
    c = model_config

    def prefill_fn(params, tokens, kv_lengths):
        # last-position logits only: a serving prefill never needs the
        # [S, vocab] head matmul (larger than the whole backbone at
        # short S) for positions it won't sample from
        return llama_prefill_last(params, tokens, c, kv_lengths=kv_lengths,
                                  implementation=implementation)

    def decode_fn(params, tokens, k_cache, v_cache, lengths):
        return llama_decode_step(params, tokens, k_cache, v_cache, lengths, c)

    def make_cache(batch, max_seq):
        return make_empty_cache(c, batch, max_seq=max_seq)

    return Engine(params, engine_config, prefill_fn=prefill_fn,
                  decode_fn=decode_fn, make_cache=make_cache,
                  metrics=metrics, logger=logger)


def moe_engine(params: Any, model_config, engine_config: EngineConfig | None = None,
               *, metrics: Any = None, logger: Any = None,
               implementation: str = "auto") -> Engine:
    from ..models.moe import moe_decode_step, moe_prefill_last
    import jax.numpy as jnp
    engine_config = engine_config or EngineConfig()
    c = model_config

    def prefill_fn(params, tokens, kv_lengths):
        logits, caches, _router = moe_prefill_last(
            params, tokens, c, kv_lengths=kv_lengths,
            implementation=implementation)
        return logits, caches

    def decode_fn(params, tokens, k_cache, v_cache, lengths):
        return moe_decode_step(params, tokens, k_cache, v_cache, lengths, c)

    def make_cache(batch, max_seq):
        shape = (c.n_layers, batch, max_seq, c.n_kv_heads, c.head_dim)
        return jnp.zeros(shape, c.dtype), jnp.zeros(shape, c.dtype)

    return Engine(params, engine_config, prefill_fn=prefill_fn,
                  decode_fn=decode_fn, make_cache=make_cache,
                  metrics=metrics, logger=logger)


def demo_llama_engine(engine_config: EngineConfig | None = None,
                      seed: int = 0, **kw) -> Engine:
    """Tiny random-weight engine for tests and examples."""
    import jax
    c = LlamaConfig.tiny()
    params = llama_init(jax.random.key(seed), c)
    return llama_engine(params, c,
                        engine_config or EngineConfig(max_batch=4, max_seq=128),
                        implementation="xla", **kw)
