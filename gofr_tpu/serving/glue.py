"""Model -> Engine glue: build engines from model families.

Single-device and mesh-sharded serving share one engine: pass
``mesh=`` to shard the model Megatron-style (``parallel/sharding.py``
specs) and the KV cache over the mesh's ``tp`` axis on the kv-head
dim. The decode step stays ONE donated jitted call — XLA inserts the
all-gathers/reduce-scatters over ICI; nothing in the engine hot loop
changes. This is the serving analog of the reference's horizontal
scale-out behind its service client (reference
pkg/gofr/service/new.go:68); on TPU the "replicas" are mesh shards in
a single SPMD program, coordinated by the runtime rather than HTTP.

``EngineConfig.kv_dtype="int8"`` needs NO glue here: ``make_cache``
always allocates the model-dtype pool and the engine re-lays it as
the quantized ``{"q", "s"}`` pytree at allocation time
(``engine._alloc_pool``). The paged model fns below take whole pools
and route writes through ``ops.paged_kv.pool_write``, which is
pytree-aware — so native decode, chunked prefill, prefix-cache
reattach and speculative verify all ride the quantized layout
unchanged.
"""

from __future__ import annotations

from typing import Any

from ..models.llama import (
    LlamaConfig,
    llama_decode_step,
    llama_init,
    llama_prefill_chunk,
    llama_prefill_last,
    make_empty_cache,
)
from .engine import Engine, EngineConfig


def _kv_sharding(mesh: Any):
    """NamedSharding for [L, B, S, Hkv, hd] caches / prompt-KV slabs:
    kv heads over ``tp``, everything else replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    tp = "tp" if "tp" in mesh.axis_names else None
    return NamedSharding(mesh, P(None, None, None, tp, None))


def llama_engine(params: Any, model_config: LlamaConfig,
                 engine_config: EngineConfig | None = None, *,
                 mesh: Any = None,
                 metrics: Any = None, logger: Any = None,
                 tracer: Any = None,
                 implementation: str = "auto",
                 quantize: str | None = None) -> Engine:
    engine_config = engine_config or EngineConfig()
    c = model_config
    if quantize is not None:
        if quantize not in ("int8", "int4"):
            raise ValueError(f"quantize must be None, 'int8' or "
                             f"'int4', got {quantize!r}")
        # weight-only quantization: int8 halves / int4 quarters the
        # HBM param stream in the memory-bound decode (ops/quant.py);
        # the model functions detect quantized leaves per-matrix, and
        # the sharding specs descend into the {'q','s'} leaves
        # (parallel/sharding.py _match_specs), so both compose with
        # mesh serving
        from ..ops.quant import quantize_llama_int4, quantize_llama_int8
        params = (quantize_llama_int8(params) if quantize == "int8"
                  else quantize_llama_int4(params))

    constrain_kv = None
    if mesh is not None:
        import jax
        import jax.numpy as jnp
        from ..parallel.sharding import llama_param_specs, shard_params
        params = shard_params(params, mesh, llama_param_specs(mesh))
        kv_sharding = _kv_sharding(mesh)

        def constrain_kv(t):
            # pin cache outputs to the input sharding so the donated
            # buffers round-trip in place across passes
            return jax.lax.with_sharding_constraint(t, kv_sharding)

    def prefill_fn(params, tokens, kv_lengths):
        # last-position logits only: a serving prefill never needs the
        # [S, vocab] head matmul (larger than the whole backbone at
        # short S) for positions it won't sample from
        logits, (k, v) = llama_prefill_last(
            params, tokens, c, kv_lengths=kv_lengths,
            implementation=implementation)
        if constrain_kv is not None:
            k, v = constrain_kv(k), constrain_kv(v)
        return logits, (k, v)

    def decode_fn(params, tokens, k_cache, v_cache, lengths,
                  attn_window=None):
        logits, kc, vc = llama_decode_step(params, tokens, k_cache,
                                           v_cache, lengths, c,
                                           attn_window=attn_window)
        if constrain_kv is not None:
            kc, vc = constrain_kv(kc), constrain_kv(vc)
        return logits, kc, vc

    def prefill_chunk_fn(params, tokens, k_cache, v_cache, offsets,
                         chunk_lengths):
        logits, kc, vc = llama_prefill_chunk(
            params, tokens, k_cache, v_cache, offsets, chunk_lengths, c,
            implementation=implementation)
        if constrain_kv is not None:
            kc, vc = constrain_kv(kc), constrain_kv(vc)
        return logits, kc, vc

    def spec_verify_fn(params, tokens, k_cache, v_cache, offsets,
                       chunk_lengths, tree_depths=None, tree_masks=None):
        logits, kc, vc = llama_prefill_chunk(
            params, tokens, k_cache, v_cache, offsets, chunk_lengths, c,
            implementation=implementation, return_all_logits=True,
            tree_depths=tree_depths, tree_masks=tree_masks)
        if constrain_kv is not None:
            kc, vc = constrain_kv(kc), constrain_kv(vc)
        return logits, kc, vc

    def make_cache(batch, max_seq, head_major=False):
        if head_major:
            # paged pool [L, Hkv, Np, pg, hd] (ops/paged_kv.py),
            # allocated directly — no transient double buffer
            import jax.numpy as jnp
            shape = (c.n_layers, c.n_kv_heads, batch, max_seq,
                     c.head_dim)
            kc = jnp.zeros(shape, c.dtype)
            vc = jnp.zeros(shape, c.dtype)
        else:
            kc, vc = make_empty_cache(c, batch, max_seq=max_seq)
        if mesh is not None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            if head_major:
                tp = "tp" if "tp" in mesh.axis_names else None
                sharding = NamedSharding(mesh, P(None, tp))
            else:
                sharding = _kv_sharding(mesh)
            kc = jax.device_put(kc, sharding)
            vc = jax.device_put(vc, sharding)
        return kc, vc

    paged_decode_fn = None
    paged_chunk_fn = None
    paged_verify_fn = None
    if engine_config.kv_layout == "paged" and mesh is None:
        # native paged serving: rows written through the block table,
        # ragged paged-attention kernels read pages in place — no
        # per-pass view materialisation on decode, chunked prefill,
        # prefix reattachment or speculative verify. (The mesh path
        # keeps the view: the kernels are single-device; tp-sharding
        # them is future work and the view path already shards.)
        from ..models.llama import (llama_decode_step_paged,
                                    llama_prefill_chunk_paged)
        impl = {"kernel": "pallas", "interpret": "interpret",
                "xla": "xla"}.get(engine_config.paged_attention, "auto")

        def paged_decode_fn(params, tokens, k_pool, v_pool, tables,
                            lengths):
            return llama_decode_step_paged(params, tokens, k_pool,
                                           v_pool, tables, lengths, c,
                                           implementation=impl)

        def paged_chunk_fn(params, tokens, k_pool, v_pool, tables,
                           offsets, chunk_lengths):
            return llama_prefill_chunk_paged(
                params, tokens, k_pool, v_pool, tables, offsets,
                chunk_lengths, c, implementation=impl)

        def paged_verify_fn(params, tokens, k_pool, v_pool, tables,
                            offsets, chunk_lengths, tree_depths=None,
                            tree_masks=None):
            return llama_prefill_chunk_paged(
                params, tokens, k_pool, v_pool, tables, offsets,
                chunk_lengths, c, implementation=impl,
                return_all_logits=True, tree_depths=tree_depths,
                tree_masks=tree_masks)

    return Engine(params, engine_config, prefill_fn=prefill_fn,
                  decode_fn=decode_fn, make_cache=make_cache,
                  prefill_chunk_fn=prefill_chunk_fn,
                  spec_verify_fn=spec_verify_fn,
                  paged_decode_fn=paged_decode_fn,
                  paged_chunk_fn=paged_chunk_fn,
                  paged_verify_fn=paged_verify_fn,
                  metrics=metrics, logger=logger, tracer=tracer)


def moe_engine(params: Any, model_config, engine_config: EngineConfig | None = None,
               *, metrics: Any = None, logger: Any = None,
               tracer: Any = None,
               implementation: str = "auto") -> Engine:
    from ..models.moe import moe_decode_step, moe_prefill_last
    import jax.numpy as jnp
    engine_config = engine_config or EngineConfig()
    c = model_config

    def prefill_fn(params, tokens, kv_lengths):
        logits, caches, _router = moe_prefill_last(
            params, tokens, c, kv_lengths=kv_lengths,
            implementation=implementation)
        return logits, caches

    def decode_fn(params, tokens, k_cache, v_cache, lengths,
                  attn_window=None):
        return moe_decode_step(params, tokens, k_cache, v_cache,
                               lengths, c, attn_window=attn_window)

    def make_cache(batch, max_seq, head_major=False):
        shape = ((c.n_layers, c.n_kv_heads, batch, max_seq, c.head_dim)
                 if head_major else
                 (c.n_layers, batch, max_seq, c.n_kv_heads, c.head_dim))
        return jnp.zeros(shape, c.dtype), jnp.zeros(shape, c.dtype)

    return Engine(params, engine_config, prefill_fn=prefill_fn,
                  decode_fn=decode_fn, make_cache=make_cache,
                  metrics=metrics, logger=logger, tracer=tracer)


def demo_llama_engine(engine_config: EngineConfig | None = None,
                      seed: int = 0, **kw) -> Engine:
    """Tiny random-weight engine for tests and examples."""
    import jax
    c = LlamaConfig.tiny()
    params = llama_init(jax.random.key(seed), c)
    return llama_engine(params, c,
                        engine_config or EngineConfig(max_batch=4, max_seq=128),
                        implementation="xla", **kw)
