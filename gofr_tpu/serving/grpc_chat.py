"""gRPC chat service: streaming token generation over grpc.aio.

BASELINE config 3 serves /chat over gRPC streaming; this is that
surface — the gRPC twin of ``handlers.make_chat_handler`` (SSE), fed
by the same continuous-batching engine. JSON codec by default (any
gRPC client sending JSON bytes interoperates; grpcurl works with
``-d '{"prompt": ...}'`` against the reflection listing).

RPCs (service ``gofr.serving.Chat``):
- ``Stream`` (server-streaming): one message per token
  ``{"token": int, "text": str}`` then a terminal ``{"done": true,
  "usage": {...}}``.
- ``Complete`` (unary): the full completion in one message, same shape
  as the HTTP handler's response.
"""

from __future__ import annotations

import time
from typing import Any, AsyncIterator

import grpc

from ..grpc.service import GRPCService, rpc, server_stream_rpc
from .engine import Engine, SamplingParams


def _params_from(req: dict) -> tuple[str, SamplingParams]:
    prompt = req.get("prompt")
    if not prompt and isinstance(req.get("messages"), list):
        prompt = "\n".join(str(m.get("content", ""))
                           for m in req["messages"])
    if not prompt or not isinstance(prompt, str):
        raise ValueError("prompt required")
    max_new = int(req.get("max_tokens", req.get("max_new_tokens", 128)))
    if not 1 <= max_new <= 4096:
        raise ValueError("max_tokens out of range")
    return prompt, SamplingParams(
        temperature=float(req.get("temperature", 0.7)),
        top_p=float(req.get("top_p", 1.0)),
        top_k=int(req.get("top_k", 0)),
        max_new_tokens=max_new)


def _tenant_of(ctx: Any) -> str | None:
    """Resolve the accounting label for a gRPC chat call — the same
    TenantResolver the HTTP path uses, against whatever auth info the
    context carries (an unauthenticated RPC lands on ``anonymous``)."""
    resolver = getattr(getattr(ctx, "container", None),
                       "tenant_resolver", None)
    if resolver is None:
        return None
    return resolver.resolve(getattr(ctx, "auth_info", None))


def make_chat_service(engine: Engine, tokenizer: Any) -> GRPCService:
    """Build the registered service instance for ``app.register_grpc``."""

    class ChatService(GRPCService):
        name = "gofr.serving.Chat"

        @server_stream_rpc
        async def Stream(self, ctx, request) -> AsyncIterator[dict]:
            prompt, params = _params_from(request or {})
            prompt_tokens = tokenizer.encode(prompt)
            start = time.perf_counter()
            tenant = _tenant_of(ctx)
            # the gRPC server's per-RPC span is active on this task;
            # invocation metadata carries the raw header as fallback
            req = engine.submit(prompt_tokens, params,
                                traceparent=ctx.header("traceparent")
                                or None, tenant=tenant)
            if req.error:
                # admission refused: distinct status, not INTERNAL
                exc = RuntimeError(req.error)
                exc.grpc_status = grpc.StatusCode.RESOURCE_EXHAUSTED
                raise exc
            n = 0
            gen = engine.stream_request(req)
            try:
                async for token in gen:
                    n += 1
                    yield {"token": token,
                           "text": tokenizer.decode([token])}
                if req.error:
                    # mid-generation failure (kv loss, shutdown): the
                    # client must not mistake truncation for completion
                    raise RuntimeError(f"generation failed: {req.error}")
                tpot_ms = None
                if (req.first_token_at is not None
                        and req.finished_at is not None and n > 1):
                    tpot_ms = round((req.finished_at - req.first_token_at)
                                    * 1000.0 / (n - 1), 3)
                yield {"done": True,
                       "usage": {"prompt_tokens": len(prompt_tokens),
                                 "completion_tokens": n,
                                 "ttft_ms": round(req.ttft_ms, 2)
                                 if req.ttft_ms else None,
                                 "tpot_ms": tpot_ms,
                                 "tenant": tenant,
                                 "duration_ms": round(
                                     (time.perf_counter() - start) * 1e3,
                                     2)}}
            finally:
                # a cancelled gRPC stream (client went away) must close
                # the engine stream NOW so the request stops decoding —
                # same contract as the HTTP SSE path
                await gen.aclose()

        @rpc
        async def Complete(self, ctx, request) -> dict:
            prompt, params = _params_from(request or {})
            prompt_tokens = tokenizer.encode(prompt)
            tenant = _tenant_of(ctx)
            req = engine.submit(prompt_tokens, params,
                                traceparent=ctx.header("traceparent")
                                or None, tenant=tenant)
            if req.error:
                # same overload condition, same status as Stream
                exc = RuntimeError(req.error)
                exc.grpc_status = grpc.StatusCode.RESOURCE_EXHAUSTED
                raise exc
            tokens: list[int] = []
            while True:
                token = await req.out_queue.get()
                if token is None:
                    break
                tokens.append(token)
            if req.error:
                raise RuntimeError(f"generation failed: {req.error}")
            return {"text": tokenizer.decode(tokens), "tokens": tokens,
                    "usage": {"prompt_tokens": len(prompt_tokens),
                              "completion_tokens": len(tokens),
                              "ttft_ms": round(req.ttft_ms, 2)
                              if req.ttft_ms else None,
                              "tenant": tenant}}

    return ChatService()
