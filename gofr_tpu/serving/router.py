"""Fleet front door: prefix-cache-aware data-plane router on the leader.

ROADMAP item 3: the leader stops being a fleet you can only *watch*
and starts serving. ``app.serve_fleet_leader(router=RouterConfig())``
proxies ``POST /chat`` and the OpenAI surface to the member whose
prefix cache already holds the request's longest pinned prefix —
cache-hit TTFT is the single biggest latency lever the engine has
(the ragged paged-attention block tables make prefix reuse cheap), so
the router's job is to stop washing that reuse out across hosts.

How the signal flows (zero new protocol):

- each worker's engine publishes a compact **prefix-cache digest** —
  truncated :func:`prefix_hash` values of its resident pinned prefix
  keys, bounded by ``EngineConfig.prefix_digest_hashes`` — refreshed
  at the throttled gauge boundary and attached to heartbeats through
  ``FlightRecorder.fleet_summary()`` (the same path that already
  carries queue depth, occupancy, tokens/s and the goodput digest);
- the leader's :class:`~.control_plane.ControlPlaneLeader` keeps the
  latest summary per member; :meth:`FleetRouter.plan` scores hosts by
  **longest page-aligned prefix match** against the digest with a
  load-aware tie-break (queue depth x fitted sec/token from the same
  summaries);
- **session affinity** (bounded LRU of session -> host, keyed on the
  body's ``session`` field or ``X-Session-Id``) keeps multi-turn
  chats on the host that holds their KV, and is broken the moment
  the host drains or is evicted (the leader's evict listeners);
- typed retryable rejects — PR 12's ``draining`` / ``engine_restart``
  503s and any 503 carrying ``Retry-After`` — fail over to the
  next-best host with the failed one excluded, **before** any bytes
  were forwarded, so greedy outputs stay bit-identical and no stream
  ever duplicates tokens;
- a host the leader's integrity divergence vote **quarantined**
  (serving/integrity.py) reports QUARANTINED in the routing view, so
  ``_members`` drops it exactly like a DOWN host: its routed share
  goes to zero on the next request, session affinity to it is swept
  (quarantine listener), and requests that would have landed there
  ride the normal typed-retry failover ladder to a healthy sibling;
- responses stream through unbuffered: the proxy forwards upstream
  chunks as they arrive (SSE passthrough rides the server's chunked
  writer), it never accumulates a stream in memory.

On the same heartbeat signals an **autoscale hook**
(:class:`Autoscaler`): sustained queue pressure above the per-host
setpoint (``scripts/capacity.py --json``'s max-sustainable
concurrency) emits scale-up decisions, sustained idle occupancy emits
scale-down decisions routed through the existing elastic join/evict
path (``autoscale_act`` gates whether scale-down actually evicts or
stays advisory).

Everything here is leader-side host work on data the heartbeats
already pay for; the async proxy path holds no locks across awaits
and performs no blocking IO (gofrlint ``blocking-in-async`` — the
fixture pair ``router_bad.py``/``router_good.py`` pins the contract).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Any, Callable

from ..http.responder import ResponseData
from .events import NO_EVENTS

#: response headers mirrored back to the client on proxied replies
_MIRROR_HEADERS = ("retry-after",)
#: request headers forwarded upstream (auth, tracing, content nego)
_FORWARD_HEADERS = ("content-type", "accept", "authorization",
                    "x-api-key", "traceparent", "x-session-id")


def prefix_hash(tokens) -> str:
    """Stable, truncated content hash of a token-id sequence — the
    wire format of one prefix-cache digest entry. Workers hash their
    resident pinned prefix keys; the router hashes the request's
    page-aligned prompt prefixes; equal sequence <=> equal hash
    (16 hex chars of blake2b, collision odds are irrelevant at fleet
    digest sizes)."""
    raw = ",".join(str(int(t)) for t in tokens).encode()
    return hashlib.blake2b(raw, digest_size=8).hexdigest()


def aligned_prefix_hashes(prompt_tokens, page_size: int,
                          max_pages: int) -> list[tuple[int, str]]:
    """``[(covered_rows, hash), ...]`` for every page-aligned prefix
    of ``prompt_tokens`` the engine could have pinned, longest first.
    Mirrors ``Engine._probe_prefix``: at least one suffix token must
    remain, so the longest probed prefix is page-aligned below
    ``len(prompt) - 1``."""
    pg = max(1, int(page_size))
    limit = len(prompt_tokens) - 1
    out: list[tuple[int, str]] = []
    pages = min(limit // pg, max(0, int(max_pages)))
    for k in range(pages, 0, -1):
        covered = k * pg
        out.append((covered, prefix_hash(prompt_tokens[:covered])))
    return out


@dataclass
class RouterConfig:
    """Knobs for the fleet front door (docs/configs.md)."""

    #: "prefix" scores hosts by longest digest match with load
    #: tie-break; "round_robin" rotates (the A/B baseline the router
    #: smoke uses to prove prefix routing actually moves prefix_hits)
    policy: str = "prefix"
    #: bounded session -> host LRU; 0 disables affinity
    affinity_size: int = 1024
    #: failover attempts on ANOTHER host after the first pick refuses
    #: with a typed retryable reject or a connect error
    max_retries: int = 2
    #: ``details.code`` values that mean "this host, right now" — safe
    #: to replay on a sibling because the engine refused before
    #: admitting (no tokens were generated)
    retryable_codes: tuple = ("draining", "engine_restart", "engine_down")
    #: upstream TCP connect budget
    connect_timeout_s: float = 5.0
    #: per-read upstream budget (response head, each body chunk)
    read_timeout_s: float = 120.0
    #: page-aligned prefix lengths probed against each host digest
    digest_max_pages: int = 64
    #: enable the autoscale hook (decisions ride /debug/fleet and the
    #: app_router_scale_decisions counter)
    autoscale: bool = False
    #: per-host concurrency setpoint (active + waiting) above which
    #: sustained pressure is a scale-up signal; 0 = take it from
    #: ``setpoint_file``
    setpoint_concurrency: int = 0
    #: ``scripts/capacity.py --json`` output; read once at install
    #: (never on the async path) for ``max_concurrency``
    setpoint_file: str = ""
    #: fleet mean occupancy below this is an idle (scale-down) signal
    idle_occupancy: float = 0.10
    #: how long a pressure/idle signal must hold before a decision
    sustain_s: float = 30.0
    #: minimum spacing between decisions
    cooldown_s: float = 60.0
    #: scale-down decisions actually evict the idlest member through
    #: the leader (the elastic join/evict path); False = advisory only
    autoscale_act: bool = False
    #: decision ring kept for /debug/fleet
    decisions_kept: int = 32


#: leader-written router series; registered by the container's
#: framework set and (belt-and-braces) on install()
_ROUTER_GAUGES = (
    ("app_router_routed_share",
     "per-host fraction of requests this router forwarded"),
    ("app_router_cache_hit_ratio",
     "fraction of routed requests sent to a host whose prefix digest "
     "covered part of the prompt"),
)
_ROUTER_COUNTERS = (
    ("app_router_routed",
     "requests forwarded to a member (by host label)"),
    ("app_router_retries",
     "typed-reject / connect-error failovers to the next-best host "
     "(by code label)"),
    ("app_router_affinity_hits",
     "requests routed by session affinity"),
    ("app_router_scale_decisions",
     "autoscale decisions emitted (by action label)"),
    ("app_router_client_aborts",
     "proxied streams cancelled because the downstream client "
     "disconnected mid-stream (upstream slot released early)"),
)


class SessionAffinity:
    """Bounded session -> host LRU. Touched from the event loop (route
    time) and from leader threads (evict listeners), so every mutation
    holds the lock — entries for a drained/evicted host drop in one
    sweep."""

    def __init__(self, size: int) -> None:
        self.size = max(0, int(size))
        self._map: OrderedDict[str, str] = OrderedDict()
        self._lock = threading.Lock()

    def get(self, session: str) -> str | None:
        if not self.size or not session:
            return None
        with self._lock:
            host = self._map.get(session)
            if host is not None:
                self._map.move_to_end(session)
            return host

    def put(self, session: str, host: str) -> None:
        if not self.size or not session:
            return
        with self._lock:
            self._map[session] = host
            self._map.move_to_end(session)
            while len(self._map) > self.size:
                self._map.popitem(last=False)

    def drop_host(self, host: str) -> int:
        with self._lock:
            dead = [s for s, h in self._map.items() if h == host]
            for s in dead:
                del self._map[s]
            return len(dead)

    def state(self) -> dict:
        with self._lock:
            return {"size": self.size, "entries": len(self._map)}


class Autoscaler:
    """Sustained-signal scale decisions over the fleet view the router
    already reads. Pure host arithmetic with an injectable clock (the
    tests pin it); decisions land in a ring, a counter, and optionally
    the leader's evict path."""

    def __init__(self, config: RouterConfig, *,
                 clock: Callable[[], float] = time.monotonic,
                 metrics: Any = None, logger: Any = None,
                 on_decision: Callable[[dict], None] | None = None) -> None:
        self.config = config
        self.clock = clock
        self.metrics = metrics
        self.logger = logger
        self.on_decision = on_decision
        self.setpoint = int(config.setpoint_concurrency)
        #: EventLedger scale decisions land on; FleetRouter wires the
        #: leader's ledger here so decisions show up in the fleet
        #: timeline next to the evictions they cause
        self.events = NO_EVENTS
        self.decisions: deque = deque(maxlen=max(1, config.decisions_kept))
        self._pressure_since: float | None = None
        self._idle_since: float | None = None
        self._last_decision = -float("inf")

    def load_setpoint_file(self, path: str) -> None:
        """Read a ``scripts/capacity.py --json`` setpoint file. Called
        at install time only — never from the async proxy path."""
        if not path:
            return
        try:
            with open(path) as f:
                doc = json.load(f)
            self.setpoint = int(doc.get("max_concurrency") or 0)
        except (OSError, ValueError) as exc:
            if self.logger:
                self.logger.warn(
                    f"autoscaler setpoint file unreadable: {exc}")

    def observe(self, hosts: list[dict]) -> dict | None:
        """One tick over the member views; returns the decision dict
        when one fires (also recorded), else None."""
        now = self.clock()
        world = len(hosts)
        if not world:
            self._pressure_since = self._idle_since = None
            return None
        loads = []
        occs = []
        for h in hosts:
            s = h.get("summary") or {}
            loads.append(float(s.get("active_slots") or 0)
                         + float(s.get("waiting") or 0))
            if isinstance(s.get("occupancy_mean"), (int, float)):
                occs.append(float(s["occupancy_mean"]))
        mean_load = sum(loads) / world
        mean_occ = (sum(occs) / len(occs)) if occs else None
        pressure = self.setpoint > 0 and mean_load > self.setpoint
        idle = (mean_occ is not None and world > 1
                and mean_occ < self.config.idle_occupancy
                and not pressure)
        self._pressure_since = (self._pressure_since or now) \
            if pressure else None
        self._idle_since = (self._idle_since or now) if idle else None
        if now - self._last_decision < self.config.cooldown_s:
            return None
        sustain = self.config.sustain_s
        if self._pressure_since is not None \
                and now - self._pressure_since >= sustain:
            return self._decide(
                "scale_up", now,
                reason=f"mean in-flight {mean_load:.1f} > setpoint "
                       f"{self.setpoint} for {sustain:.0f}s",
                mean_load=round(mean_load, 2), world=world)
        if self._idle_since is not None \
                and now - self._idle_since >= sustain:
            victim = min(
                hosts, key=lambda h: (
                    float((h.get("summary") or {}).get("active_slots")
                          or 0)
                    + float((h.get("summary") or {}).get("waiting")
                            or 0),
                    h.get("host_id", "")))
            return self._decide(
                "scale_down", now,
                reason=f"mean occupancy {mean_occ:.3f} < "
                       f"{self.config.idle_occupancy} for {sustain:.0f}s",
                victim=victim.get("host_id"), world=world)
        return None

    def _decide(self, action: str, now: float, **extra: Any) -> dict:
        self._last_decision = now
        self._pressure_since = self._idle_since = None
        decision = {"action": action, "at": round(now, 3),
                    "setpoint": self.setpoint, **extra}
        self.decisions.append(decision)
        if self.metrics is not None:
            self.metrics.increment_counter("app_router_scale_decisions",
                                           action=action)
        self.events.emit(
            "router.scale", severity="warn", cause=action,
            **{k: v for k, v in decision.items()
               if k not in ("action", "at")})
        if self.logger:
            self.logger.warn("autoscale decision", **decision)
        if self.on_decision is not None:
            try:
                self.on_decision(decision)
            except Exception:
                pass  # a broken hook must not break routing
        return decision

    def state(self) -> dict:
        return {"setpoint": self.setpoint,
                "decisions": list(self.decisions)}


class FleetRouter:
    """The data-plane half of the leader: plan (score members against
    the request), proxy (stream through, fail over on typed rejects),
    account (``app_router_*``), and optionally autoscale."""

    def __init__(self, leader: Any, config: RouterConfig | None = None,
                 *, tokenizer: Any = None, metrics: Any = None,
                 logger: Any = None, tracer: Any = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if tokenizer is None:
            from .tokenizer import ByteTokenizer
            tokenizer = ByteTokenizer()
        self.leader = leader
        self.config = config if config is not None else RouterConfig()
        self.tokenizer = tokenizer
        self.metrics = metrics
        self.logger = logger
        self.tracer = tracer
        # router events land on the leader's ledger (the router IS the
        # leader's data plane) so they interleave with evict/failover
        # in one timeline; a ledger-less leader (tests) gets NO_EVENTS
        events = getattr(leader, "events", None)
        self.events = events if events is not None else NO_EVENTS
        self.clock = clock
        self.affinity = SessionAffinity(self.config.affinity_size)
        self.autoscaler: Autoscaler | None = None
        if self.config.autoscale:
            self.autoscaler = Autoscaler(
                self.config, clock=clock, metrics=metrics, logger=logger,
                on_decision=self._act_on_decision
                if self.config.autoscale_act else None)
            self.autoscaler.events = self.events
        #: routed accounting, all under _lock: per-host counts feed the
        #: share gauge and /debug/fleet; hits feed the cache-hit ratio
        self._lock = threading.Lock()
        self._routed: dict[str, int] = {}
        self._routed_total = 0
        self._routed_cache_hits = 0
        self._affinity_hits = 0
        self._retries = 0
        self._client_aborts = 0
        self._rr_next = 0
        self._autoscale_tick = -float("inf")
        #: integrity-quarantine transitions observed (debug_state)
        self._quarantines: dict[str, int] = {}
        if hasattr(leader, "add_evict_listener"):
            leader.add_evict_listener(self._on_member_gone)
        if hasattr(leader, "add_quarantine_listener"):
            leader.add_quarantine_listener(self._on_quarantine)

    # ------------------------------------------------------- membership
    def _on_member_gone(self, host_id: str, reason: str) -> None:
        dropped = self.affinity.drop_host(host_id)
        if not dropped:
            return
        self.events.emit("router.affinity_drop", severity="warn",
                         cause=reason, host=host_id, sessions=dropped)
        if self.logger:
            self.logger.info(
                "router dropped session affinity for departed host",
                host=host_id, reason=reason, sessions=dropped)

    def _on_quarantine(self, host_id: str, action: str) -> None:
        """Leader quarantine listener: sweep session affinity off a
        quarantined host immediately — multi-turn chats pinned to it
        must re-plan onto a healthy sibling, not ride the pin back
        into bad output — and count both transitions for
        ``debug_state``. Routing itself needs no action: the
        QUARANTINED status in the routing view already drops the host
        from ``_members`` on the next plan."""
        with self._lock:
            self._quarantines[action] = \
                self._quarantines.get(action, 0) + 1
        if action == "quarantine":
            self._on_member_gone(host_id, "quarantined")

    def _members(self) -> list[dict]:
        view = self.leader.routing_view()
        return [m for m in view if m.get("status", "UP") == "UP"
                and m.get("address")]

    # ---------------------------------------------------------- scoring
    @staticmethod
    def _load(summary: dict) -> float:
        """Queue depth x fitted sec/token: in-flight work scaled by
        how fast this host retires it. ``pass_p50_s`` is the per-token
        decode cadence; its absence falls back to 1/tokens_per_s, then
        to raw depth (cold host, no passes yet)."""
        depth = (float(summary.get("active_slots") or 0)
                 + float(summary.get("waiting") or 0))
        spt = summary.get("pass_p50_s")
        if not isinstance(spt, (int, float)) or spt <= 0:
            tps = summary.get("tokens_per_s")
            spt = 1.0 / float(tps) if isinstance(tps, (int, float)) \
                and tps > 0 else 1.0
        return depth * float(spt)

    def _covered(self, member: dict, prompt_tokens) -> int:
        digest = (member.get("summary") or {}).get("prefix_digest")
        if not isinstance(digest, dict):
            return 0
        hashes = digest.get("hashes")
        if not hashes:
            return 0
        resident = set(hashes)
        for covered, h in aligned_prefix_hashes(
                prompt_tokens, digest.get("page") or 1,
                self.config.digest_max_pages):
            if h in resident:
                return covered
        return 0

    def plan(self, prompt_tokens, session: str | None = None
             ) -> list[dict]:
        """Ordered candidates for one request: each
        ``{host_id, address, covered, load, affinity}``. First entry
        is the route; the rest are the failover ladder."""
        members = self._members()
        self._maybe_autoscale(members)
        if not members:
            return []
        if self.config.policy == "round_robin":
            members.sort(key=lambda m: m["host_id"])
            with self._lock:
                start = self._rr_next % len(members)
                self._rr_next += 1
            ordered = members[start:] + members[:start]
            return [{"host_id": m["host_id"], "address": m["address"],
                     "covered": 0, "load": 0.0, "affinity": False}
                    for m in ordered]
        scored = []
        for m in members:
            summary = m.get("summary") or {}
            scored.append({
                "host_id": m["host_id"], "address": m["address"],
                "covered": self._covered(m, prompt_tokens),
                "load": round(self._load(summary), 6),
                "affinity": False,
            })
        scored.sort(key=lambda c: (-c["covered"], c["load"],
                                   c["host_id"]))
        pinned = self.affinity.get(session) if session else None
        if pinned is not None:
            for i, c in enumerate(scored):
                if c["host_id"] == pinned:
                    c["affinity"] = True
                    scored.insert(0, scored.pop(i))
                    break
        return scored

    def _maybe_autoscale(self, members: list[dict]) -> None:
        if self.autoscaler is None:
            return
        now = self.clock()
        with self._lock:
            if now - self._autoscale_tick < 1.0:
                return
            self._autoscale_tick = now
        self.autoscaler.observe(members)

    def _act_on_decision(self, decision: dict) -> None:
        """``autoscale_act``: scale-down rides the existing elastic
        evict path — the evicted worker's agent backs off and can
        rejoin when the fleet scales back up. Scale-up stays advisory
        (the leader cannot conjure hosts; operators or an external
        provisioner watch the decision ring)."""
        if decision.get("action") != "scale_down":
            return
        victim = decision.get("victim")
        if victim and hasattr(self.leader, "evict"):
            self.leader.evict(victim, reason="scale_down")

    # ------------------------------------------------------- accounting
    def _note_routed(self, cand: dict, session: str | None,
                     retried: int) -> None:
        with self._lock:
            host = cand["host_id"]
            self._routed[host] = self._routed.get(host, 0) + 1
            self._routed_total += 1
            if cand["covered"] > 0:
                self._routed_cache_hits += 1
            if cand["affinity"]:
                self._affinity_hits += 1
            self._retries += retried
            total = self._routed_total
            shares = {h: n / total for h, n in self._routed.items()}
            ratio = self._routed_cache_hits / total
        if session:
            self.affinity.put(session, host)
        m = self.metrics
        if m is None:
            return
        m.increment_counter("app_router_routed", host=host)
        if cand["affinity"]:
            m.increment_counter("app_router_affinity_hits")
        for h, share in shares.items():
            m.set_gauge("app_router_routed_share", round(share, 4),
                        host=h)
        m.set_gauge("app_router_cache_hit_ratio", round(ratio, 4))

    def _note_retry(self, code: str) -> None:
        if self.metrics is not None:
            self.metrics.increment_counter("app_router_retries",
                                           code=code)

    # ------------------------------------------------------------ proxy
    @staticmethod
    def routing_text(path: str, body: dict) -> str:
        """The prompt text a worker will tokenize for this request —
        the router must hash the same bytes the engine caches.
        Mirrors make_chat_handler for /chat and the OpenAI chat
        template for /v1/*; best-effort (malformed bodies route by
        load alone and let the worker emit the typed 4xx)."""
        if path.startswith("/v1/chat"):
            messages = body.get("messages")
            if not isinstance(messages, list):
                return ""
            parts = []
            for m in messages:
                if not isinstance(m, dict):
                    return ""
                content = m.get("content")
                if isinstance(content, list):
                    content = "".join(
                        str(p.get("text", "")) for p in content
                        if isinstance(p, dict))
                parts.append(f"{m.get('role', 'user')}: {content}")
            parts.append("assistant:")
            return "\n".join(parts)
        prompt = body.get("prompt")
        if isinstance(prompt, str):
            return prompt
        if isinstance(body.get("messages"), list):
            return "\n".join(str(m.get("content", ""))
                             for m in body["messages"]
                             if isinstance(m, dict))
        return ""

    def make_proxy(self, path: str):
        """A proxy handler bound to one upstream path."""

        async def proxy(ctx):
            return await self.proxy_request(ctx, path)

        proxy.__name__ = f"route_{path.strip('/').replace('/', '_')}"
        return proxy

    def _leadership_gate(self) -> None:
        """HA fence on the data plane: a standby leader must not route
        (clients get a typed ``not_leader`` 503 naming the candidates
        to re-dial — see GET /control/leader), and a fresh takeover
        serves typed retryable ``leader_takeover`` 503s until the
        first heartbeat round rebuilds the routing table — the client
        retry honoring Retry-After is what keeps greedy outputs
        bit-identical through a failover."""
        lead = getattr(self.leader, "leadership", None)
        if lead is None:
            return  # non-HA leader (or test fake): nothing to gate
        state = lead()
        from ..http.errors import ErrorServiceUnavailable
        if not state.get("active", True):
            raise ErrorServiceUnavailable(
                "this leader is a standby; re-resolve the active "
                "leader via GET /control/leader",
                details={"code": "not_leader",
                         "epoch": state.get("epoch", 0),
                         "candidates": state.get("candidates", [])},
                headers={"Retry-After": "1"})
        if state.get("converging"):
            interval = getattr(getattr(self.leader, "fleet", None),
                               "heartbeat_interval_s", 1.0)
            raise ErrorServiceUnavailable(
                "leader takeover in progress; routing state rebuilds "
                "from the next heartbeat round",
                details={"code": "leader_takeover",
                         "epoch": state.get("epoch", 0)},
                headers={"Retry-After":
                         str(max(1, round(float(interval))))})

    async def _abort_watch(self, upstream):
        """Client-abort propagation: when the downstream client
        disconnects mid-stream the HTTP server closes this generator
        (GeneratorExit); close the upstream iterator NOW — its
        ``finally`` tears the worker connection down, releasing the
        decode slot — instead of draining tokens nobody will read."""
        try:
            async for chunk in upstream:
                yield chunk
        except GeneratorExit:
            with self._lock:
                self._client_aborts += 1
            if self.metrics is not None:
                self.metrics.increment_counter("app_router_client_aborts")
            if self.logger:
                self.logger.info(
                    "client disconnected mid-stream; cancelled upstream")
            await upstream.aclose()
            raise

    async def proxy_request(self, ctx, path: str) -> ResponseData:
        request = ctx.request
        # the router's half of the trace graph: a router.route span
        # joins the client's traceparent (or the server middleware's
        # span via the contextvar) and is injected downstream so the
        # worker's engine spans hang off it; retries/failovers become
        # post-hoc child spans, and every router event carries the
        # trace_id so timeline entries resolve back to the trace
        span = None
        if self.tracer is not None:
            span = self.tracer.start_span(
                "router.route",
                traceparent=request.header("traceparent"),
                attributes={"path": path})
        try:
            return await self._proxy_request(ctx, request, path, span)
        except Exception as exc:
            if span is not None:
                span.set_status(f"ERROR: {exc}")
            raise
        finally:
            if span is not None:
                span.end()

    def _failover_span(self, span, name: str, started: float,
                       host: str, code: str) -> None:
        if span is None:
            return
        self.tracer.emit_span(
            name, trace_id=span.trace_id, parent_id=span.span_id,
            start_time=started, end_time=time.time(),
            attributes={"host": host, "code": code},
            status=f"ERROR: {code}")

    async def _proxy_request(self, ctx, request, path: str,
                             span) -> ResponseData:
        self._leadership_gate()
        trace_id = span.trace_id if span is not None else None
        raw_body = getattr(request, "body", b"") or b""
        try:
            body = json.loads(raw_body) if raw_body else {}
        except ValueError:
            body = {}
        if not isinstance(body, dict):
            body = {}
        session = body.get("session") \
            or request.header("x-session-id") or None
        if session is not None:
            session = str(session)
        prompt_tokens = self.tokenizer.encode(
            self.routing_text(path, body))
        plan = self.plan(prompt_tokens, session)
        if not plan:
            from ..http.errors import ErrorServiceUnavailable
            raise ErrorServiceUnavailable(
                "no fleet members available to route to",
                details={"code": "no_members"},
                headers={"Retry-After": "1"})
        headers = {k: request.header(k) for k in _FORWARD_HEADERS
                   if request.header(k)}
        if self.tracer is not None:
            # replace the client's traceparent with the router span so
            # the worker's server span is a child of router.route
            self.tracer.inject_headers(headers)
        attempts = min(len(plan), self.config.max_retries + 1)
        last: ResponseData | None = None
        retry_code = ""
        for attempt in range(attempts):
            cand = plan[attempt]
            started = time.time()
            if attempt:
                self._note_retry(retry_code)
            try:
                status, uhdrs, reader, writer = await _open_upstream(
                    "POST", cand["address"], path, headers, raw_body,
                    connect_timeout=self.config.connect_timeout_s,
                    read_timeout=self.config.read_timeout_s)
            except (OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError) as exc:
                retry_code = "connect_error"
                # transport-level failover: the host is gone, not busy
                self.events.emit(
                    "router.failover", severity="warn",
                    cause=retry_code, trace_id=trace_id,
                    host=cand["host_id"], attempt=attempt)
                self._failover_span(span, "router.failover", started,
                                    cand["host_id"], retry_code)
                last = _error_response(
                    502, f"upstream {cand['host_id']} unreachable: "
                         f"{exc!r}")
                continue
            if status in (429, 503):
                # typed admission rejects are small JSON bodies; read
                # them fully to see details.code, then either fail
                # over (zero bytes were forwarded) or mirror verbatim
                payload = await _read_all(
                    reader, writer, uhdrs, self.config.read_timeout_s)
                code = _reject_code(payload)
                last = _mirror(status, uhdrs, payload)
                if status == 503 and attempt < attempts - 1 and (
                        code in self.config.retryable_codes
                        or "retry-after" in uhdrs):
                    retry_code = code or "503"
                    # typed retry: the host said "not right now"
                    self.events.emit(
                        "router.retry", severity="warn",
                        cause=retry_code, trace_id=trace_id,
                        host=cand["host_id"], attempt=attempt)
                    self._failover_span(span, "router.retry", started,
                                        cand["host_id"], retry_code)
                    continue
                return last
            self._note_routed(cand, session, retried=attempt)
            if span is not None:
                span.attributes["host"] = cand["host_id"]
                span.attributes["attempts"] = attempt + 1
            ctype = uhdrs.get("content-type",
                              "application/octet-stream")
            if uhdrs.get("transfer-encoding", "").lower() == "chunked" \
                    or "text/event-stream" in ctype:
                return ResponseData(
                    status=status, content_type=ctype,
                    headers=_mirror_headers(uhdrs),
                    stream=self._abort_watch(
                        _iter_body(reader, writer, uhdrs,
                                   self.config.read_timeout_s)))
            payload = await _read_all(reader, writer, uhdrs,
                                      self.config.read_timeout_s)
            return _mirror(status, uhdrs, payload)
        assert last is not None
        return last

    # ------------------------------------------------------------ misc
    async def models_proxy(self, ctx) -> ResponseData:
        """GET /v1/models passthrough to the first healthy member (the
        model list is identical fleet-wide)."""
        self._leadership_gate()
        for m in self._members():
            try:
                status, uhdrs, reader, writer = await _open_upstream(
                    "GET", m["address"], "/v1/models", {}, b"",
                    connect_timeout=self.config.connect_timeout_s,
                    read_timeout=self.config.read_timeout_s)
            except (OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError):
                continue
            payload = await _read_all(reader, writer, uhdrs,
                                      self.config.read_timeout_s)
            return _mirror(status, uhdrs, payload)
        from ..http.errors import ErrorServiceUnavailable
        raise ErrorServiceUnavailable(
            "no fleet members available",
            details={"code": "no_members"},
            headers={"Retry-After": "1"})

    def debug_state(self) -> dict:
        """The ``router`` block of ``/debug/fleet``."""
        with self._lock:
            routed = dict(self._routed)
            total = self._routed_total
            hits = self._routed_cache_hits
            affinity_hits = self._affinity_hits
            retries = self._retries
            aborts = self._client_aborts
            quarantines = dict(self._quarantines)
        out = {
            "policy": self.config.policy,
            "routed": routed,
            "routed_total": total,
            "cache_hit_ratio": round(hits / total, 4) if total else 0.0,
            "affinity": {**self.affinity.state(),
                         "hits": affinity_hits},
            "retries": retries,
            "client_aborts": aborts,
            "quarantines": quarantines,
        }
        if self.autoscaler is not None:
            out["autoscale"] = self.autoscaler.state()
        return out

    def install(self, app: Any,
                paths: tuple = ("/chat", "/v1/chat/completions",
                                "/v1/completions")) -> None:
        """Register the proxy routes on the leader app and adopt its
        metrics manager."""
        if self.metrics is None:
            self.metrics = app.container.metrics
            if self.autoscaler is not None:
                self.autoscaler.metrics = self.metrics
        if self.tracer is None:
            self.tracer = getattr(app.container, "tracer", None)
        for name, desc in _ROUTER_GAUGES:
            if self.metrics.get(name) is None:
                self.metrics.new_gauge(name, desc)
        for name, desc in _ROUTER_COUNTERS:
            if self.metrics.get(name) is None:
                self.metrics.new_counter(name, desc)
        if self.autoscaler is not None and self.config.setpoint_file:
            self.autoscaler.load_setpoint_file(self.config.setpoint_file)
        for path in paths:
            app.post(path, self.make_proxy(path))
        if any(p.startswith("/v1/") for p in paths):
            app.get("/v1/models", self.models_proxy)
        if hasattr(self.leader, "status_sources"):
            self.leader.status_sources["router"] = self.debug_state


# --------------------------------------------------- upstream transport
#
# The service client's _raw_request buffers the whole response — fine
# for control RPCs, useless for SSE passthrough. This half-duplex
# reader hands the body back incrementally so the proxy forwards
# chunks the moment they arrive.

def _base_parts(address: str) -> tuple[str, int]:
    """``host:port`` or ``http://host:port`` -> (host, port)."""
    addr = address
    if "://" in addr:
        addr = addr.split("://", 1)[1]
    addr = addr.split("/", 1)[0]
    host, _, port = addr.rpartition(":")
    if not host:
        return addr, 80
    return host, int(port)


async def _open_upstream(method: str, address: str, path: str,
                         headers: dict, body: bytes, *,
                         connect_timeout: float, read_timeout: float):
    """Dial a member, send the request, parse the response head.
    Returns ``(status, lowercase-headers, reader, writer)`` with the
    body left on the wire for :func:`_iter_body` / :func:`_read_all`."""
    from ..http.server import MAX_HEADER_BYTES
    host, port = _base_parts(address)
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port, limit=MAX_HEADER_BYTES),
        connect_timeout)
    try:
        head = [f"{method} {path} HTTP/1.1",
                f"Host: {host}:{port}",
                "Connection: close",
                f"Content-Length: {len(body)}"]
        head.extend(f"{k}: {v}" for k, v in headers.items())
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode("latin-1")
                     + body)
        await writer.drain()
        raw = await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"),
                                     read_timeout)
    except BaseException:
        writer.close()
        raise
    lines = raw.decode("latin-1").split("\r\n")
    status = int(lines[0].split(" ", 2)[1])
    uhdrs: dict[str, str] = {}
    for line in lines[1:]:
        if ":" in line:
            k, _, v = line.partition(":")
            uhdrs[k.strip().lower()] = v.strip()
    return status, uhdrs, reader, writer


async def _iter_body(reader, writer, uhdrs: dict, timeout: float):
    """Incremental body iterator: yields chunks as the upstream sends
    them. Closing this generator (client disconnect) closes the
    upstream socket, which cancels the worker's stream producer."""
    try:
        if uhdrs.get("transfer-encoding", "").lower() == "chunked":
            while True:
                size_line = await asyncio.wait_for(reader.readline(),
                                                   timeout)
                size = int(size_line.strip().split(b";")[0] or b"0", 16)
                if size == 0:
                    break
                yield await asyncio.wait_for(reader.readexactly(size),
                                             timeout)
                await reader.readexactly(2)
        elif "content-length" in uhdrs:
            remaining = int(uhdrs["content-length"])
            while remaining > 0:
                chunk = await asyncio.wait_for(
                    reader.read(min(65536, remaining)), timeout)
                if not chunk:
                    break
                remaining -= len(chunk)
                yield chunk
        else:
            while True:
                chunk = await asyncio.wait_for(reader.read(65536),
                                               timeout)
                if not chunk:
                    break
                yield chunk
    finally:
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionResetError, BrokenPipeError):
            pass


async def _read_all(reader, writer, uhdrs: dict,
                    timeout: float) -> bytes:
    chunks = []
    async for chunk in _iter_body(reader, writer, uhdrs, timeout):
        chunks.append(chunk)
    return b"".join(chunks)


def _mirror_headers(uhdrs: dict) -> dict:
    return {k.title(): v for k, v in uhdrs.items()
            if k in _MIRROR_HEADERS}


def _mirror(status: int, uhdrs: dict, payload: bytes) -> ResponseData:
    return ResponseData(
        status=status, body=payload, headers=_mirror_headers(uhdrs),
        content_type=uhdrs.get("content-type", "application/json"))


def _reject_code(payload: bytes) -> str:
    """``details.code`` out of a worker's typed error envelope."""
    try:
        doc = json.loads(payload)
        return str(((doc.get("error") or {}).get("details") or {})
                   .get("code") or "")
    except (ValueError, AttributeError):
        return ""


def _error_response(status: int, message: str) -> ResponseData:
    return ResponseData(
        status=status,
        body=json.dumps({"error": {"message": message}}).encode())


__all__ = ["FleetRouter", "RouterConfig", "Autoscaler",
           "SessionAffinity", "prefix_hash", "aligned_prefix_hashes"]
