"""ASR serving: batched Whisper transcription behind HTTP and Pub/Sub
(baseline config 4: "Whisper-large ASR via Pub/Sub batch").

The transcriber jits ``transcribe_audio`` per (batch, samples) bucket —
audio lengths are padded up to a bucket so XLA compiles a handful of
graphs, not one per request — and exposes:

- :func:`make_asr_handler` — HTTP handler (``POST /transcribe`` with
  base64 PCM or a float array) for interactive use;
- :class:`ASRWorker` — the pub/sub batch consumer: drains up to
  ``max_batch`` audio messages per device execution, publishes
  transcripts to a results topic, commits each message only after its
  transcript is published (at-least-once end to end, reference
  subscriber.go:75-78 semantics).
"""

from __future__ import annotations

import asyncio
import base64
import time
from dataclasses import dataclass
from typing import Any

import numpy as np


def _bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


@dataclass
class ASRConfig:
    max_batch: int = 8
    max_tokens: int = 64
    #: audio-length buckets in samples (16 kHz): 1 s, 5 s, 10 s, 30 s
    sample_buckets: tuple[int, ...] = (16000, 80000, 160000, 480000)


class Transcriber:
    """Bucketed, jitted batch transcription over a Whisper param tree."""

    def __init__(self, params: Any, model_config: Any,
                 asr_config: ASRConfig | None = None,
                 tokenizer: Any = None) -> None:
        import jax
        from ..models.whisper import transcribe_audio
        self.params = params
        self.config = model_config
        self.asr = asr_config if asr_config is not None else ASRConfig()
        self.tokenizer = tokenizer
        self._jitted = jax.jit(
            lambda p, a: transcribe_audio(p, a, model_config,
                                          max_tokens=self.asr.max_tokens))
        self.executions = 0

    def transcribe_batch(self, audios: list[np.ndarray]) -> list[dict]:
        """Pad a list of PCM arrays into one bucketed device batch."""
        import jax.numpy as jnp
        if not audios:
            return []
        longest = max(len(a) for a in audios)
        samples = _bucket(longest, self.asr.sample_buckets)
        batch = _bucket(len(audios), tuple(
            b for b in (1, 2, 4, self.asr.max_batch) if b <= self.asr.max_batch)
            or (self.asr.max_batch,))
        padded = np.zeros((batch, samples), np.float32)
        for i, a in enumerate(audios):
            padded[i, :min(len(a), samples)] = a[:samples]
        start = time.perf_counter()
        tokens, lengths = self._jitted(self.params, jnp.asarray(padded))
        tokens = np.asarray(tokens)
        lengths = np.asarray(lengths)
        elapsed = time.perf_counter() - start
        self.executions += 1
        out = []
        for i in range(len(audios)):
            toks = tokens[i, :lengths[i]].tolist()
            entry = {"tokens": toks, "n_tokens": int(lengths[i]),
                     "batch": batch, "samples": samples,
                     "execute_ms": round(elapsed * 1000, 2)}
            if self.tokenizer is not None:
                entry["text"] = self.tokenizer.decode(toks)
            out.append(entry)
        return out

    def health_check(self) -> dict:
        return {"status": "UP",
                "details": {"model": "whisper", "executions": self.executions}}


def decode_audio_payload(data: Any) -> np.ndarray:
    """Accept {'audio': [floats]} or {'audio_b64': base64 f32 PCM}."""
    if isinstance(data, dict) and "audio_b64" in data:
        raw = base64.b64decode(data["audio_b64"])
        return np.frombuffer(raw, np.float32).copy()
    if isinstance(data, dict) and "audio" in data:
        return np.asarray(data["audio"], np.float32)
    raise ValueError("payload needs 'audio' (float list) or 'audio_b64'")


def make_asr_handler(transcriber: Transcriber):
    """``POST /transcribe`` handler (single-request path; interactive)."""

    def transcribe_handler(ctx: Any) -> Any:
        audio = decode_audio_payload(ctx.bind())
        result = transcriber.transcribe_batch([audio])[0]
        return result
    return transcribe_handler


class ASRWorker:
    """Pub/sub batch consumer: greedily drains up to ``max_batch``
    pending audio messages, transcribes them in ONE device execution,
    publishes results, then commits (TPU-efficient at-least-once)."""

    def __init__(self, transcriber: Transcriber, pubsub: Any,
                 in_topic: str = "asr.requests",
                 out_topic: str = "asr.results",
                 group: str = "asr-workers",
                 drain_wait_s: float = 0.01) -> None:
        self.transcriber = transcriber
        self.pubsub = pubsub
        self.in_topic = in_topic
        self.out_topic = out_topic
        self.group = group
        self.drain_wait_s = drain_wait_s
        self.processed = 0
        self.batches = 0

    async def _drain(self, max_batch: int) -> list:
        """Block for the first message, then opportunistically grab more
        without waiting (continuous batching for the batch lane)."""
        first = await self.pubsub.subscribe(self.in_topic, self.group)
        messages = [first]
        while len(messages) < max_batch:
            try:
                more = await asyncio.wait_for(
                    self.pubsub.subscribe(self.in_topic, self.group),
                    timeout=self.drain_wait_s)
                messages.append(more)
            except asyncio.TimeoutError:
                break
        return messages

    async def run_once(self) -> int:
        """One drain -> one device batch -> publish+commit. Returns the
        number of messages handled."""
        messages = await self._drain(self.transcriber.asr.max_batch)
        audios, ok_msgs = [], []
        for msg in messages:
            try:
                audios.append(decode_audio_payload(msg.bind()))
                ok_msgs.append(msg)
            except Exception:
                msg.commit()  # poison message: drop, don't redeliver forever
        if not audios:
            return 0
        # the jitted batch is a long synchronous device call; run it in
        # a worker thread so HTTP/health/pub-sub on this event loop
        # stay live for the duration
        results = await asyncio.to_thread(
            self.transcriber.transcribe_batch, audios)
        for msg, result in zip(ok_msgs, results):
            request_id = ""
            payload = msg.bind()
            if isinstance(payload, dict):
                request_id = str(payload.get("request_id", ""))
            await self.pubsub.publish(self.out_topic,
                                      {"request_id": request_id, **result})
            msg.commit()  # only after the transcript is out: at-least-once
        self.processed += len(ok_msgs)
        self.batches += 1
        return len(ok_msgs)

    async def run(self) -> None:
        while True:
            try:
                await self.run_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                await asyncio.sleep(2.0)  # backoff, reference subscriber.go:35-41
