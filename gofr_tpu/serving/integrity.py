"""The output-integrity observatory: fingerprinted outputs, golden
canary probes, and the engine half of fleet divergence voting.

The observability stack answers *how fast* (goodput), *how available*
(SLO burn) and *which kernel* (pass-cost observatory) — this plane
answers **"is this host still producing correct tokens?"**. A silently
corrupting host (bad HBM, a miscompiled kernel after a rollout, a
drifted dequant path on the int8 page pool) serves garbage at full SLO
compliance until a user complains; at fleet scale silent data
corruption is a *when*, not an *if*. The repo already owns the perfect
detector primitive — greedy replay bit-identity — and this module
turns it into a continuously running correctness check, in three
tiers:

- **Output fingerprinting** — :func:`request_digest` folds every
  retired request into a cheap host-side blake2b digest over the
  prompt tokens, a coarsely-quantized sampling-parameter summary and
  the emitted token ids (plus a forward-compatible hook for a
  quantized top-k logprob summary; the decode graph returns only
  sampled token ids today — logits never cross to the host in steady
  state, by the zero-h2d invariant, so the logprob slot stays empty
  until a model surfaces them). The digest is stamped into
  ``GenRequest.digest``, the flight-recorder request log, the workload
  record (so replay can diff fingerprints) and ``obs.integrity``
  events. The fold runs once per request at the retire boundary
  (``Engine._note_integrity``, a declared ``@hot_path_boundary`` —
  the ``_note_pass_cost`` pattern): greedy outputs stay bit-identical
  and the transfer guard stays quiet with the plane ON.
- **Golden canary probes** — :class:`GoldenSet` seals a small set of
  (prompt, expected greedy digest) pairs from the replay corpus into a
  versioned JSONL file (header contract like ``gofr-workload``).
  :class:`IntegrityPlane` replays them through the engine on the
  scheduler's background lane at a **pass-count-driven** cadence
  (never wall clock — deterministic under replay); probe device time
  is re-priced as the ``integrity_probe`` waste cause in the
  conserving goodput ledger, so canaries are never mistaken for
  serving goodput. A digest mismatch opens an episode ONCE (one WARN,
  one ``obs.integrity`` event, one
  ``app_engine_integrity_failures{kind}`` bump, one incident bundle);
  the episode re-arms after ``rearm_probes`` consecutive clean probes
  (hysteresis, mirroring the cost-drift sentinel).
- **Fleet divergence voting** — :meth:`IntegrityPlane.summary` rides
  heartbeat summaries (``FlightRecorder.integrity_source``); with >= 3
  hosts reporting the same golden probe the control-plane leader
  majority-votes per probe, names the outlier host, emits a
  ``fleet.integrity_divergence`` event + incident bundle and
  quarantines the host out of the router's member view until it
  produces N consecutive clean probes (serving/control_plane.py).

Everything here is engine-thread host arithmetic at already-declared
boundaries — no locks on the hot path, no device syncs, zero hot-path
perturbation (gofrlint's hot-path-purity walk and the
``TestIntegrityContract`` tests pin digest folding off the hot
closure).
"""

from __future__ import annotations

import hashlib
import json
import struct
from typing import Any

from ..analysis.annotations import hot_path_boundary

#: digest recipe version — bumped when the fold's byte layout changes,
#: so a fleet mid-rollout never votes v1 digests against v2 digests
DIGEST_VERSION = 1

#: golden-set file header contract, mirroring WORKLOAD_FORMAT/VERSION
GOLDEN_FORMAT = "gofr-golden"
GOLDEN_VERSION = 1

#: quantization step for the (future) top-k logprob summary: logprobs
#: are rounded to this grid before folding so benign ULP-level numeric
#: jitter between identical hosts cannot fragment the vote, while a
#: genuinely drifted dequant path still lands in a different bucket
LOGPROB_QUANT = 0.25


def quantize_logprobs(logprobs) -> tuple:
    """Coarsely quantize a top-k logprob summary for digest folding.
    The forward-compatible hook for models that surface per-token
    logprobs: today's serving graphs return only sampled token ids
    (the zero-h2d invariant keeps full logits on device), so callers
    pass ``()`` and the digest covers token ids alone."""
    return tuple(int(round(float(lp) / LOGPROB_QUANT))
                 for lp in (logprobs or ()))


def _quantized_params(params: Any) -> tuple:
    """The sampling-parameter summary folded into the digest — coarse
    1e-4 grids so a cosmetic float round-trip (JSON replay) maps to
    the same digest while any semantically different temperature/top_p
    does not."""
    return (int(round(float(getattr(params, "temperature", 0.0)) * 1e4)),
            int(round(float(getattr(params, "top_p", 1.0)) * 1e4)),
            int(getattr(params, "top_k", 0) or 0),
            int(getattr(params, "max_new_tokens", 0) or 0))


def request_digest(prompt_tokens, params: Any, token_ids, *,
                   logprobs=()) -> str:
    """The output fingerprint: blake2b-128 over (digest version,
    prompt token ids, quantized sampling params, emitted token ids,
    quantized top-k logprob summary). Pure host byte-packing — cheap
    enough to fold every retired request."""
    h = hashlib.blake2b(digest_size=16)
    h.update(struct.pack("<II", DIGEST_VERSION, len(prompt_tokens)))
    h.update(b"".join(struct.pack("<i", int(t)) for t in prompt_tokens))
    h.update(struct.pack("<iiii", *_quantized_params(params)))
    h.update(struct.pack("<I", len(token_ids)))
    h.update(b"".join(struct.pack("<i", int(t)) for t in token_ids))
    q = quantize_logprobs(logprobs)
    h.update(struct.pack("<I", len(q)))
    h.update(b"".join(struct.pack("<i", v) for v in q))
    return h.hexdigest()


# ------------------------------------------------------- golden corpus
class GoldenEntry:
    """One sealed canary: a greedy prompt, the full sampling params it
    was recorded with (the digest folds them, so the probe must replay
    them verbatim), and the digest its replay must reproduce
    bit-for-bit."""

    __slots__ = ("id", "prompt_tokens", "params", "digest")

    def __init__(self, id: str, prompt_tokens: list[int],
                 params: dict, digest: str) -> None:
        self.id = str(id)
        self.prompt_tokens = [int(t) for t in prompt_tokens]
        self.params = {"temperature": float(params.get("temperature", 0.0)),
                       "top_p": float(params.get("top_p", 1.0)),
                       "top_k": int(params.get("top_k", 0)),
                       "max_new_tokens":
                           max(1, int(params.get("max_new_tokens", 16)))}
        self.digest = str(digest)

    def to_dict(self) -> dict:
        return {"id": self.id, "prompt_tokens": self.prompt_tokens,
                "params": self.params, "digest": self.digest}


class GoldenSet:
    """A versioned golden canary corpus: JSONL with a header line
    (the ``gofr-workload`` compatibility pattern) followed by one
    :class:`GoldenEntry` per line. Sealed from replay-corpus records
    (:meth:`seal`) or loaded from disk (:meth:`load`); an unknown
    format/version fails loudly — probing against the wrong corpus
    would alarm on every probe or, worse, on none."""

    def __init__(self, entries=()) -> None:
        self.entries: list[GoldenEntry] = list(entries)

    def __len__(self) -> int:
        return len(self.entries)

    def __bool__(self) -> bool:
        return bool(self.entries)

    @classmethod
    def seal(cls, records, *, limit: int = 8) -> "GoldenSet":
        """Seal canaries from workload-capture records (the dict shape
        ``WorkloadRecorder.record`` writes): only greedy
        (temperature == 0) records carrying a recorded digest qualify
        — a sampled stream or an unfingerprinted record cannot anchor
        a bit-identity probe. Deterministic: first ``limit`` qualifying
        records in corpus order, ids derived from the digest."""
        entries = []
        for rec in records:
            if len(entries) >= max(1, int(limit)):
                break
            params = rec.get("params") or {}
            if float(params.get("temperature", 0.0)) != 0.0:
                continue
            digest = rec.get("digest")
            prompt = rec.get("prompt_tokens")
            if not digest or not isinstance(prompt, list) or not prompt:
                continue
            entries.append(GoldenEntry(
                id=f"g{len(entries):03d}-{str(digest)[:8]}",
                prompt_tokens=prompt, params=params,
                digest=str(digest)))
        return cls(entries)

    # --------------------------------------------------------- file io
    def header(self) -> dict:
        return {"format": GOLDEN_FORMAT, "version": GOLDEN_VERSION,
                "digest_version": DIGEST_VERSION,
                "count": len(self.entries)}

    def to_jsonl(self) -> str:
        lines = [json.dumps(self.header(), sort_keys=True)]
        lines += [json.dumps(e.to_dict(), sort_keys=True)
                  for e in self.entries]
        return "\n".join(lines) + "\n"

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_jsonl())

    @classmethod
    def load(cls, path: str, *, limit: int | None = None) -> "GoldenSet":
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        if not lines:
            raise ValueError(f"golden set {path!r} is empty")
        header = json.loads(lines[0])
        if header.get("format") != GOLDEN_FORMAT:
            raise ValueError(
                f"golden set {path!r}: format "
                f"{header.get('format')!r} != {GOLDEN_FORMAT!r}")
        if int(header.get("version", -1)) > GOLDEN_VERSION:
            raise ValueError(
                f"golden set {path!r}: version {header.get('version')} "
                f"is newer than supported {GOLDEN_VERSION}")
        if int(header.get("digest_version", DIGEST_VERSION)) \
                != DIGEST_VERSION:
            raise ValueError(
                f"golden set {path!r}: digest_version "
                f"{header.get('digest_version')} != {DIGEST_VERSION} — "
                "reseal the corpus on this build")
        entries = []
        for ln in lines[1:]:
            rec = json.loads(ln)
            entries.append(GoldenEntry(
                id=rec["id"], prompt_tokens=rec["prompt_tokens"],
                params=rec.get("params") or {}, digest=rec["digest"]))
            if limit is not None and len(entries) >= limit:
                break
        return cls(entries)


# ----------------------------------------------------- engine-side plane
class IntegrityPlane:
    """The engine-side correctness plane: digest folding, probe
    cadence, mismatch episodes, and the heartbeat digest block.

    Single-writer discipline (the engine thread feeds every writer at
    collect/retire boundaries; readers copy plain dicts under the
    GIL), mirroring the FlightRecorder and CostModel. Probe cadence is
    invocation-count-driven (:meth:`note_pass` counts collected
    passes), never wall clock, so probe schedules replay
    deterministically."""

    def __init__(self, enabled: bool = True, *,
                 golden: GoldenSet | None = None,
                 probe_passes: int = 0,
                 rearm_probes: int = 2) -> None:
        self.enabled = bool(enabled)
        self.golden = golden if golden else None
        self.probe_passes = max(0, int(probe_passes))
        self.rearm_probes = max(1, int(rearm_probes))
        #: collected passes since the last probe launch
        self._since_probe = 0
        #: round-robin cursor over the golden entries
        self._next_idx = 0
        #: probes currently submitted but not yet retired — cadence
        #: skips while one is in flight so a stalled engine can't
        #: stack canaries into its own backlog
        self.inflight = 0
        #: monotone probe sequence — rides the heartbeat summary so
        #: the leader can tell a NEW probe observation from a repeat
        self.seq = 0
        self.folded = 0
        self.probes = {"run": 0, "ok": 0, "mismatch": 0, "error": 0}
        #: per-golden-id latest local result: {digest, expected, ok}
        self.last: dict[str, dict] = {}
        #: mismatch-episode latch (hysteresis twin of the cost-drift
        #: sentinel): one episode record per trip, re-armed after
        #: ``rearm_probes`` consecutive clean probes
        self.episode = False
        self.episodes = 0
        self._clean_streak = 0
        #: total device seconds re-priced to the integrity_probe cause
        self.probe_device_s = 0.0

    # ------------------------------------------------------------ folds
    @hot_path_boundary(
        "digest fold at the retire boundary: one blake2b over token "
        "ids the collects already emitted plus a handful of host dict "
        "updates for probe results — runs once per request, never per "
        "pass; the purity walk stops here by design")
    def fold(self, req: Any) -> str:
        """Fingerprint one retired request (stamps ``req.digest``) and,
        when the request is a golden probe, compare against the sealed
        expectation. Returns a mismatch record exactly once per
        episode; ``None`` otherwise."""
        digest = request_digest(req.prompt_tokens, req.params,
                                req.generated)
        req.digest = digest
        self.folded += 1
        if not req.probe:
            return None
        self.inflight = max(0, self.inflight - 1)
        self.seq += 1
        if req.error is not None or req.cancelled:
            # a refused/failed probe proves nothing about correctness
            # (drain window, queue_full) — count it, don't judge it
            self.probes["error"] += 1
            return None
        self.probes["run"] += 1
        ok = digest == req.probe_expected
        self.last[req.probe] = {"digest": digest,
                                "expected": req.probe_expected,
                                "ok": ok, "seq": self.seq}
        if ok:
            self.probes["ok"] += 1
            if self.episode:
                self._clean_streak += 1
                if self._clean_streak >= self.rearm_probes:
                    # hysteresis re-arm: enough consecutive clean
                    # probes close the episode; the next mismatch
                    # opens (and alarms) a fresh one
                    self.episode = False
                    self._clean_streak = 0
            return None
        self.probes["mismatch"] += 1
        self._clean_streak = 0
        if self.episode:
            return None  # already alarmed this episode
        self.episode = True
        self.episodes += 1
        return {"golden_id": req.probe, "digest": digest,
                "expected": req.probe_expected,
                "episode": self.episodes}

    def note_pass(self):
        """Pass-count probe cadence, called once per collected pass
        (from ``Engine._note_pass_cost``, already a boundary): returns
        the :class:`GoldenEntry` to probe when the cadence fires and
        no probe is in flight, else ``None``. One int compare when
        probing is off."""
        if not self.probe_passes or self.golden is None:
            return None
        self._since_probe += 1
        if self._since_probe < self.probe_passes or self.inflight:
            return None
        self._since_probe = 0
        entry = self.golden.entries[self._next_idx % len(self.golden)]
        self._next_idx += 1
        self.inflight += 1
        return entry

    def probe_aborted(self) -> None:
        """A probe launch failed before submission reached the queue —
        release the in-flight latch so the cadence keeps breathing."""
        self.inflight = max(0, self.inflight - 1)

    # ----------------------------------------------------------- readers
    def summary(self) -> dict | None:
        """The heartbeat digest block (``FlightRecorder.
        integrity_source``): per-golden-probe digests + the probe
        sequence, the leader's voting input. Compact by construction —
        the golden set is small and bounded."""
        if not self.enabled:
            return None
        out: dict = {"digest_version": DIGEST_VERSION, "seq": self.seq,
                     "folded": self.folded,
                     "probes": dict(self.probes)}
        if self.last:
            out["probe_digests"] = {gid: rec["digest"]
                                    for gid, rec in self.last.items()}
            out["probe_ok"] = all(rec["ok"] for rec in self.last.values())
        return out

    def state(self) -> dict:
        """The full ``GET /debug/integrity`` payload (also an
        incident-bundle source)."""
        return {
            "enabled": self.enabled,
            "digest_version": DIGEST_VERSION,
            "folded": self.folded,
            "golden": ({"count": len(self.golden),
                        "ids": [e.id for e in self.golden.entries]}
                       if self.golden else None),
            "probe_passes": self.probe_passes,
            "rearm_probes": self.rearm_probes,
            "probes": dict(self.probes),
            "inflight": self.inflight,
            "seq": self.seq,
            "last": {gid: dict(rec) for gid, rec in self.last.items()},
            "episode": self.episode,
            "episodes": self.episodes,
            "probe_device_s": round(self.probe_device_s, 6),
        }


__all__ = ["DIGEST_VERSION", "GOLDEN_FORMAT", "GOLDEN_VERSION",
           "GoldenEntry", "GoldenSet", "IntegrityPlane",
           "quantize_logprobs", "request_digest"]
