"""The pass-cost observatory: a per-dispatch-signature online cost
model, its drift sentinel, and the anomaly-triggered profiler
controller.

The observability plane can already say *that* serving got slower (SLO
burn rates, goodput ratio, fleet p95 skew) but not *which compiled
graph* got slower. The :class:`~.observability.RecompileSentinel`
fingerprints every dispatch by shape signature — prefill ``(bucket,
group)``, chunk ``(width, G, window)``, decode ``(window)``, verify
``(width)``, each tagged with a non-default ``kv_dtype`` — and then
throws the timing away. :class:`CostModel` keeps it: for every
signature it maintains an EWMA + variance of pass device time plus
per-row/per-token cost, fed host-side at the engine's existing collect
boundaries (``Engine._note_pass_cost``, a declared
``@hot_path_boundary``) from durations those collects already
measured. Zero hot-path perturbation: greedy outputs stay bit-identical
with the model ON.

Three consumers sit on top:

- **Drift sentinel** — after a signature's first
  ``baseline_passes`` serving observations its baseline (EWMA mean +
  std) seals; a later EWMA that exceeds ``baseline * drift_ratio`` AND
  ``baseline + drift_sigma * std`` opens a drift episode:
  :meth:`CostModel.observe` returns a drift record exactly once per
  episode (the engine turns it into one ``obs.cost_drift`` event, one
  WARN, one ``app_engine_cost_drift{kind}`` bump and one incident
  bundle). Decisions are purely count-driven compares over observed
  durations — no wall clock, no RNG — so fault-injected tests are
  deterministic.
- **Anomaly-triggered profiling** — :class:`AutoProfiler` arms a
  single-flight, bounded :class:`~.observability.ProfilerCapture` on
  drift, SLO fast-burn, or a goodput-ratio floor breach; the capture
  auto-stops after N passes or ``max_capture_s``, arms are debounced,
  and ``GOFR_AUTOPROF=0`` is the kill-switch. The artifact path and the
  cost table ride the incident bundle, so the 3am incident ships with
  the trace already captured.
- **Fleet federation** — :meth:`CostModel.table` is a compact digest
  that rides heartbeat summaries (``FlightRecorder.fleet_summary``) and
  workload headers; the leader uses it for signature-normalized
  straggler comparison (serving/control_plane.py).

Surfaces: ``GET /debug/costs``, a ``costs`` block in
``/debug/efficiency`` and ``/debug/fleet``, and report-only
``cost_<kind>_us_per_token`` bench headline keys (:meth:`by_kind`).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

from ..analysis.annotations import hot_path_boundary

#: bounded per-signature table — the shape space is tiny by design
#: (compiled buckets/windows), so hitting this means a recompile storm
#: the RecompileSentinel is already screaming about; overflow durations
#: still land in ``total_s`` so conservation against the goodput
#: meter's busy seconds holds.
MAX_SIGNATURES = 64


class _SigCost:
    """One signature's running cost state — plain host floats."""

    __slots__ = ("kind", "n", "ewma_s", "var_s2", "sum_s", "synthetic_s",
                 "rows", "tokens", "baseline_s", "baseline_std_s",
                 "drifting", "episodes")

    def __init__(self, kind: str) -> None:
        self.kind = kind
        self.n = 0
        self.ewma_s = 0.0
        self.var_s2 = 0.0
        self.sum_s = 0.0
        self.synthetic_s = 0.0
        self.rows = 0
        self.tokens = 0
        self.baseline_s: float | None = None
        self.baseline_std_s = 0.0
        self.drifting = False
        self.episodes = 0


class CostModel:
    """Online per-dispatch-signature cost model + drift sentinel.

    ``observe`` is fed once per collected pass with the same duration
    the goodput ledger bills, so ``total_s - synthetic_s`` conserves
    against ``GoodputMeter.busy_s - waste_s['bubble']`` (bubbles are
    scheduling gaps the meter bills between passes — no pass, so no
    cost observation; ``synthetic_s`` is the cost_skew fault site's
    injected inflation — observed by the model, never slept, so
    bit-identity holds).
    """

    def __init__(self, enabled: bool = True, *, alpha: float = 0.2,
                 baseline_passes: int = 32, drift_ratio: float = 2.0,
                 drift_sigma: float = 6.0,
                 max_signatures: int = MAX_SIGNATURES) -> None:
        self.enabled = bool(enabled)
        self.alpha = min(1.0, max(0.01, float(alpha)))
        self.baseline_passes = max(1, int(baseline_passes))
        self.drift_ratio = max(1.0, float(drift_ratio))
        self.drift_sigma = max(0.0, float(drift_sigma))
        self.max_signatures = max(1, int(max_signatures))
        self._sigs: dict[str, _SigCost] = {}
        self.total_s = 0.0
        self.synthetic_s = 0.0
        self.drift_episodes = 0
        self.overflow = 0

    # ------------------------------------------------------------ writer
    @hot_path_boundary(
        "cost-model fold at the collect boundary: a handful of host "
        "float updates (EWMA/variance/running totals) over the pass "
        "duration the collect already measured; drift decisions are "
        "pure count-driven compares — no clocks, no RNG, no device "
        "reads")
    def observe(self, kind: str, sig: str, dur_s: float, *,
                rows: int = 0, tokens: int = 0,
                skew_s: float = 0.0) -> dict | None:
        """Fold one collected pass into the signature's cost state.

        Returns a drift record exactly once per episode entry (the
        caller emits the event/metric/WARN and arms the profiler),
        None otherwise. ``skew_s`` is synthetic duration inflation from
        the ``cost_skew`` fault site — tracked separately so the
        busy-seconds conservation check can subtract it.
        """
        if not self.enabled:
            return None
        x = float(dur_s) + float(skew_s)
        self.total_s += x
        self.synthetic_s += float(skew_s)
        rec = self._sigs.get(sig)
        if rec is None:
            if len(self._sigs) >= self.max_signatures:
                self.overflow += 1
                return None
            rec = self._sigs[sig] = _SigCost(kind)
        rec.n += 1
        rec.sum_s += x
        rec.synthetic_s += float(skew_s)
        rec.rows += int(rows)
        rec.tokens += int(tokens)
        if rec.n == 1:
            rec.ewma_s = x
        else:
            diff = x - rec.ewma_s
            incr = self.alpha * diff
            rec.ewma_s += incr
            rec.var_s2 = (1.0 - self.alpha) * (rec.var_s2 + diff * incr)
        if rec.baseline_s is None:
            if rec.n >= self.baseline_passes:
                # seal: serving-path observations only (warmup never
                # feeds the model, its timings are compile-laden)
                rec.baseline_s = rec.ewma_s
                rec.baseline_std_s = rec.var_s2 ** 0.5
            return None
        base, std = rec.baseline_s, rec.baseline_std_s
        if rec.drifting:
            # hysteresis: the episode ends at the midpoint threshold,
            # so a cost hovering at the trip point can't flap episodes
            if rec.ewma_s <= base * (1.0 + (self.drift_ratio - 1.0) / 2.0):
                rec.drifting = False
            return None
        if base > 0 and rec.ewma_s > base * self.drift_ratio \
                and rec.ewma_s > base + self.drift_sigma * std:
            rec.drifting = True
            rec.episodes += 1
            self.drift_episodes += 1
            return {"kind": kind, "signature": sig,
                    "ewma_s": round(rec.ewma_s, 6),
                    "baseline_s": round(base, 6),
                    "baseline_std_s": round(std, 6),
                    "ratio": round(rec.ewma_s / base, 3)}
        return None

    def reset(self) -> None:
        """Forget every signature and total (replay runs start clean)."""
        self._sigs.clear()
        self.total_s = 0.0
        self.synthetic_s = 0.0
        self.drift_episodes = 0
        self.overflow = 0

    # ------------------------------------------------------------ readers
    def state(self) -> dict:
        """The ``GET /debug/costs`` block: full per-signature state."""
        sigs = {}
        for sig, rec in self._sigs.items():
            entry: dict[str, Any] = {
                "kind": rec.kind, "n": rec.n,
                "mean_s": round(rec.sum_s / rec.n, 6) if rec.n else 0.0,
                "ewma_s": round(rec.ewma_s, 6),
                "std_s": round(rec.var_s2 ** 0.5, 6),
                "total_s": round(rec.sum_s, 6),
                "drifting": rec.drifting,
                "drift_episodes": rec.episodes,
            }
            if rec.rows:
                entry["us_per_row"] = round(
                    rec.sum_s / rec.rows * 1e6, 3)
            if rec.tokens:
                entry["us_per_token"] = round(
                    rec.sum_s / rec.tokens * 1e6, 3)
            if rec.baseline_s is not None:
                entry["baseline_s"] = round(rec.baseline_s, 6)
                entry["baseline_std_s"] = round(rec.baseline_std_s, 6)
            if rec.synthetic_s:
                entry["synthetic_s"] = round(rec.synthetic_s, 6)
            sigs[sig] = entry
        return {"enabled": self.enabled, "signatures": sigs,
                "total_s": round(self.total_s, 6),
                "synthetic_s": round(self.synthetic_s, 6),
                "drift_episodes": self.drift_episodes,
                "overflow": self.overflow,
                "baseline_passes": self.baseline_passes,
                "drift_ratio": self.drift_ratio,
                "drift_sigma": self.drift_sigma}

    def table(self) -> dict | None:
        """Compact per-signature digest for heartbeat federation and
        workload headers (additive fields — readers that predate them
        ignore the key). None while empty so sources stay lean."""
        if not self.enabled or not self._sigs:
            return None
        out = {}
        for sig, rec in self._sigs.items():
            entry: dict[str, Any] = {"kind": rec.kind, "n": rec.n,
                                     "mean_s": round(rec.sum_s / rec.n, 6)}
            if rec.tokens:
                entry["us_per_token"] = round(
                    rec.sum_s / rec.tokens * 1e6, 3)
            if rec.drifting:
                entry["drifting"] = True
            out[sig] = entry
        return out

    def by_kind(self) -> dict:
        """``{kind: us_per_token}`` aggregate — the bench headline hook
        (report-only ``cost_<kind>_us_per_token`` keys; the next TPU
        window re-baselines on silicon from these)."""
        busy: dict[str, float] = {}
        toks: dict[str, int] = {}
        for rec in self._sigs.values():
            busy[rec.kind] = busy.get(rec.kind, 0.0) + rec.sum_s
            toks[rec.kind] = toks.get(rec.kind, 0) + rec.tokens
        return {k: round(busy[k] / toks[k] * 1e6, 3)
                for k in busy if toks.get(k)}


# -------------------------------------------------- anomaly profiling
def _autoprof_killed() -> bool:
    """``GOFR_AUTOPROF=0`` kill-switch, read at arm time so an operator
    can flip it on a live process without a restart."""
    return os.environ.get("GOFR_AUTOPROF", "").strip().lower() \
        in ("0", "false", "no", "off")


class AutoProfiler:
    """Single-flight anomaly-triggered profiler controller.

    ``arm(reason, cause)`` starts a bounded
    :class:`~.observability.ProfilerCapture` when an anomaly fires
    (cost drift, SLO fast-burn, goodput-floor breach); the capture
    stops after ``passes`` collected passes (``note_pass``, called at
    the engine's collect boundary) or ``max_capture_s`` (checked at
    collect, with the capture's own watchdog as the idle-engine
    backstop). Arms are debounced (``debounce_s``), refused while a
    capture is in flight, and globally killed by ``GOFR_AUTOPROF=0``.
    The finished artifact (path + trigger) is retained in
    ``last_artifact`` for ``/debug/costs`` and incident bundles.
    """

    def __init__(self, capture: Any = None, *, enabled: bool = True,
                 passes: int = 64, max_capture_s: float = 30.0,
                 debounce_s: float = 300.0, logger: Any = None,
                 clock=time.time) -> None:
        self.capture = capture
        self.enabled = bool(enabled) and capture is not None
        self.passes = max(1, int(passes))
        self.max_capture_s = max(0.1, float(max_capture_s))
        self.debounce_s = max(0.0, float(debounce_s))
        self.logger = logger
        self.clock = clock
        self._lock = threading.Lock()
        self._armed: dict | None = None
        self._last_arm: float | None = None
        self.captures = 0
        self.debounced = 0
        self.suppressed = 0
        self.last_artifact: dict | None = None

    def arm(self, reason: str, cause: str = "") -> dict | None:
        """Start a capture for an anomaly; returns ``{"dir", "reason"}``
        or None when suppressed (disabled, killed, in flight, debounced
        or the underlying start refused)."""
        if not self.enabled:
            return None
        if _autoprof_killed():
            with self._lock:
                self.suppressed += 1
            return None
        now = self.clock()
        with self._lock:
            if self._armed is not None:
                self.suppressed += 1
                return None
            if self._last_arm is not None \
                    and now - self._last_arm < self.debounce_s:
                self.debounced += 1
                return None
            res = self.capture.start(max_capture_s=self.max_capture_s)
            if not res.get("ok"):
                self.suppressed += 1
                return None
            self._armed = {"reason": reason, "cause": cause,
                           "dir": res.get("dir"),
                           "remaining": self.passes, "started": now}
            self._last_arm = now
        if self.logger is not None:
            self.logger.warn(
                "anomaly-triggered profiler capture armed",
                reason=reason, cause=cause, dir=res.get("dir"),
                passes=self.passes)
        return {"dir": res.get("dir"), "reason": reason}

    def note_pass(self) -> None:
        """Collect-boundary tick: one attribute check when idle; an
        armed capture counts down and auto-stops on pass budget or
        ``max_capture_s``."""
        armed = self._armed
        if armed is None:
            return
        armed["remaining"] -= 1
        if armed["remaining"] <= 0 \
                or self.clock() - armed["started"] >= self.max_capture_s:
            self._finish()

    def _finish(self) -> None:
        with self._lock:
            armed, self._armed = self._armed, None
            if armed is None:
                return
            res = self.capture.stop()
            # the capture's own max_capture_s watchdog may have beaten
            # us to the stop — the artifact was still written
            ok = bool(res.get("ok")) \
                or "no capture running" in str(res.get("error", ""))
            self.captures += 1
            self.last_artifact = {
                "dir": armed["dir"], "reason": armed["reason"],
                "cause": armed["cause"],
                "passes": self.passes - max(0, armed["remaining"]),
                "ok": ok,
            }
            if res.get("duration_s") is not None:
                self.last_artifact["duration_s"] = res["duration_s"]
        if self.logger is not None:
            self.logger.info(
                f"anomaly-triggered profiler capture finished: "
                f"{armed['dir']}", reason=armed["reason"], ok=ok)

    def state(self) -> dict:
        armed = self._armed
        return {"enabled": self.enabled,
                "kill_switch": _autoprof_killed(),
                "armed": None if armed is None else {
                    "reason": armed["reason"], "cause": armed["cause"],
                    "dir": armed["dir"],
                    "remaining": armed["remaining"]},
                "captures": self.captures,
                "debounced": self.debounced,
                "suppressed": self.suppressed,
                "last_artifact": self.last_artifact,
                "passes": self.passes,
                "max_capture_s": self.max_capture_s,
                "debounce_s": self.debounce_s}
